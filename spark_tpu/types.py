"""Data types for the TPU-native engine.

Mirrors the surface of the reference's `sql/catalyst/.../types/*` (e.g.
`DataType`, `StructType`) but the *device representation* is designed for
TPU, not for UnsafeRow (`sql/catalyst/src/main/java/.../UnsafeRow.java:62`):

- every column is a flat ``jax.Array`` plus an optional validity mask;
- strings are dictionary-encoded: device data is int32 codes into a
  host-side pyarrow dictionary (SURVEY.md section 2.4 row "Off-heap memory
  + pointer strings");
- DECIMAL(p, s) is a scaled int64 on device: exact integer arithmetic is
  fast on the VPU and gives bit-exact SUM/GROUP BY parity, unlike float
  accumulation. Division and AVG promote to float64.
- DATE is days-since-epoch int32; TIMESTAMP is microseconds int64.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np


class DataType:
    """Base of the type lattice (reference: catalyst types/DataType.scala)."""

    #: numpy dtype of the device representation
    np_dtype: np.dtype = None  # type: ignore

    def simple_string(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self) -> str:
        return self.simple_string()

    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self).__name__)


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    np_dtype = np.dtype(np.bool_)


class ByteType(IntegralType):
    np_dtype = np.dtype(np.int8)


class ShortType(IntegralType):
    np_dtype = np.dtype(np.int16)


class IntegerType(IntegralType):
    np_dtype = np.dtype(np.int32)


class LongType(IntegralType):
    np_dtype = np.dtype(np.int64)


class FloatType(FractionalType):
    np_dtype = np.dtype(np.float32)


class DoubleType(FractionalType):
    np_dtype = np.dtype(np.float64)


class StringType(DataType):
    """Dictionary-encoded: device repr is int32 codes (-1 reserved unused);
    bytes live in a host-side pyarrow dictionary on the column."""

    np_dtype = np.dtype(np.int32)


class DateType(DataType):
    """Days since 1970-01-01, int32 (same physical encoding as Arrow date32)."""

    np_dtype = np.dtype(np.int32)


class TimestampType(DataType):
    """Microseconds since epoch, int64."""

    np_dtype = np.dtype(np.int64)


@dataclass(frozen=True)
class DecimalType(FractionalType):
    """DECIMAL(precision, scale) as scaled int64 on device.

    value = unscaled / 10**scale. Addition/subtraction are exact; a
    multiply of (p1,s1)x(p2,s2) yields scale s1+s2 (rescaled by the
    expression layer); division promotes to float64. Precision is tracked
    for schema fidelity but int64 range (~9.2e18) is the true bound;
    out-of-range arithmetic wraps (no configurable ANSI error mode —
    unlike the reference's `Decimal.scala`).
    """

    precision: int = 38
    scale: int = 18

    np_dtype = np.dtype(np.int64)

    def simple_string(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, DecimalType)
                and other.precision == self.precision
                and other.scale == self.scale)

    def __hash__(self) -> int:
        return hash(("decimal", self.precision, self.scale))


class NullType(DataType):
    np_dtype = np.dtype(np.int8)


@dataclass(frozen=True)
class ArrayType(DataType):
    """ARRAY<element>: offsets-encoded on device — the column's data is
    the FLATTENED element array (element dtype) and an int32 offsets
    array [rows+1] marks each row's slice, the Arrow List layout rather
    than the reference's UnsafeArrayData
    (`sql/catalyst/src/main/java/.../UnsafeArrayData.java:1`)."""

    element: DataType = None  # type: ignore
    contains_null: bool = True

    @property
    def np_dtype(self):  # type: ignore[override]
        return self.element.np_dtype

    def simple_string(self) -> str:
        return f"array<{self.element!r}>"

    def __eq__(self, other) -> bool:
        return isinstance(other, ArrayType) and other.element == self.element

    def __hash__(self) -> int:
        return hash(("array", self.element))


# Singletons, mirroring the reference's `DataTypes` statics.
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()


@dataclass(frozen=True)
class Field:
    """A named, typed, nullable column (reference: StructField)."""

    name: str
    dtype: DataType
    nullable: bool = True

    def __repr__(self) -> str:
        return f"{self.name}:{self.dtype!r}{'' if self.nullable else ' not null'}"


@dataclass(frozen=True)
class Schema:
    """Ordered column list (reference: StructType)."""

    fields: Tuple[Field, ...]

    def __init__(self, fields):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(repr(f) for f in self.fields) + ")"


def is_integer_like(dt: DataType) -> bool:
    return isinstance(dt, IntegralType) or isinstance(dt, (StringType, DateType, BooleanType))


_WIDENING: List[type] = [ByteType, ShortType, IntegerType, LongType,
                         FloatType, DoubleType]


def common_type(a: DataType, b: DataType) -> DataType:
    """Least common numeric type, mirroring the reference's TypeCoercion
    (`sql/catalyst/.../analysis/TypeCoercion.scala`) for the numeric lattice."""
    if a == b:
        return a
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        return DecimalType(min(38, intd + scale), scale)
    if isinstance(a, DecimalType):
        if isinstance(b, IntegralType):
            return a
        if isinstance(b, FractionalType):
            return DOUBLE
    if isinstance(b, DecimalType):
        return common_type(b, a)
    if isinstance(a, NumericType) and isinstance(b, NumericType):
        ia = _WIDENING.index(type(a))
        ib = _WIDENING.index(type(b))
        return _WIDENING[max(ia, ib)]()
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    raise TypeError(f"no common type for {a!r} and {b!r}")

"""Linear regression (reference: ml/regression/LinearRegression.scala).

TPU-first: the training pass is ONE jitted program — the Gram matrix
X^T X and moment vector X^T y are MXU matmuls, the solve is a tiny
[d+1, d+1] linear system — instead of the reference's treeAggregate of
per-partition gradient summaries (WeightedLeastSquares.scala)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import Estimator, Model
from .util import attach_column, collect_xy


@jax.jit
def _gram_moments(X, y, reg):
    """Device side: the O(n d^2) matmuls. The tiny [d+1, d+1] solve
    happens on host — TPU XLA implements LuDecomposition only for f32,
    and the Gram matrix is a few KB anyway."""
    n = X.shape[0]
    Xb = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    gram = Xb.T @ Xb                    # MXU
    gram = gram + reg * jnp.eye(Xb.shape[1], dtype=X.dtype) \
        .at[-1, -1].set(0.0)            # no intercept regularization
    return gram, Xb.T @ y


def _normal_solve(X, y, reg):
    gram, xty = _gram_moments(X, y, reg)
    return np.linalg.solve(np.asarray(gram), np.asarray(xty))


class LinearRegression(Estimator):
    def __init__(self, featuresCol="features", labelCol="label",
                 predictionCol="prediction", regParam=0.0):
        self.featuresCol = featuresCol
        self.labelCol = labelCol
        self.predictionCol = predictionCol
        self.regParam = float(regParam)

    def fit(self, df) -> "LinearRegressionModel":
        _, X, y = collect_xy(df, self.featuresCol, self.labelCol)
        theta = np.asarray(_normal_solve(jnp.asarray(X), jnp.asarray(y),
                                         jnp.float64(self.regParam)))
        return LinearRegressionModel(self.featuresCol,
                                     self.predictionCol,
                                     theta[:-1], float(theta[-1]))


class LinearRegressionModel(Model):
    def __init__(self, featuresCol, predictionCol, coefficients,
                 intercept):
        self.featuresCol = featuresCol
        self.predictionCol = predictionCol
        self.coefficients = np.asarray(coefficients)
        self.intercept = float(intercept)

    def transform(self, df):
        table, X, _ = collect_xy(df, self.featuresCol, None)
        pred = np.asarray(
            jnp.asarray(X) @ jnp.asarray(self.coefficients)
            + self.intercept)
        return attach_column(df, table, self.predictionCol, pred)

    def save(self, path: str) -> None:
        np.savez(path, coefficients=self.coefficients,
                 intercept=self.intercept,
                 featuresCol=self.featuresCol,
                 predictionCol=self.predictionCol)

    @staticmethod
    def load(path: str) -> "LinearRegressionModel":
        z = np.load(path, allow_pickle=True)
        return LinearRegressionModel(str(z["featuresCol"]),
                                     str(z["predictionCol"]),
                                     z["coefficients"],
                                     float(z["intercept"]))

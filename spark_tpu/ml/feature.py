"""Feature transformers (reference: ml/feature — VectorAssembler.scala,
StandardScaler.scala)."""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from .base import Estimator, Model, Transformer
from .util import collect_xy, features_to_matrix


class VectorAssembler(Transformer):
    """Combine numeric columns into one fixed-width array column —
    pure engine expression (F.array), fully lazy/jitted."""

    def __init__(self, inputCols=None, outputCol="features"):
        self.inputCols = list(inputCols or [])
        self.outputCol = outputCol

    def transform(self, df):
        from .. import functions as F
        from ..functions import col
        keep = [col(n) for n in df.plan.schema().names]
        arr = F.array(*[_dbl(c) for c in self.inputCols])
        return df.select(*keep, arr.alias(self.outputCol))


def _dbl(name):
    from ..expr import Cast, ColumnRef
    from .. import types as T
    return Cast(ColumnRef(name), T.DOUBLE)


class StandardScaler(Estimator):
    """fit: per-feature mean/std via one device pass; transform rebuilds
    the vector column with standardized values."""

    def __init__(self, inputCol="features", outputCol="scaled",
                 withMean=True, withStd=True):
        self.inputCol = inputCol
        self.outputCol = outputCol
        self.withMean = withMean
        self.withStd = withStd

    def fit(self, df) -> "StandardScalerModel":
        _, X, _ = collect_xy(df, self.inputCol, None)
        mean = X.mean(axis=0) if len(X) else np.zeros(X.shape[1])
        std = X.std(axis=0) if len(X) else np.ones(X.shape[1])
        std = np.where(std == 0, 1.0, std)
        return StandardScalerModel(self.inputCol, self.outputCol,
                                   mean if self.withMean else
                                   np.zeros_like(mean),
                                   std if self.withStd else
                                   np.ones_like(std))


class StandardScalerModel(Model):
    def __init__(self, inputCol, outputCol, mean, std):
        self.inputCol = inputCol
        self.outputCol = outputCol
        self.mean = np.asarray(mean)
        self.std = np.asarray(std)

    def transform(self, df):
        table = df.collect()
        X = features_to_matrix(table, self.inputCol)
        Z = (X - self.mean) / self.std
        n, d = Z.shape if Z.size else (0, len(self.mean))
        arr = pa.ListArray.from_arrays(
            pa.array(np.arange(n + 1, dtype=np.int32) * d),
            pa.array(Z.reshape(-1)))
        out = table.append_column(self.outputCol, arr)
        return df.session.create_dataframe(out, "__scaled__")

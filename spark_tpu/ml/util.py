"""Shared ML data plumbing: DataFrame <-> dense device matrices."""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa


def features_to_matrix(table: pa.Table, features_col: str) -> np.ndarray:
    """Fixed-width array column -> dense [rows, d] float64 matrix."""
    col = table.column(features_col)
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if not (pa.types.is_list(arr.type) or pa.types.is_large_list(arr.type)):
        raise TypeError(
            f"{features_col!r} must be an array column (use "
            f"VectorAssembler); got {arr.type}")
    offs = arr.offsets.to_numpy(zero_copy_only=False)
    widths = np.diff(offs)
    if len(widths) == 0:
        return np.zeros((0, 0))
    d = int(widths[0])
    if not (widths == d).all():
        raise ValueError(
            f"{features_col!r} is ragged; ML needs fixed-width vectors")
    vals = arr.values.to_numpy(zero_copy_only=False).astype(np.float64)
    return vals.reshape(len(widths), d)


def collect_xy(df, features_col: str, label_col: Optional[str]
               ) -> Tuple[pa.Table, np.ndarray, Optional[np.ndarray]]:
    table = df.collect() if hasattr(df, "collect") else df
    X = features_to_matrix(table, features_col)
    y = None
    if label_col is not None:
        y = np.asarray(table.column(label_col).to_numpy(
            zero_copy_only=False), dtype=np.float64)
    return table, X, y


def attach_column(df, table: pa.Table, name: str,
                  values: np.ndarray):
    """Materialized table + new column -> DataFrame (the transform
    output seat; array columns in `table` round-trip untouched)."""
    out = table.append_column(name, pa.array(np.asarray(values)))
    return df.session.create_dataframe(out, name="__ml__")

"""spark_tpu.ml: the MLlib analog (reference: `mllib/src/main/scala/
org/apache/spark/ml/Pipeline.scala:1` + feature/regression/
classification/clustering packages), re-designed TPU-first:

- feature vectors are fixed-width array columns (the engine's offsets
  layout) that reshape to a dense [rows, n_features] device matrix —
  every algorithm below is then MXU matmuls + jitted optimization
  loops, not per-row iterators;
- estimators `fit` on a DataFrame and return Models (Transformers);
  `Pipeline` chains them exactly like the reference's Estimator/
  Transformer/Params contract;
- training is one `jax.jit` program per estimator (normal equations,
  lax.scan gradient descent, Lloyd iterations) — the data-parallel
  `treeAggregate` loops of the reference collapse into XLA reductions.
"""

from .base import Estimator, Model, Pipeline, PipelineModel, Transformer
from .feature import StandardScaler, StandardScalerModel, VectorAssembler
from .regression import LinearRegression, LinearRegressionModel
from .classification import LogisticRegression, LogisticRegressionModel
from .clustering import KMeans, KMeansModel
from .evaluation import (BinaryClassificationEvaluator,
                         RegressionEvaluator)

__all__ = [
    "Estimator", "Model", "Pipeline", "PipelineModel", "Transformer",
    "VectorAssembler", "StandardScaler", "StandardScalerModel",
    "LinearRegression", "LinearRegressionModel",
    "LogisticRegression", "LogisticRegressionModel",
    "KMeans", "KMeansModel",
    "RegressionEvaluator", "BinaryClassificationEvaluator",
]

"""Evaluators (reference: ml/evaluation/RegressionEvaluator.scala,
BinaryClassificationEvaluator.scala)."""

from __future__ import annotations

import numpy as np

from .base import Params


class RegressionEvaluator(Params):
    def __init__(self, labelCol="label", predictionCol="prediction",
                 metricName="rmse"):
        self.labelCol = labelCol
        self.predictionCol = predictionCol
        self.metricName = metricName

    def evaluate(self, df) -> float:
        t = df.collect()
        y = np.asarray(t.column(self.labelCol).to_numpy(
            zero_copy_only=False), dtype=np.float64)
        p = np.asarray(t.column(self.predictionCol).to_numpy(
            zero_copy_only=False), dtype=np.float64)
        err = y - p
        if self.metricName == "rmse":
            return float(np.sqrt(np.mean(err ** 2)))
        if self.metricName == "mse":
            return float(np.mean(err ** 2))
        if self.metricName == "mae":
            return float(np.mean(np.abs(err)))
        if self.metricName == "r2":
            ss_res = float(np.sum(err ** 2))
            ss_tot = float(np.sum((y - y.mean()) ** 2))
            return 1.0 - ss_res / ss_tot if ss_tot else 0.0
        raise ValueError(f"unknown metric {self.metricName!r}")


class BinaryClassificationEvaluator(Params):
    """areaUnderROC via the rank statistic (exact, ties averaged)."""

    def __init__(self, labelCol="label", rawPredictionCol="probability",
                 metricName="areaUnderROC"):
        self.labelCol = labelCol
        self.rawPredictionCol = rawPredictionCol
        self.metricName = metricName

    def evaluate(self, df) -> float:
        if self.metricName != "areaUnderROC":
            raise ValueError(f"unknown metric {self.metricName!r}")
        t = df.collect()
        y = np.asarray(t.column(self.labelCol).to_numpy(
            zero_copy_only=False), dtype=np.float64)
        s = np.asarray(t.column(self.rawPredictionCol).to_numpy(
            zero_copy_only=False), dtype=np.float64)
        import pandas as pd
        ranks = pd.Series(s).rank(method="average").to_numpy()
        n_pos = int((y == 1).sum())
        n_neg = len(y) - n_pos
        if n_pos == 0 or n_neg == 0:
            return 0.0
        return float((ranks[y == 1].sum()
                      - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))

"""Pipeline abstractions (reference: `ml/Pipeline.scala:1`,
`ml/param/params.scala` Params): Estimator.fit -> Model,
Transformer.transform, Pipeline = sequential fit/transform."""

from __future__ import annotations

import copy
from typing import Dict, List


class Params:
    """Declared-parameter holder (the reference's Params trait without
    the reflection): subclasses set defaults in __init__; get/set by
    name with copy-on-write semantics."""

    def _params(self) -> Dict[str, object]:
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_")}

    def set(self, **kwargs) -> "Params":
        out = copy.copy(self)
        for k, v in kwargs.items():
            if not hasattr(out, k):
                raise ValueError(
                    f"{type(self).__name__} has no param {k!r}; "
                    f"known: {sorted(self._params())}")
            setattr(out, k, v)
        return out

    def explain_params(self) -> str:
        return "\n".join(f"{k}: {v!r}"
                         for k, v in sorted(self._params().items()))


class Transformer(Params):
    def transform(self, df):
        raise NotImplementedError

    def __call__(self, df):
        return self.transform(df)


class Estimator(Params):
    def fit(self, df) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    pass


class Pipeline(Estimator):
    """fit: run stages in order — estimators fit on the running
    transformed frame and contribute their models; transformers pass
    through (Pipeline.scala:1 semantics)."""

    def __init__(self, stages: List[Params]):
        self.stages = list(stages)

    def fit(self, df) -> "PipelineModel":
        models: List[Transformer] = []
        cur = df
        for stage in self.stages:
            if isinstance(stage, Estimator):
                m = stage.fit(cur)
                models.append(m)
                cur = m.transform(cur)
            elif isinstance(stage, Transformer):
                models.append(stage)
                cur = stage.transform(cur)
            else:
                raise TypeError(f"not a pipeline stage: {stage!r}")
        return PipelineModel(models)


class PipelineModel(Model):
    def __init__(self, stages: List[Transformer]):
        self.stages = list(stages)

    def transform(self, df):
        cur = df
        for s in self.stages:
            cur = s.transform(cur)
        return cur

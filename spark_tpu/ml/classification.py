"""Logistic regression (reference: ml/classification/
LogisticRegression.scala): full-batch gradient descent as ONE jitted
lax.scan — every iteration is two MXU matmuls (X @ w, X^T residual)
instead of the reference's per-partition LogisticAggregator
treeAggregate round trips."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .base import Estimator, Model
from .util import attach_column, collect_xy


@partial(jax.jit, static_argnums=(2,))
def _logreg_fit(X, y, max_iter: int, step, reg):
    n, d = X.shape
    Xb = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)

    def body(w, _):
        p = jax.nn.sigmoid(Xb @ w)
        grad = Xb.T @ (p - y) / n
        grad = grad + reg * w.at[-1].set(0.0)
        return w - step * grad, None

    w0 = jnp.zeros((d + 1,), X.dtype)
    w, _ = jax.lax.scan(body, w0, None, length=max_iter)
    return w


class LogisticRegression(Estimator):
    def __init__(self, featuresCol="features", labelCol="label",
                 predictionCol="prediction",
                 probabilityCol="probability",
                 maxIter=200, stepSize=1.0, regParam=0.0):
        self.featuresCol = featuresCol
        self.labelCol = labelCol
        self.predictionCol = predictionCol
        self.probabilityCol = probabilityCol
        self.maxIter = int(maxIter)
        self.stepSize = float(stepSize)
        self.regParam = float(regParam)

    def fit(self, df) -> "LogisticRegressionModel":
        _, X, y = collect_xy(df, self.featuresCol, self.labelCol)
        w = np.asarray(_logreg_fit(jnp.asarray(X), jnp.asarray(y),
                                   self.maxIter,
                                   jnp.float64(self.stepSize),
                                   jnp.float64(self.regParam)))
        return LogisticRegressionModel(
            self.featuresCol, self.predictionCol, self.probabilityCol,
            w[:-1], float(w[-1]))


class LogisticRegressionModel(Model):
    def __init__(self, featuresCol, predictionCol, probabilityCol,
                 coefficients, intercept):
        self.featuresCol = featuresCol
        self.predictionCol = predictionCol
        self.probabilityCol = probabilityCol
        self.coefficients = np.asarray(coefficients)
        self.intercept = float(intercept)

    def transform(self, df):
        table, X, _ = collect_xy(df, self.featuresCol, None)
        p = np.asarray(jax.nn.sigmoid(
            jnp.asarray(X) @ jnp.asarray(self.coefficients)
            + self.intercept))
        out = attach_column(df, table, self.probabilityCol, p)
        table2 = out.collect()
        return attach_column(out, table2, self.predictionCol,
                             (p >= 0.5).astype(np.float64))

"""KMeans (reference: ml/clustering/KMeans.scala): Lloyd's iterations
as one jitted lax.scan — the [n, k] distance matrix is an MXU matmul
(|x|^2 - 2 x.c + |c|^2) and centroid updates are segment sums, versus
the reference's per-partition runs + collectAsMap per iteration."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .base import Estimator, Model
from .util import attach_column, collect_xy


@partial(jax.jit, static_argnums=(2, 3))
def _lloyd(X, init_centers, k: int, max_iter: int):
    n = X.shape[0]
    x2 = jnp.sum(X * X, axis=1, keepdims=True)        # [n, 1]

    def assign(C):
        c2 = jnp.sum(C * C, axis=1)[None, :]          # [1, k]
        d = x2 - 2.0 * (X @ C.T) + c2                 # MXU
        return jnp.argmin(d, axis=1)

    def body(C, _):
        a = assign(C)
        one = jnp.ones((n,), X.dtype)
        cnt = jax.ops.segment_sum(one, a, num_segments=k)
        tot = jax.ops.segment_sum(X, a, num_segments=k)
        newC = tot / jnp.maximum(cnt, 1.0)[:, None]
        # empty clusters keep their previous center
        newC = jnp.where((cnt > 0)[:, None], newC, C)
        return newC, None

    C, _ = jax.lax.scan(body, init_centers, None, length=max_iter)
    return C, assign(C)


class KMeans(Estimator):
    def __init__(self, k=2, featuresCol="features",
                 predictionCol="prediction", maxIter=20, seed=42):
        self.k = int(k)
        self.featuresCol = featuresCol
        self.predictionCol = predictionCol
        self.maxIter = int(maxIter)
        self.seed = int(seed)

    def fit(self, df) -> "KMeansModel":
        _, X, _ = collect_xy(df, self.featuresCol, None)
        rs = np.random.RandomState(self.seed)
        # farthest-point init (the k-means|| seat): robust to seeds
        # landing inside one cluster, deterministic per seed
        centers = [X[rs.randint(len(X))]]
        for _ in range(1, self.k):
            d = np.min(np.stack([
                np.sum((X - c) ** 2, axis=1) for c in centers]), axis=0)
            centers.append(X[int(np.argmax(d))])
        init = np.stack(centers)
        C, _ = _lloyd(jnp.asarray(X), jnp.asarray(init), self.k,
                      self.maxIter)
        return KMeansModel(self.featuresCol, self.predictionCol,
                           np.asarray(C))


class KMeansModel(Model):
    def __init__(self, featuresCol, predictionCol, centers):
        self.featuresCol = featuresCol
        self.predictionCol = predictionCol
        self.cluster_centers = np.asarray(centers)

    clusterCenters = property(lambda self: self.cluster_centers)

    def transform(self, df):
        table, X, _ = collect_xy(df, self.featuresCol, None)
        C = jnp.asarray(self.cluster_centers)
        Xj = jnp.asarray(X)
        d = (jnp.sum(Xj * Xj, axis=1, keepdims=True)
             - 2.0 * (Xj @ C.T) + jnp.sum(C * C, axis=1)[None, :])
        a = np.asarray(jnp.argmin(d, axis=1)).astype(np.int64)
        return attach_column(df, table, self.predictionCol, a)

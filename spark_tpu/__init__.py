"""spark_tpu: a TPU-native analytics engine with Spark SQL's capabilities.

See SURVEY.md for the blueprint (reference: apache/spark 3.3.0-SNAPSHOT)
and README.md for the architecture stance: Catalyst-shaped compiler,
columnar jax.Array batches, XLA as the whole-stage codegen, collectives
as the shuffle.
"""

import jax

# The engine operates on 64-bit SQL types (BIGINT, DOUBLE, scaled-int64
# decimals); enable them globally before any array is created.
jax.config.update("jax_enable_x64", True)

from . import functions  # noqa: E402
from . import types  # noqa: E402
from .columnar import Batch, Column  # noqa: E402
from .config import Conf  # noqa: E402
from .dataframe import DataFrame  # noqa: E402
from .session import SparkTpuSession  # noqa: E402

__version__ = "0.1.0"

__all__ = ["SparkTpuSession", "DataFrame", "Batch", "Column", "Conf",
           "functions", "types", "__version__"]

"""The TPC-H north-star queries as DataFrame programs (BASELINE.md
progression: Q1 -> Q6 -> Q3 -> Q5).

Join orders put the big table on the probe (left) side so the expansion
join's default output capacity (probe capacity) is exact for the FK
shapes, and dimension tables land on the build side where the planner
can pick the broadcast (all_gather) strategy on a mesh.
"""

from __future__ import annotations

import os

from .. import functions as F
from ..functions import col, lit, to_date
from ..io.sources import ParquetSource


def register_tables(session, path: str) -> None:
    """Point the session catalog at the generated Parquet directory."""
    for name in ("lineitem", "orders", "customer", "supplier", "nation",
                 "region", "part", "partsupp"):
        p = os.path.join(path, f"{name}.parquet")
        if os.path.exists(p):
            session.register_table(name, ParquetSource(p, name))


def q1(session):
    """Pricing summary report (TPC-H Q1)."""
    l = session.table("lineitem")
    disc_price = col("l_extendedprice") * (lit(1) - col("l_discount"))
    charge = disc_price * (lit(1) + col("l_tax"))
    return (l.filter(col("l_shipdate") <= to_date("1998-09-02"))
            .group_by(col("l_returnflag"), col("l_linestatus"))
            .agg(F.sum(col("l_quantity")).alias("sum_qty"),
                 F.sum(col("l_extendedprice")).alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg(col("l_quantity")).alias("avg_qty"),
                 F.avg(col("l_extendedprice")).alias("avg_price"),
                 F.avg(col("l_discount")).alias("avg_disc"),
                 F.count().alias("count_order"))
            .sort(col("l_returnflag").asc(), col("l_linestatus").asc()))


def q3(session):
    """Shipping priority (TPC-H Q3): 3-way join + top-10."""
    c = session.table("customer").filter(
        col("c_mktsegment") == lit("BUILDING"))
    o = (session.table("orders")
         .filter(col("o_orderdate") < to_date("1995-03-15"))
         .join(c, left_on=col("o_custkey"), right_on=col("c_custkey")))
    l = (session.table("lineitem")
         .filter(col("l_shipdate") > to_date("1995-03-15"))
         .join(o, left_on=col("l_orderkey"), right_on=col("o_orderkey")))
    revenue = col("l_extendedprice") * (lit(1) - col("l_discount"))
    return (l.group_by(col("l_orderkey"), col("o_orderdate"),
                       col("o_shippriority"))
            .agg(F.sum(revenue).alias("revenue"))
            .sort(col("revenue").desc(), col("o_orderdate").asc())
            .limit(10))


def q5(session):
    """Local supplier volume (TPC-H Q5): 6-way join over ASIA."""
    r = session.table("region").filter(col("r_name") == lit("ASIA"))
    n = session.table("nation").join(
        r, left_on=col("n_regionkey"), right_on=col("r_regionkey"))
    c = session.table("customer").join(
        n, left_on=col("c_nationkey"), right_on=col("n_nationkey"))
    o = (session.table("orders")
         .filter((col("o_orderdate") >= to_date("1994-01-01"))
                 & (col("o_orderdate") < to_date("1995-01-01")))
         .join(c, left_on=col("o_custkey"), right_on=col("c_custkey")))
    l = session.table("lineitem").join(
        o, left_on=col("l_orderkey"), right_on=col("o_orderkey"))
    # supplier must sit in the customer's nation (the Q5 twist)
    ls = l.join(session.table("supplier"),
                left_on=col("l_suppkey"), right_on=col("s_suppkey"),
                condition=col("c_nationkey") == col("s_nationkey"))
    revenue = col("l_extendedprice") * (lit(1) - col("l_discount"))
    return (ls.group_by(col("n_name"))
            .agg(F.sum(revenue).alias("revenue"))
            .sort(col("revenue").desc()))


def q6(session):
    """Forecasting revenue change (TPC-H Q6): predicate-heavy scan + SUM."""
    l = session.table("lineitem")
    return (l.filter((col("l_shipdate") >= to_date("1994-01-01"))
                     & (col("l_shipdate") < to_date("1995-01-01"))
                     & (col("l_discount") >= lit(0.05))
                     & (col("l_discount") <= lit(0.07))
                     & (col("l_quantity") < lit(24)))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


QUERIES = {"q1": q1, "q3": q3, "q5": q5, "q6": q6}

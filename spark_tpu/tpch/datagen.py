"""TPC-H data generator (vectorized numpy -> pyarrow -> Parquet).

Produces the eight TPC-H tables with dbgen-like shapes, types, and value
distributions (row counts scale with `sf`; lineitem ~= 6M rows/sf).
Not bit-identical to dbgen — golden answers are computed on THIS data by
an independent pandas implementation (golden.py), so parity checks are
self-consistent, the pattern of the reference's golden-file SQL tests
(`SQLQueryTestSuite.scala:124`).

Types follow the spec: keys int64, money DECIMAL(15,2), dates DATE32,
flags/names dictionary strings — exercising the engine's scaled-int64
decimal path, date arithmetic, and dictionary tier end to end.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

EPOCH = np.datetime64("1970-01-01", "D")
START = (np.datetime64("1992-01-01", "D") - EPOCH).astype(np.int32)
END = (np.datetime64("1998-08-02", "D") - EPOCH).astype(np.int32)

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                "TAKE BACK RETURN"]


def _dec(x: np.ndarray, scale: int = 2) -> pa.Array:
    """int64 UNSCALED units (cents for scale 2) -> decimal128(15, scale),
    built directly from the little-endian 128-bit buffer (a cast would
    treat the ints as whole units and rescale them)."""
    lo = x.astype(np.int64)
    raw = np.empty((len(lo), 2), dtype=np.int64)
    raw[:, 0] = lo
    raw[:, 1] = lo >> 63  # sign extension
    return pa.Array.from_buffers(pa.decimal128(15, scale), len(lo),
                                 [None, pa.py_buffer(raw.tobytes())])


def _date(days: np.ndarray) -> pa.Array:
    return pa.array(days.astype(np.int32), type=pa.int32()).cast(pa.date32())


def _pick(rs, values, n) -> pa.Array:
    return pa.array(np.array(values)[rs.randint(0, len(values), n)])


def generate(sf: float, seed: int = 42) -> Dict[str, pa.Table]:
    """Generate all eight tables at scale factor `sf`."""
    rs = np.random.RandomState(seed)
    n_cust = max(1, int(150_000 * sf))
    n_ord = max(1, int(1_500_000 * sf))
    n_supp = max(1, int(10_000 * sf))
    n_part = max(1, int(200_000 * sf))

    tables: Dict[str, pa.Table] = {}

    tables["region"] = pa.table({
        "r_regionkey": pa.array(np.arange(5, dtype=np.int64)),
        "r_name": pa.array(REGIONS),
        "r_comment": pa.array([f"region {r}" for r in REGIONS]),
    })

    n_names = [n for n, _ in NATIONS]
    tables["nation"] = pa.table({
        "n_nationkey": pa.array(np.arange(25, dtype=np.int64)),
        "n_name": pa.array(n_names),
        "n_regionkey": pa.array(np.array([r for _, r in NATIONS],
                                         dtype=np.int64)),
        "n_comment": pa.array([f"nation {n}" for n in n_names]),
    })

    c_nation = rs.randint(0, 25, n_cust).astype(np.int64)
    tables["customer"] = pa.table({
        "c_custkey": pa.array(np.arange(1, n_cust + 1, dtype=np.int64)),
        "c_name": pa.array([f"Customer#{i:09d}" for i in range(1, n_cust + 1)]),
        "c_address": pa.array([f"addr{i % 1000}" for i in range(n_cust)]),
        "c_nationkey": pa.array(c_nation),
        "c_phone": pa.array([f"{10 + i % 25}-{i % 1000:03d}-0000"
                             for i in range(n_cust)]),
        "c_acctbal": _dec(rs.randint(-99999, 999999, n_cust)),
        "c_mktsegment": _pick(rs, SEGMENTS, n_cust),
        "c_comment": pa.array([f"cust comment {i % 97}"
                               for i in range(n_cust)]),
    })

    s_nation = rs.randint(0, 25, n_supp).astype(np.int64)
    tables["supplier"] = pa.table({
        "s_suppkey": pa.array(np.arange(1, n_supp + 1, dtype=np.int64)),
        "s_name": pa.array([f"Supplier#{i:09d}"
                            for i in range(1, n_supp + 1)]),
        "s_address": pa.array([f"saddr{i % 500}" for i in range(n_supp)]),
        "s_nationkey": pa.array(s_nation),
        "s_phone": pa.array([f"{10 + i % 25}-{i % 1000:03d}-1111"
                             for i in range(n_supp)]),
        "s_acctbal": _dec(rs.randint(-99999, 999999, n_supp)),
        "s_comment": pa.array([f"supp comment {i % 89}"
                               for i in range(n_supp)]),
    })

    p_retail = (90000 + (np.arange(1, n_part + 1) % 20001) * 10
                + (np.arange(1, n_part + 1) % 1000) * 100).astype(np.int64)
    tables["part"] = pa.table({
        "p_partkey": pa.array(np.arange(1, n_part + 1, dtype=np.int64)),
        "p_name": pa.array([f"part name {i % 1000}" for i in range(n_part)]),
        "p_mfgr": pa.array([f"Manufacturer#{1 + i % 5}"
                            for i in range(n_part)]),
        "p_brand": pa.array([f"Brand#{11 + i % 45}" for i in range(n_part)]),
        "p_type": pa.array([f"TYPE {i % 150}" for i in range(n_part)]),
        "p_size": pa.array((1 + rs.randint(0, 50, n_part)).astype(np.int32)),
        "p_container": pa.array([f"CONTAINER {i % 40}"
                                 for i in range(n_part)]),
        "p_retailprice": _dec(p_retail),
        "p_comment": pa.array([f"part comment {i % 83}"
                               for i in range(n_part)]),
    })

    o_custkey = rs.randint(1, n_cust + 1, n_ord).astype(np.int64)
    o_date = rs.randint(START, END - 121, n_ord).astype(np.int32)
    n_line = rs.randint(1, 8, n_ord)  # 1..7 lines per order, avg 4
    tables["orders"] = pa.table({
        "o_orderkey": pa.array(np.arange(1, n_ord + 1, dtype=np.int64)),
        "o_custkey": pa.array(o_custkey),
        "o_orderstatus": _pick(rs, ["O", "F", "P"], n_ord),
        "o_totalprice": _dec(rs.randint(85000, 55528600, n_ord)),
        "o_orderdate": _date(o_date),
        "o_orderpriority": _pick(rs, PRIORITIES, n_ord),
        "o_clerk": pa.array([f"Clerk#{i % 1000:09d}" for i in range(n_ord)]),
        "o_shippriority": pa.array(np.zeros(n_ord, dtype=np.int32)),
        "o_comment": pa.array([f"order comment {i % 79}"
                               for i in range(n_ord)]),
    })

    # lineitem: expand orders by per-order line counts
    l_orderkey = np.repeat(np.arange(1, n_ord + 1, dtype=np.int64), n_line)
    l_odate = np.repeat(o_date, n_line)
    n_li = len(l_orderkey)
    # linenumber: position within order, vectorized
    starts = np.zeros(n_ord, dtype=np.int64)
    starts[1:] = np.cumsum(n_line)[:-1]
    l_linenumber = (np.arange(n_li, dtype=np.int64)
                    - np.repeat(starts, n_line) + 1).astype(np.int32)

    l_partkey = rs.randint(1, n_part + 1, n_li).astype(np.int64)
    l_suppkey = rs.randint(1, n_supp + 1, n_li).astype(np.int64)
    qty = rs.randint(1, 51, n_li).astype(np.int64)
    price_per_unit = rs.randint(90001, 2100001, n_li).astype(np.int64) // 100
    extended = qty * price_per_unit  # cents
    discount = rs.randint(0, 11, n_li).astype(np.int64)  # 0.00..0.10
    tax = rs.randint(0, 9, n_li).astype(np.int64)  # 0.00..0.08
    ship = l_odate + rs.randint(1, 122, n_li).astype(np.int32)
    commit = l_odate + rs.randint(30, 91, n_li).astype(np.int32)
    receipt = ship + rs.randint(1, 31, n_li).astype(np.int32)
    cutoff = (np.datetime64("1995-06-17", "D") - EPOCH).astype(np.int32)
    returnflag = np.where(receipt <= cutoff,
                          np.where(rs.rand(n_li) < 0.5, "R", "A"), "N")
    linestatus = np.where(ship > cutoff, "O", "F")

    tables["lineitem"] = pa.table({
        "l_orderkey": pa.array(l_orderkey),
        "l_partkey": pa.array(l_partkey),
        "l_suppkey": pa.array(l_suppkey),
        "l_linenumber": pa.array(l_linenumber),
        "l_quantity": _dec(qty * 100),
        "l_extendedprice": _dec(extended),
        "l_discount": _dec(discount),
        "l_tax": _dec(tax),
        "l_returnflag": pa.array(returnflag),
        "l_linestatus": pa.array(linestatus),
        "l_shipdate": _date(ship),
        "l_commitdate": _date(commit),
        "l_receiptdate": _date(receipt),
        "l_shipinstruct": _pick(rs, SHIPINSTRUCT, n_li),
        "l_shipmode": _pick(rs, SHIPMODES, n_li),
        "l_comment": pa.array([f"li {i % 71}" for i in range(n_li)]),
    })

    # partsupp (Q2/Q9/Q11/Q16/Q20 family)
    n_ps = n_part * 4
    ps_part = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    ps_supp = ((ps_part + np.tile(np.arange(4, dtype=np.int64), n_part)
                * max(1, n_supp // 4)) % n_supp + 1).astype(np.int64)
    tables["partsupp"] = pa.table({
        "ps_partkey": pa.array(ps_part),
        "ps_suppkey": pa.array(ps_supp),
        "ps_availqty": pa.array(rs.randint(1, 10000, n_ps).astype(np.int32)),
        "ps_supplycost": _dec(rs.randint(100, 100100, n_ps)),
        "ps_comment": pa.array([f"ps comment {i % 67}" for i in range(n_ps)]),
    })
    return tables


def write_parquet(path: str, sf: float, seed: int = 42,
                  overwrite: bool = False) -> str:
    """Write all tables under `path/<table>.parquet`; returns `path`.
    Skips generation when the directory is already populated."""
    os.makedirs(path, exist_ok=True)
    marker = os.path.join(path, f".sf_{sf}_{seed}")
    if os.path.exists(marker) and not overwrite:
        return path
    tables = generate(sf, seed)
    for name, table in tables.items():
        pq.write_table(table, os.path.join(path, f"{name}.parquet"))
    with open(marker, "w") as f:
        f.write("ok\n")
    return path

"""TPC-H harness: data generation, the north-star queries, and pandas
golden references for result-parity checks.

The reference repo commits TPC-DS benchmark results only
(`sql/core/benchmarks/TPCDSQueryBenchmark-results.txt`); BASELINE.md
directs that the TPC-H harness be written fresh, modeled on
`TPCDSQueryBenchmark.scala:54` (timed queries over generated Parquet) and
`SQLQueryTestSuite.scala:124` (golden-answer comparison).
"""

from .datagen import generate, write_parquet
from .queries import QUERIES, register_tables

__all__ = ["generate", "write_parquet", "QUERIES", "register_tables"]

"""Independent pandas implementations of the north-star queries.

These compute golden answers on the generated data (the trusted-engine
role duckdb/real-Spark would play; pandas is the independent engine baked
into this image). Parity checks compare engine output against these with
a small float tolerance — the `QueryTest.checkAnswer` pattern.
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd
import pyarrow.parquet as pq


def _read(path: str, name: str) -> pd.DataFrame:
    df = pq.read_table(os.path.join(path, f"{name}.parquet")).to_pandas()
    for c in df.columns:
        # decimals -> float for the pandas reference arithmetic
        if df[c].dtype == object and len(df) and \
                df[c].iloc[0].__class__.__name__ == "Decimal":
            df[c] = df[c].astype(float)
    return df


def q1(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    l = l[l["l_shipdate"] <= pd.Timestamp("1998-09-02").date()]
    l = l.assign(
        disc_price=l["l_extendedprice"] * (1 - l["l_discount"]),
        charge=l["l_extendedprice"] * (1 - l["l_discount"])
        * (1 + l["l_tax"]))
    out = (l.groupby(["l_returnflag", "l_linestatus"], as_index=False)
           .agg(sum_qty=("l_quantity", "sum"),
                sum_base_price=("l_extendedprice", "sum"),
                sum_disc_price=("disc_price", "sum"),
                sum_charge=("charge", "sum"),
                avg_qty=("l_quantity", "mean"),
                avg_price=("l_extendedprice", "mean"),
                avg_disc=("l_discount", "mean"),
                count_order=("l_quantity", "size")))
    return out.sort_values(["l_returnflag", "l_linestatus"]) \
        .reset_index(drop=True)


def q3(path: str) -> pd.DataFrame:
    c = _read(path, "customer")
    o = _read(path, "orders")
    l = _read(path, "lineitem")
    c = c[c["c_mktsegment"] == "BUILDING"]
    o = o[o["o_orderdate"] < pd.Timestamp("1995-03-15").date()]
    l = l[l["l_shipdate"] > pd.Timestamp("1995-03-15").date()]
    m = l.merge(o, left_on="l_orderkey", right_on="o_orderkey") \
        .merge(c, left_on="o_custkey", right_on="c_custkey")
    m = m.assign(revenue=m["l_extendedprice"] * (1 - m["l_discount"]))
    out = (m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                     as_index=False).agg(revenue=("revenue", "sum")))
    out = out.sort_values(["revenue", "o_orderdate"],
                          ascending=[False, True]).head(10)
    return out[["l_orderkey", "o_orderdate", "o_shippriority", "revenue"]] \
        .reset_index(drop=True)


def q5(path: str) -> pd.DataFrame:
    r = _read(path, "region")
    n = _read(path, "nation")
    c = _read(path, "customer")
    o = _read(path, "orders")
    l = _read(path, "lineitem")
    s = _read(path, "supplier")
    r = r[r["r_name"] == "ASIA"]
    m = (c.merge(n, left_on="c_nationkey", right_on="n_nationkey")
         .merge(r, left_on="n_regionkey", right_on="r_regionkey"))
    o = o[(o["o_orderdate"] >= pd.Timestamp("1994-01-01").date())
          & (o["o_orderdate"] < pd.Timestamp("1995-01-01").date())]
    m = o.merge(m, left_on="o_custkey", right_on="c_custkey")
    m = l.merge(m, left_on="l_orderkey", right_on="o_orderkey")
    m = m.merge(s, left_on="l_suppkey", right_on="s_suppkey")
    m = m[m["c_nationkey"] == m["s_nationkey"]]
    m = m.assign(revenue=m["l_extendedprice"] * (1 - m["l_discount"]))
    out = (m.groupby("n_name", as_index=False).agg(revenue=("revenue", "sum"))
           .sort_values("revenue", ascending=False))
    return out.reset_index(drop=True)


def q6(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    m = l[(l["l_shipdate"] >= pd.Timestamp("1994-01-01").date())
          & (l["l_shipdate"] < pd.Timestamp("1995-01-01").date())
          & (l["l_discount"] >= 0.05 - 1e-9)
          & (l["l_discount"] <= 0.07 + 1e-9)
          & (l["l_quantity"] < 24)]
    return pd.DataFrame(
        {"revenue": [(m["l_extendedprice"] * m["l_discount"]).sum()]})


GOLDEN = {"q1": q1, "q3": q3, "q5": q5, "q6": q6}


def compare(got: pd.DataFrame, want: pd.DataFrame,
            float_rtol: float = 1e-6, float_atol: float = 1e-6) -> None:
    """Row-set comparison with float tolerance (QueryTest.checkAnswer).
    `float_atol` absorbs legitimate decimal-scale rounding: avg(decimal)
    rounds HALF_UP at result scale 6 per the reference, pandas does not."""
    if len(got) != len(want):
        raise AssertionError(
            f"row count {len(got)} != {len(want)}\n{got}\n{want}")
    if list(got.columns) != list(want.columns):
        raise AssertionError(f"columns {list(got.columns)} != "
                             f"{list(want.columns)}")
    for c in want.columns:
        g, w = got[c], want[c]
        try:
            gf = g.astype(float)
            wf = w.astype(float)
            if not np.allclose(gf, wf, rtol=float_rtol, atol=float_atol, equal_nan=True):
                bad = np.nonzero(~np.isclose(gf, wf, rtol=float_rtol,
                                             atol=float_atol, equal_nan=True))[0]
                raise AssertionError(
                    f"column {c} diverges at rows {bad[:5]}:\n"
                    f"got {gf.iloc[bad[:5]].tolist()}\n"
                    f"want {wf.iloc[bad[:5]].tolist()}")
        except (ValueError, TypeError):
            if list(g.astype(str)) != list(w.astype(str)):
                raise AssertionError(f"column {c} diverges:\n{g}\n{w}")

"""Independent pandas implementations of the north-star queries.

These compute golden answers on the generated data (the trusted-engine
role duckdb/real-Spark would play; pandas is the independent engine baked
into this image). Parity checks compare engine output against these with
a small float tolerance — the `QueryTest.checkAnswer` pattern.
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd
import pyarrow.parquet as pq


def normalize_decimals(df: pd.DataFrame) -> pd.DataFrame:
    """Cast Decimal object columns to float (in place, returned for
    chaining) — the shared normalization for pandas reference arithmetic
    and for comparing engine output against the goldens."""
    for c in df.columns:
        if df[c].dtype == object and len(df) and \
                df[c].iloc[0].__class__.__name__ == "Decimal":
            df[c] = df[c].astype(float)
    return df


def _read(path: str, name: str) -> pd.DataFrame:
    df = pq.read_table(os.path.join(path, f"{name}.parquet")).to_pandas()
    return normalize_decimals(df)


def q1(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    l = l[l["l_shipdate"] <= pd.Timestamp("1998-09-02").date()]
    l = l.assign(
        disc_price=l["l_extendedprice"] * (1 - l["l_discount"]),
        charge=l["l_extendedprice"] * (1 - l["l_discount"])
        * (1 + l["l_tax"]))
    out = (l.groupby(["l_returnflag", "l_linestatus"], as_index=False)
           .agg(sum_qty=("l_quantity", "sum"),
                sum_base_price=("l_extendedprice", "sum"),
                sum_disc_price=("disc_price", "sum"),
                sum_charge=("charge", "sum"),
                avg_qty=("l_quantity", "mean"),
                avg_price=("l_extendedprice", "mean"),
                avg_disc=("l_discount", "mean"),
                count_order=("l_quantity", "size")))
    return out.sort_values(["l_returnflag", "l_linestatus"]) \
        .reset_index(drop=True)


def q3(path: str) -> pd.DataFrame:
    c = _read(path, "customer")
    o = _read(path, "orders")
    l = _read(path, "lineitem")
    c = c[c["c_mktsegment"] == "BUILDING"]
    o = o[o["o_orderdate"] < pd.Timestamp("1995-03-15").date()]
    l = l[l["l_shipdate"] > pd.Timestamp("1995-03-15").date()]
    m = l.merge(o, left_on="l_orderkey", right_on="o_orderkey") \
        .merge(c, left_on="o_custkey", right_on="c_custkey")
    m = m.assign(revenue=m["l_extendedprice"] * (1 - m["l_discount"]))
    out = (m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                     as_index=False).agg(revenue=("revenue", "sum")))
    out = out.sort_values(["revenue", "o_orderdate"],
                          ascending=[False, True]).head(10)
    return out[["l_orderkey", "o_orderdate", "o_shippriority", "revenue"]] \
        .reset_index(drop=True)


def q5(path: str) -> pd.DataFrame:
    r = _read(path, "region")
    n = _read(path, "nation")
    c = _read(path, "customer")
    o = _read(path, "orders")
    l = _read(path, "lineitem")
    s = _read(path, "supplier")
    r = r[r["r_name"] == "ASIA"]
    m = (c.merge(n, left_on="c_nationkey", right_on="n_nationkey")
         .merge(r, left_on="n_regionkey", right_on="r_regionkey"))
    o = o[(o["o_orderdate"] >= pd.Timestamp("1994-01-01").date())
          & (o["o_orderdate"] < pd.Timestamp("1995-01-01").date())]
    m = o.merge(m, left_on="o_custkey", right_on="c_custkey")
    m = l.merge(m, left_on="l_orderkey", right_on="o_orderkey")
    m = m.merge(s, left_on="l_suppkey", right_on="s_suppkey")
    m = m[m["c_nationkey"] == m["s_nationkey"]]
    m = m.assign(revenue=m["l_extendedprice"] * (1 - m["l_discount"]))
    out = (m.groupby("n_name", as_index=False).agg(revenue=("revenue", "sum"))
           .sort_values("revenue", ascending=False))
    return out.reset_index(drop=True)


def q6(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    m = l[(l["l_shipdate"] >= pd.Timestamp("1994-01-01").date())
          & (l["l_shipdate"] < pd.Timestamp("1995-01-01").date())
          & (l["l_discount"] >= 0.05 - 1e-9)
          & (l["l_discount"] <= 0.07 + 1e-9)
          & (l["l_quantity"] < 24)]
    return pd.DataFrame(
        {"revenue": [(m["l_extendedprice"] * m["l_discount"]).sum()]})


GOLDEN = {"q1": q1, "q3": q3, "q5": q5, "q6": q6}


def compare(got: pd.DataFrame, want: pd.DataFrame,
            float_rtol: float = 1e-6, float_atol: float = 1e-6) -> None:
    """Row-set comparison with float tolerance (QueryTest.checkAnswer).
    `float_atol` absorbs legitimate decimal-scale rounding: avg(decimal)
    rounds HALF_UP at result scale 6 per the reference, pandas does not."""
    if len(got) != len(want):
        raise AssertionError(
            f"row count {len(got)} != {len(want)}\n{got}\n{want}")
    if list(got.columns) != list(want.columns):
        raise AssertionError(f"columns {list(got.columns)} != "
                             f"{list(want.columns)}")
    for c in want.columns:
        g, w = got[c], want[c]
        try:
            gf = g.astype(float)
            wf = w.astype(float)
            if not np.allclose(gf, wf, rtol=float_rtol, atol=float_atol, equal_nan=True):
                bad = np.nonzero(~np.isclose(gf, wf, rtol=float_rtol,
                                             atol=float_atol, equal_nan=True))[0]
                raise AssertionError(
                    f"column {c} diverges at rows {bad[:5]}:\n"
                    f"got {gf.iloc[bad[:5]].tolist()}\n"
                    f"want {wf.iloc[bad[:5]].tolist()}")
        except (ValueError, TypeError):
            if list(g.astype(str)) != list(w.astype(str)):
                raise AssertionError(f"column {c} diverges:\n{g}\n{w}")


def q4(path: str) -> pd.DataFrame:
    o = _read(path, "orders")
    l = _read(path, "lineitem")
    o = o[(o["o_orderdate"] >= pd.Timestamp("1993-07-01").date())
          & (o["o_orderdate"] < pd.Timestamp("1993-10-01").date())]
    late = l[l["l_commitdate"] < l["l_receiptdate"]]["l_orderkey"].unique()
    m = o[o["o_orderkey"].isin(late)]
    out = (m.groupby("o_orderpriority", as_index=False)
           .agg(order_count=("o_orderkey", "size"))
           .sort_values("o_orderpriority"))
    return out.reset_index(drop=True)


def q12(path: str) -> pd.DataFrame:
    o = _read(path, "orders")
    l = _read(path, "lineitem")
    l = l[l["l_shipmode"].isin(["MAIL", "SHIP"])
          & (l["l_commitdate"] < l["l_receiptdate"])
          & (l["l_shipdate"] < l["l_commitdate"])
          & (l["l_receiptdate"] >= pd.Timestamp("1994-01-01").date())
          & (l["l_receiptdate"] < pd.Timestamp("1995-01-01").date())]
    m = l.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    high = m["o_orderpriority"].isin(["1-URGENT", "2-HIGH"])
    m = m.assign(high_line_count=high.astype(np.int64),
                 low_line_count=(~high).astype(np.int64))
    out = (m.groupby("l_shipmode", as_index=False)
           .agg(high_line_count=("high_line_count", "sum"),
                low_line_count=("low_line_count", "sum"))
           .sort_values("l_shipmode"))
    return out.reset_index(drop=True)


def q14(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    p = _read(path, "part")
    l = l[(l["l_shipdate"] >= pd.Timestamp("1995-09-01").date())
          & (l["l_shipdate"] < pd.Timestamp("1995-10-01").date())]
    m = l.merge(p, left_on="l_partkey", right_on="p_partkey")
    rev = m["l_extendedprice"] * (1 - m["l_discount"])
    promo = rev.where(m["p_type"].str.startswith("TYPE 1"), 0.0)
    return pd.DataFrame({"promo_revenue":
                         [100.0 * promo.sum() / rev.sum()]})


def q17(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    p = _read(path, "part")
    p = p[(p["p_brand"] == "Brand#23") & (p["p_container"] == "CONTAINER 7")]
    m = l.merge(p, left_on="l_partkey", right_on="p_partkey")
    avg_qty = l.groupby("l_partkey")["l_quantity"].mean()
    thresh = m["l_partkey"].map(avg_qty) * 0.2
    m = m[m["l_quantity"] < thresh]
    return pd.DataFrame({"avg_yearly":
                         [m["l_extendedprice"].sum() / 7.0]})


def q19(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    p = _read(path, "part")
    m = l.merge(p, left_on="l_partkey", right_on="p_partkey")
    m = m[m["l_shipmode"].isin(["AIR", "AIR REG"])
          & (m["l_shipinstruct"] == "DELIVER IN PERSON")]
    c1 = ((m["p_brand"] == "Brand#12")
          & (m["l_quantity"] >= 1) & (m["l_quantity"] <= 11)
          & (m["p_size"] >= 1) & (m["p_size"] <= 5))
    c2 = ((m["p_brand"] == "Brand#23")
          & (m["l_quantity"] >= 10) & (m["l_quantity"] <= 20)
          & (m["p_size"] >= 1) & (m["p_size"] <= 10))
    c3 = ((m["p_brand"] == "Brand#34")
          & (m["l_quantity"] >= 20) & (m["l_quantity"] <= 30)
          & (m["p_size"] >= 1) & (m["p_size"] <= 15))
    m = m[c1 | c2 | c3]
    # SQL SUM over zero rows is NULL, not 0 (small SFs select nothing)
    rev = (m["l_extendedprice"] * (1 - m["l_discount"])).sum()
    return pd.DataFrame({"revenue": [rev if len(m) else np.nan]})


GOLDEN.update({"q4": q4, "q12": q12, "q14": q14, "q17": q17, "q19": q19})


def _cached(qname: str, fn):
    """Disk-cache golden results next to the data (golden_cache/<q>.parquet):
    the pandas implementations convert every Decimal cell through Python
    objects — minutes of host time per query at SF10+ — while parity runs
    only need the answer once per dataset."""
    def run(path: str) -> pd.DataFrame:
        import pyarrow as pa
        # key on the dataset's content stamp so regenerated data
        # invalidates old answers
        stamp = 0.0
        for f in sorted(os.listdir(path)) if os.path.isdir(path) else []:
            if f.endswith(".parquet"):
                stamp = max(stamp, os.path.getmtime(os.path.join(path, f)))
        cache = os.path.join(path, "golden_cache",
                             f"{qname}-{int(stamp * 1e6)}.parquet")
        if os.path.exists(cache):
            return pq.read_table(cache).to_pandas()
        out = fn(path)
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        tmp = f"{cache}.{os.getpid()}.tmp"  # per-process: concurrent-safe
        pq.write_table(pa.Table.from_pandas(out, preserve_index=False),
                       tmp)
        os.replace(tmp, cache)  # atomic: no truncated caches on Ctrl-C
        return out
    return run


GOLDEN = {k: _cached(k, v) for k, v in GOLDEN.items()}


def q10(path: str) -> pd.DataFrame:
    c = _read(path, "customer")
    o = _read(path, "orders")
    l = _read(path, "lineitem")
    n = _read(path, "nation")
    o = o[(o["o_orderdate"] >= pd.Timestamp("1993-10-01").date())
          & (o["o_orderdate"] < pd.Timestamp("1994-01-01").date())]
    l = l[l["l_returnflag"] == "R"]
    m = (l.merge(o, left_on="l_orderkey", right_on="o_orderkey")
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(n, left_on="c_nationkey", right_on="n_nationkey"))
    m = m.assign(revenue=m["l_extendedprice"] * (1 - m["l_discount"]))
    out = (m.groupby(["c_custkey", "c_name", "c_acctbal", "n_name",
                      "c_address", "c_phone", "c_comment"], as_index=False)
           .agg(revenue=("revenue", "sum"))
           .sort_values(["revenue", "c_custkey"],
                        ascending=[False, True]).head(20))
    cols = ["c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
            "c_address", "c_phone", "c_comment"]
    return out[cols].reset_index(drop=True)


GOLDEN_RAW_Q10 = q10
GOLDEN["q10"] = _cached("q10", q10)


def q9(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    p = _read(path, "part")
    s = _read(path, "supplier")
    ps = _read(path, "partsupp")
    o = _read(path, "orders")
    n = _read(path, "nation")
    p = p[p["p_name"].str.contains("name 5", regex=False)]
    m = (l.merge(p, left_on="l_partkey", right_on="p_partkey")
         .merge(s, left_on="l_suppkey", right_on="s_suppkey")
         .merge(ps, left_on=["l_suppkey", "l_partkey"],
                right_on=["ps_suppkey", "ps_partkey"])
         .merge(o, left_on="l_orderkey", right_on="o_orderkey")
         .merge(n, left_on="s_nationkey", right_on="n_nationkey"))
    amount = (m["l_extendedprice"] * (1 - m["l_discount"])
              - m["ps_supplycost"] * m["l_quantity"])
    year = pd.to_datetime(m["o_orderdate"]).dt.year
    g = pd.DataFrame({"nation": m["n_name"], "o_year": year,
                      "amount": amount})
    out = (g.groupby(["nation", "o_year"], as_index=False)
           .agg(sum_profit=("amount", "sum"))
           .sort_values(["nation", "o_year"], ascending=[True, False]))
    return out.reset_index(drop=True)


GOLDEN["q9"] = _cached("q9", q9)


def q7(path: str) -> pd.DataFrame:
    s = _read(path, "supplier")
    l = _read(path, "lineitem")
    o = _read(path, "orders")
    c = _read(path, "customer")
    n = _read(path, "nation")
    l = l[(l["l_shipdate"] >= pd.Timestamp("1995-01-01").date())
          & (l["l_shipdate"] <= pd.Timestamp("1996-12-31").date())]
    m = (l.merge(s, left_on="l_suppkey", right_on="s_suppkey")
         .merge(o, left_on="l_orderkey", right_on="o_orderkey")
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(n.rename(columns=lambda x: x + "_1"),
                left_on="s_nationkey", right_on="n_nationkey_1")
         .merge(n.rename(columns=lambda x: x + "_2"),
                left_on="c_nationkey", right_on="n_nationkey_2"))
    cond = (((m["n_name_1"] == "FRANCE") & (m["n_name_2"] == "GERMANY"))
            | ((m["n_name_1"] == "GERMANY") & (m["n_name_2"] == "FRANCE")))
    m = m[cond]
    vol = m["l_extendedprice"] * (1 - m["l_discount"])
    year = pd.to_datetime(m["l_shipdate"]).dt.year
    g = pd.DataFrame({"supp_nation": m["n_name_1"],
                      "cust_nation": m["n_name_2"],
                      "l_year": year, "revenue": vol})
    out = (g.groupby(["supp_nation", "cust_nation", "l_year"],
                     as_index=False).agg(revenue=("revenue", "sum"))
           .sort_values(["supp_nation", "cust_nation", "l_year"]))
    return out.reset_index(drop=True)


GOLDEN["q7"] = _cached("q7", q7)


def q8(path: str) -> pd.DataFrame:
    p = _read(path, "part")
    s = _read(path, "supplier")
    l = _read(path, "lineitem")
    o = _read(path, "orders")
    c = _read(path, "customer")
    n = _read(path, "nation")
    r = _read(path, "region")
    p = p[p["p_type"] == "TYPE 25"]
    o = o[(o["o_orderdate"] >= pd.Timestamp("1995-01-01").date())
          & (o["o_orderdate"] <= pd.Timestamp("1996-12-31").date())]
    m = (l.merge(p, left_on="l_partkey", right_on="p_partkey")
         .merge(s, left_on="l_suppkey", right_on="s_suppkey")
         .merge(o, left_on="l_orderkey", right_on="o_orderkey")
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(n.rename(columns=lambda x: x + "_1"),
                left_on="c_nationkey", right_on="n_nationkey_1")
         .merge(r, left_on="n_regionkey_1", right_on="r_regionkey")
         .merge(n.rename(columns=lambda x: x + "_2"),
                left_on="s_nationkey", right_on="n_nationkey_2"))
    m = m[m["r_name"] == "AMERICA"]
    vol = m["l_extendedprice"] * (1 - m["l_discount"])
    year = pd.to_datetime(m["o_orderdate"]).dt.year
    g = pd.DataFrame({"o_year": year, "volume": vol,
                      "nation": m["n_name_2"]})
    def share(sub):
        tot = sub["volume"].sum()
        br = sub.loc[sub["nation"] == "BRAZIL", "volume"].sum()
        return br / tot if tot else np.nan
    out = (g.groupby("o_year").apply(share, include_groups=False)
           .reset_index(name="mkt_share").sort_values("o_year"))
    return out.reset_index(drop=True)


GOLDEN["q8"] = _cached("q8", q8)


def q13(path: str) -> pd.DataFrame:
    c = _read(path, "customer")
    o = _read(path, "orders")
    o = o[~o["o_comment"].str.contains("comment 7", regex=False)]
    m = c.merge(o, left_on="c_custkey", right_on="o_custkey", how="left")
    per_cust = (m.groupby("c_custkey")["o_orderkey"].count()
                .reset_index(name="c_count"))
    out = (per_cust.groupby("c_count").size().reset_index(name="custdist")
           .sort_values(["custdist", "c_count"], ascending=[False, False]))
    return out[["c_count", "custdist"]].reset_index(drop=True)


def q18(path: str) -> pd.DataFrame:
    c = _read(path, "customer")
    o = _read(path, "orders")
    l = _read(path, "lineitem")
    big = l.groupby("l_orderkey")["l_quantity"].sum()
    keys = big[big > 300].index
    m = (o[o["o_orderkey"].isin(keys)]
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(l, left_on="o_orderkey", right_on="l_orderkey"))
    out = (m.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                      "o_totalprice"], as_index=False)
           .agg(sum_qty=("l_quantity", "sum"))
           .sort_values(["o_totalprice", "o_orderdate"],
                        ascending=[False, True]).head(100))
    return out.reset_index(drop=True)


GOLDEN["q13"] = _cached("q13", q13)
GOLDEN["q18"] = _cached("q18", q18)


def q16(path: str) -> pd.DataFrame:
    ps = _read(path, "partsupp")
    p = _read(path, "part")
    s = _read(path, "supplier")
    p = p[(p["p_brand"] != "Brand#45")
          & ~p["p_type"].str.startswith("TYPE 3")
          & p["p_size"].isin([49, 14, 23, 45, 19, 3, 36, 9])]
    bad = s[s["s_comment"].str.contains("comment 5", regex=False)][
        "s_suppkey"]
    m = ps.merge(p, left_on="ps_partkey", right_on="p_partkey")
    m = m[~m["ps_suppkey"].isin(bad)]
    out = (m.groupby(["p_brand", "p_type", "p_size"])["ps_suppkey"]
           .nunique().reset_index(name="supplier_cnt")
           .sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                        ascending=[False, True, True, True]))
    return out[["p_brand", "p_type", "p_size", "supplier_cnt"]] \
        .reset_index(drop=True)


GOLDEN["q16"] = _cached("q16", q16)


def q11(path: str) -> pd.DataFrame:
    ps = _read(path, "partsupp")
    s = _read(path, "supplier")
    n = _read(path, "nation")
    m = (ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey")
         .merge(n, left_on="s_nationkey", right_on="n_nationkey"))
    m = m[m["n_name"] == "GERMANY"]
    m = m.assign(value=m["ps_supplycost"] * m["ps_availqty"])
    thresh = m["value"].sum() * 0.0001
    out = (m.groupby("ps_partkey", as_index=False)
           .agg(value=("value", "sum")))
    out = out[out["value"] > thresh] \
        .sort_values("value", ascending=False)
    return out[["ps_partkey", "value"]].reset_index(drop=True)


def q22(path: str) -> pd.DataFrame:
    c = _read(path, "customer")
    o = _read(path, "orders")
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    c = c.assign(cntrycode=c["c_phone"].str[:2])
    cc = c[c["cntrycode"].isin(codes)]
    avg_bal = cc.loc[cc["c_acctbal"] > 0.0, "c_acctbal"].mean()
    sel = cc[(cc["c_acctbal"] > avg_bal)
             & ~cc["c_custkey"].isin(o["o_custkey"])]
    out = (sel.groupby("cntrycode", as_index=False)
           .agg(numcust=("c_custkey", "size"),
                totacctbal=("c_acctbal", "sum"))
           .sort_values("cntrycode"))
    return out[["cntrycode", "numcust", "totacctbal"]] \
        .reset_index(drop=True)


GOLDEN["q11"] = _cached("q11", q11)
GOLDEN["q22"] = _cached("q22", q22)


def q15(path: str) -> pd.DataFrame:
    l = _read(path, "lineitem")
    s = _read(path, "supplier")
    l = l[(l["l_shipdate"] >= pd.Timestamp("1996-01-01").date())
          & (l["l_shipdate"] < pd.Timestamp("1996-04-01").date())]
    rev = (l.assign(r=l["l_extendedprice"] * (1 - l["l_discount"]))
           .groupby("l_suppkey", as_index=False).agg(total_revenue=("r", "sum")))
    top = rev[rev["total_revenue"] == rev["total_revenue"].max()]
    m = s.merge(top, left_on="s_suppkey", right_on="l_suppkey")
    out = m[["s_suppkey", "s_name", "s_address", "s_phone",
             "total_revenue"]].sort_values("s_suppkey")
    return out.reset_index(drop=True)


GOLDEN["q15"] = _cached("q15", q15)


def q2(path: str) -> pd.DataFrame:
    p = _read(path, "part")
    s = _read(path, "supplier")
    ps = _read(path, "partsupp")
    n = _read(path, "nation")
    r = _read(path, "region")
    r = r[r["r_name"] == "EUROPE"]
    base = (ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey")
            .merge(n, left_on="s_nationkey", right_on="n_nationkey")
            .merge(r, left_on="n_regionkey", right_on="r_regionkey"))
    min_cost = base.groupby("ps_partkey")["ps_supplycost"].min()
    p = p[(p["p_size"] == 15) & p["p_type"].str.contains("TYPE 2",
                                                         regex=False)]
    m = base.merge(p, left_on="ps_partkey", right_on="p_partkey")
    m = m[m["ps_supplycost"] == m["ps_partkey"].map(min_cost)]
    out = (m.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                         ascending=[False, True, True, True]).head(100))
    cols = ["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
            "s_address", "s_phone", "s_comment"]
    return out[cols].reset_index(drop=True)


GOLDEN["q2"] = _cached("q2", q2)


def q20(path: str) -> pd.DataFrame:
    s = _read(path, "supplier")
    n = _read(path, "nation")
    p = _read(path, "part")
    ps = _read(path, "partsupp")
    l = _read(path, "lineitem")
    parts = p[p["p_name"].str.startswith("part name 5")]["p_partkey"]
    l = l[(l["l_shipdate"] >= pd.Timestamp("1994-01-01").date())
          & (l["l_shipdate"] < pd.Timestamp("1995-01-01").date())]
    half = (l.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum()
            * 0.5)
    m = ps[ps["ps_partkey"].isin(parts)].copy()
    key = list(zip(m["ps_partkey"], m["ps_suppkey"]))
    m = m[m["ps_availqty"] > pd.Series(key, index=m.index).map(half)]
    sel = s[s["s_suppkey"].isin(m["ps_suppkey"])]
    sel = sel.merge(n, left_on="s_nationkey", right_on="n_nationkey")
    sel = sel[sel["n_name"] == "CANADA"]
    out = sel[["s_name", "s_address"]].sort_values("s_name")
    return out.reset_index(drop=True)


GOLDEN["q20"] = _cached("q20", q20)


def q21(path: str) -> pd.DataFrame:
    s = _read(path, "supplier")
    l = _read(path, "lineitem")
    o = _read(path, "orders")
    n = _read(path, "nation")
    late = l[l["l_receiptdate"] > l["l_commitdate"]]
    n_supp = l.groupby("l_orderkey")["l_suppkey"].nunique()
    n_late_supp = late.groupby("l_orderkey")["l_suppkey"].nunique()
    m = (late.merge(s, left_on="l_suppkey", right_on="s_suppkey")
         .merge(o[o["o_orderstatus"] == "F"], left_on="l_orderkey",
                right_on="o_orderkey")
         .merge(n, left_on="s_nationkey", right_on="n_nationkey"))
    m = m[m["n_name"] == "SAUDI ARABIA"]
    m = m[(m["l_orderkey"].map(n_supp) > 1)
          & (m["l_orderkey"].map(n_late_supp) == 1)]
    out = (m.groupby("s_name").size().reset_index(name="numwait")
           .sort_values(["numwait", "s_name"], ascending=[False, True])
           .head(100))
    return out[["s_name", "numwait"]].reset_index(drop=True)


GOLDEN["q21"] = _cached("q21", q21)

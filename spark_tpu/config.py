"""Declarative configuration registry.

Design follows the reference's two-tier config system (Spark
`core/src/main/scala/org/apache/spark/SparkConf.scala:54` string map +
typed `internal/config/ConfigEntry.scala:74` declarations, and the
session-scoped `sql/catalyst/.../internal/SQLConf.scala:56`): a single
module-level registry of typed entries with defaults/docs/validators,
overlaid by a per-session mutable map that is runtime-settable.
"""

from __future__ import annotations

import os as _os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


@dataclass(frozen=True)
class ConfigEntry:
    """A typed config declaration (reference: ConfigEntry.scala:74)."""

    key: str
    default: Any
    type_: type
    doc: str = ""
    validator: Optional[Callable[[Any], bool]] = None
    version: str = "0.1.0"

    def coerce(self, value: Any) -> Any:
        if self.type_ is bool and isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes")
        return self.type_(value)


_REGISTRY: Dict[str, ConfigEntry] = {}
_REGISTRY_LOCK = threading.Lock()


def register(key: str, default: Any, doc: str = "",
             validator: Optional[Callable[[Any], bool]] = None,
             type_: Optional[type] = None) -> ConfigEntry:
    entry = ConfigEntry(key=key, default=default,
                        type_=type_ or type(default), doc=doc,
                        validator=validator)
    with _REGISTRY_LOCK:
        if key in _REGISTRY:
            raise ValueError(f"duplicate config entry: {key}")
        _REGISTRY[key] = entry
    return entry


def registry() -> Dict[str, ConfigEntry]:
    return dict(_REGISTRY)


class Conf:
    """Session-scoped overlay over the registry (reference: SQLConf.scala:56).

    Unknown keys are allowed (string passthrough) to mirror SparkConf's
    open string map; known keys are validated and coerced.
    """

    def __init__(self, parent: Optional["Conf"] = None):
        self._settings: Dict[str, Any] = {}
        self._parent = parent

    def set(self, key: str, value: Any) -> "Conf":
        entry = _REGISTRY.get(key)
        if entry is not None:
            value = entry.coerce(value)
            if entry.validator is not None and not entry.validator(value):
                raise ValueError(f"invalid value for {key}: {value!r}")
        self._settings[key] = value
        return self

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._settings:
            return self._settings[key]
        if self._parent is not None and self._parent.contains(key):
            return self._parent.get(key)
        entry = _REGISTRY.get(key)
        if entry is not None:
            return entry.default
        return default

    def contains(self, key: str) -> bool:
        return (key in self._settings
                or (self._parent is not None and self._parent.contains(key))
                or key in _REGISTRY)

    def is_explicitly_set(self, key: str) -> bool:
        """True when the key was set on this conf or any parent overlay
        (as opposed to merely having a registry default) — the
        deprecated-alias resolution hook (a legacy key only overrides
        its successor when a user actually set it)."""
        return (key in self._settings
                or (self._parent is not None
                    and self._parent.is_explicitly_set(key)))

    def unset(self, key: str) -> None:
        self._settings.pop(key, None)

    def copy(self) -> "Conf":
        c = Conf(parent=self._parent)
        c._settings.update(self._settings)
        return c


# ---------------------------------------------------------------------------
# Core entries (analog of internal/config/package.scala + SQLConf registrations)
# ---------------------------------------------------------------------------

AGG_SORT_FALLBACK = register(
    "spark_tpu.sql.aggregate.maxDirectDomain", 1 << 22,
    doc="Max combined integer key domain for the direct scatter-add "
        "aggregate fast path; larger domains use the sort-based aggregate.")

AGG_KERNEL_MODE = register(
    "spark_tpu.sql.aggregate.kernelMode", "auto",
    doc="Dense-domain aggregate update kernel: 'auto' picks the Pallas "
        "MXU one-hot matmul on TPU and XLA scatter elsewhere; 'matmul' / "
        "'scatter' force a path (matmul off-TPU runs the Pallas kernel "
        "in interpret mode — slow, for tests).",
    validator=lambda v: v in ("auto", "matmul", "scatter"))

AGG_TABLE_SIZE = register(
    "spark_tpu.sql.aggregate.estimatedGroups", 1 << 16,
    doc="Estimated distinct group count used to size hash-aggregate output "
        "when no tighter bound can be inferred (AQE may revise).")

JOIN_KERNEL_MODE = register(
    "spark_tpu.sql.join.kernelMode", "auto",
    doc="Equi-join match kernel (execution/hash_join.py vs the sorted-"
        "build binary search in execution/join.py): 'hash' builds a "
        "power-of-two open-addressing table over the (sorted) build "
        "keys and probes it with a fixed-bound vectorized loop — the "
        "BytesToBytesMap.java seat, replacing the probe-side "
        "searchsorted sorts that dominated the join-bound TPC-H "
        "profile; 'sort' keeps the binary-search path; 'auto' picks "
        "hash only for large probes over comparatively small builds "
        "(join.hashMinProbeRows / hashProbeBuildRatio), so small joins "
        "and CPU test runs keep the sort path. Results are "
        "byte-identical across modes (both kernels emit matches in the "
        "same sorted-build order).",
    validator=lambda v: v in ("auto", "hash", "sort"))

JOIN_HASH_LOAD_FACTOR = register(
    "spark_tpu.sql.join.hashLoadFactor", 0.5,
    doc="Target load factor for the hash-join table: slots = the "
        "smallest power of two >= build capacity / loadFactor (clamped "
        "by join.hashMaxTableSlots). Lower = fewer probe steps, more "
        "HBM.",
    validator=lambda v: 0.0 < v <= 0.9)

JOIN_HASH_MAX_PROBE = register(
    "spark_tpu.sql.join.hashMaxProbe", 64,
    doc="Fixed bound on linear-probe steps for hash-join build inserts "
        "and probes. A build whose longest collision cluster exceeds it "
        "raises the join_hashsat_<tag> flag and the AQE loop re-jits "
        "that join on the sort kernel (correctness never depends on "
        "the bound).",
    validator=lambda v: v >= 1)

JOIN_HASH_MAX_SLOTS = register(
    "spark_tpu.sql.join.hashMaxTableSlots", 1 << 26,
    doc="Upper bound on hash-join table slots (HBM guard: ~16 bytes "
        "per slot). A build capacity that would push the effective "
        "load factor past 0.7 under this clamp falls back to the sort "
        "kernel at trace time (surfaced by the analyzer's "
        "JOIN_HASH_TABLE_PRESSURE finding).",
    validator=lambda v: v >= 16)

JOIN_HASH_MIN_PROBE_ROWS = register(
    "spark_tpu.sql.join.hashMinProbeRows", 1 << 19,
    doc="kernelMode=auto: minimum probe-side capacity for the hash "
        "kernel. Below it the sorted-build binary search wins (the "
        "probe-side sort it pays is tiny) and tier-1 CPU runs stay on "
        "the extensively-exercised sort path.")

JOIN_HASH_PROBE_BUILD_RATIO = register(
    "spark_tpu.sql.join.hashProbeBuildRatio", 4.0,
    doc="kernelMode=auto: minimum probe/build capacity ratio for the "
        "hash kernel. The hash table amortizes its build cost over "
        "probe rows; near-square joins keep the sort path.",
    validator=lambda v: v >= 0)

INGEST_PREFETCH = register(
    "spark_tpu.sql.ingest.prefetch", True,
    doc="Double-buffered chunk ingest for the streaming drivers "
        "(streaming_agg direct/spill/mesh + external collect): a "
        "background thread decodes and dictionary-unifies Parquet "
        "chunk N+1 into HOST buffers while chunk N computes on device "
        "— the shuffle-fetch/compute pipelining seat (SURVEY 2.5). "
        "Bounded to ONE in-flight chunk; device placement stays on the "
        "consumer thread, so HBM residency, arbiter leases and the "
        "per-chunk retry/checkpoint semantics are unchanged. Results "
        "are identical on/off; only ingest/compute overlap changes "
        "(ingest_overlap_ms / ingest_stall_ms counters).")

SHUFFLE_PARTITIONS = register(
    "spark_tpu.sql.shuffle.partitions", 8,
    doc="Number of logical shuffle partitions (mesh data axis size).")

BROADCAST_THRESHOLD = register(
    "spark_tpu.sql.autoBroadcastJoinThreshold", 64 << 20,
    doc="Max estimated build-side bytes for broadcast (all_gather) joins; "
        "analog of spark.sql.autoBroadcastJoinThreshold.")

BATCH_BUCKET_GROWTH = register(
    "spark_tpu.sql.execution.bucketGrowth", 2.0,
    doc="Padding bucket growth factor: batch capacities are rounded up to "
        "powers of this factor to bound XLA recompilation across batch "
        "sizes (static-shape discipline, SURVEY.md section 7).")

STREAMING_CHUNK_ROWS = register(
    "spark_tpu.sql.execution.streamingChunkRows", 1 << 24,
    doc="Chunk size (rows) for streaming large scans through aggregates "
        "with carried accumulator tables; bounds HBM residency of a scan "
        "the way the reference's row-iterator pipeline does. (1<<26 "
        "chunks faulted the v5e runtime on wide-domain aggregates.)")

TASK_MAX_FAILURES = register(
    "spark_tpu.sql.execution.maxTaskFailures", 2,
    doc="DEPRECATED alias of spark_tpu.execution.maxRetries (kept for "
        "compatibility): when explicitly set, it overrides maxRetries. "
        "The spark.task.maxFailures seat — gang SPMD retries the whole "
        "stage, not one task.")

EXEC_MAX_RETRIES = register(
    "spark_tpu.execution.maxRetries", 3,
    doc="Retry budget per query execution for TRANSIENT failures "
        "(remote-compile 500s, UNAVAILABLE, DEADLINE_EXCEEDED) and "
        "stage wall-clock timeouts, with exponential backoff + jitter "
        "(execution/failures.py taxonomy). A transient retry drops the "
        "failed stage's compiled entry and recompiles; a timeout retry "
        "keeps it (the program was fine, just slow).")

EXEC_BACKOFF_MS = register(
    "spark_tpu.execution.backoffMs", 50.0,
    doc="Base backoff for stage-failure retries: attempt n sleeps "
        "backoffMs * 2^n * uniform(0.5, 1.0) milliseconds.",
    validator=lambda v: v >= 0)

EXEC_STAGE_TIMEOUT_MS = register(
    "spark_tpu.execution.stageTimeoutMs", 0,
    doc="Per-stage wall-clock deadline (compile + run + stats pull of "
        "one attempt), checked cooperatively after the attempt's host "
        "sync. A blown deadline raises StageTimeoutError and retries "
        "under the maxRetries budget. 0 disables.")

EXEC_QUERY_DEADLINE_MS = register(
    "spark_tpu.execution.queryDeadlineMs", 0.0,
    doc="End-to-end query deadline in milliseconds, armed on the "
        "cooperative cancel token (execution/lifecycle.py) at "
        "execution entry (at SERVICE SUBMIT entry for POST /sql, so "
        "admission-queue and session waits count against the budget; "
        "per-request override via the request's conf map). Every "
        "downstream wait — stage attempts, retry backoff, admission "
        "queue, arbiter lease, chunk boundaries — is capped by the "
        "remaining budget; a blown deadline raises the structured "
        "QueryDeadlineError, which STOPS the recovery ladder instead "
        "of retrying through it (distinct from the per-stage "
        "stageTimeoutMs TIMEOUT class). 0 disables.",
    validator=lambda v: v >= 0)

EXEC_DISPATCH_POLL_MS = register(
    "spark_tpu.execution.dispatchPollMs", 25,
    doc="Cancellable host sync of a DISPATCHED stage: with a cancel "
        "token installed, the post-dispatch stats pull polls the "
        "output arrays' readiness instead of blocking in "
        "jax.device_get — the tick ramps 1ms up to this cap, so a "
        "cancel (DELETE /queries/<id>) or a blown queryDeadlineMs "
        "lands within ~one capped tick while the device compute "
        "proceeds in the background, and short stages pay ~1ms of "
        "added sync latency. 0 restores the blocking sync "
        "(cancellation then lands only when the stage completes).",
    validator=lambda v: v >= 0)

CHUNK_RETRY_ENABLED = register(
    "spark_tpu.execution.chunkRetry.enabled", True,
    doc="Chunk-granular retry inside the streaming drivers "
        "(execution/recovery.py): a TRANSIENT/TIMEOUT failure while "
        "streaming replays only the failed chunk against the carried "
        "accumulator state, instead of surfacing to the whole-query "
        "retry loop and re-ingesting from chunk 0. Recoveries are "
        "recorded as `chunk_retry` actions in fault_summary and the "
        "`rec_chunks_replayed` counter.")

CHUNK_RETRY_MAX = register(
    "spark_tpu.execution.chunkRetry.maxRetries", 2,
    doc="Per-CHUNK retry budget for the streaming drivers (a fresh "
        "exponential-backoff RetryPolicy per chunk, the "
        "spark.task.maxFailures discipline — per task attempt, not "
        "per stream). Backoff follows spark_tpu.execution.backoffMs. "
        "0 disables chunk retry (failures surface to the whole-query "
        "ladder).",
    validator=lambda v: v >= 0)

CHECKPOINT_EVERY_CHUNKS = register(
    "spark_tpu.execution.checkpoint.everyChunks", 8,
    doc="Mesh streaming checkpoint cadence: every N consumed chunks, "
        "snapshot the per-shard accumulator state device->host as a "
        "partial-aggregate Arrow table (bytes counted in "
        "rec_ckpt_bytes). On a mesh failure, the single-device "
        "fallback re-plan resumes the stream at the last checkpointed "
        "chunk cursor instead of chunk 0 (recorded as "
        "`checkpoint_restore`). 0 disables checkpointing (fallback "
        "restarts from scratch).",
    validator=lambda v: v >= 0)

MESH_RESTART_ENABLED = register(
    "spark_tpu.execution.meshRestart.enabled", True,
    doc="Gang restart (parallel/elastic.py): on a mesh/collective "
        "failure, re-execute the query still MESH-planned — up to "
        "meshRestart.maxRestarts attempts with exponential backoff — "
        "before degrading to the single-device fallback. The mesh "
        "streaming driver resumes at its last checkpoint "
        "(checkpoint.everyChunks), so a host lost mid-stream replays "
        "at most one checkpoint interval ON the mesh. Restarts are "
        "recorded as `mesh_restart` actions (mesh_restart_attempts "
        "counter); disabled, mesh failure degrades straight to "
        "single-device (the pre-elastic PR-5 behavior).")

MESH_RESTART_MAX = register(
    "spark_tpu.execution.meshRestart.maxRestarts", 2,
    doc="Gang-restart budget per query execution: mesh failures past "
        "it fall through to the single-device fallback rung. Backoff "
        "follows spark_tpu.execution.backoffMs (exponential, "
        "jittered).",
    validator=lambda v: v >= 0)

DECOMMISSION_SHARDS = register(
    "spark_tpu.execution.decommission.shards", "",
    doc="Graceful-decommission drain request (comma-separated mesh "
        "positions, e.g. '3' or '3,5'; session.decommission_shards() "
        "sets it): a running mesh stream drains at its NEXT chunk "
        "boundary — checkpoint forced at the current cursor, "
        "`decommission` recorded, the shards' devices excluded at "
        "session level (spark_tpu.sql.mesh.excludeDevices) — and the "
        "query continues on the reduced gang from the checkpoint. The "
        "BlockManagerDecommissioner analog. One-shot: cleared once "
        "applied; a request with NO position valid for the next mesh "
        "query's gang is discarded with a warning (never left armed "
        "for a future larger mesh).")

MESH_EXCLUDE_DEVICES = register(
    "spark_tpu.sql.mesh.excludeDevices", "",
    doc="Comma-separated device ids never meshed over (written by the "
        "decommission drain; settable directly to pin out a bad "
        "device). get_mesh builds the gang over the surviving pool — "
        "shrinking below mesh.size instead of failing. Limitation: "
        "a pool of <= 1 survivors degrades to the SINGLE-CHIP path, "
        "which places on the process's JAX default device and does "
        "not consult this list — excluding the default device itself "
        "requires restarting with JAX visible-device flags.")

STRAGGLER_REBALANCE_ENABLED = register(
    "spark_tpu.sql.straggler.rebalance.enabled", True,
    doc="Straggler mitigation (parallel/elastic.py): when the "
        "StragglerMonitor flags a shard mid-stream, re-assign "
        "subsequent chunks' rows away from it — the flagged shard's "
        "live-row share drops by straggler.rebalance.maxSkew, spread "
        "over the healthy shards. Partial aggregation is "
        "row-assignment independent: integer/decimal results are "
        "bit-exact; float sums may move in the last ulp (summation "
        "order), as with any mesh-size change. Recorded as "
        "`shard_rebalance` with the rebalance_rows counter.")

STRAGGLER_REBALANCE_MAX_SKEW = register(
    "spark_tpu.sql.straggler.rebalance.maxSkew", 0.5,
    doc="How much of a flagged shard's fair row share the rebalancer "
        "may shift to healthy shards (0.5 = the straggler steps over "
        "half its fair share). Bounds the skew so one bad detection "
        "cannot starve a shard entirely; 0 disables movement.",
    validator=lambda v: 0.0 <= v < 1.0,
    type_=float)

STRAGGLER_REBALANCE_DECAY_CHUNKS = register(
    "spark_tpu.sql.straggler.rebalance.decayChunks", 0,
    doc="Straggler rebalance weight DECAY: a flagged shard's skew "
        "penalty fades linearly back to zero over this many healthy "
        "chunks after the flag, so a recovered shard earns its fair "
        "row share back instead of staying penalized for the rest of "
        "the stream. Chunk-shape capacity stays sized for the "
        "full-penalty trajectory (static shapes never re-specialize "
        "mid-decay); when every penalty reaches zero the zero-cost "
        "unflagged path resumes. A re-flag mid-decay resets that "
        "shard's penalty to full. 0 keeps the legacy behavior "
        "(penalized until the stream ends).",
    validator=lambda v: v >= 0)

MESH_FALLBACK_ENABLED = register(
    "spark_tpu.execution.meshFallback.enabled", True,
    doc="When a distributed run fails inside the mesh/collective path "
        "(shard_map, all_to_all/all_gather lowering), re-plan the query "
        "single-device and retry instead of failing — the degraded-mode "
        "analog of the reference rescheduling tasks off a lost "
        "executor. The fallback is recorded as a `mesh_fallback` metric "
        "and in the event log's fault_summary.")

OOM_SPILL_ENABLED = register(
    "spark_tpu.execution.oom.spillOnExhausted", True,
    doc="Rung 2 of the RESOURCE_EXHAUSTED degradation ladder: after a "
        "device-cache eviction retry still OOMs, re-route the query "
        "through the host-spill chunked paths (execution/external.py / "
        "streaming partial spill) by re-planning under a 1-byte device "
        "budget. Disabled, the ladder goes straight from eviction to "
        "the diagnostic raise.")

FAULT_INJECT = register(
    "spark_tpu.faults.inject", "",
    doc="Deterministic fault injection for chaos testing "
        "(spark_tpu/testing/faults.py): comma-separated "
        "`site:fault:nth[:arg]` rules, e.g. "
        "'shuffle:resource_exhausted:2,join_build:unavailable:1' raises "
        "a synthetic RESOURCE_EXHAUSTED on the 2nd shuffle lowering and "
        "a synthetic UNAVAILABLE on the 1st join build. Each rule fires "
        "once. Empty disables (zero overhead).")

SKEW_JOIN_ENABLED = register(
    "spark_tpu.sql.adaptive.skewJoin.enabled", True,
    doc="When a shuffle join's exchange overflows with one receive "
        "bucket holding more than skewJoin.factor x the mean rows per "
        "shard, re-plan the join as broadcast (all_gather the build "
        "side) instead of growing buckets — no exchange, no skew. The "
        "OptimizeSkewedJoin.scala:56 + DynamicJoinSelection.scala:1 "
        "analog, expressed as strategy re-planning rather than "
        "partition splitting (static SPMD shapes make the broadcast "
        "form strictly simpler).")

SKEW_JOIN_FACTOR = register(
    "spark_tpu.sql.adaptive.skewJoin.factor", 4.0,
    doc="Skew threshold: max-bucket rows / (total rows / shards) above "
        "which a shuffle join re-plans (skewJoin.enabled).")

SKEW_BROADCAST_BYTES = register(
    "spark_tpu.sql.adaptive.skewJoin.broadcastThreshold", 256 << 20,
    doc="Max measured build-side bytes for the skew-triggered broadcast "
        "re-plan (larger than autoBroadcastJoinThreshold: paying a "
        "bigger all_gather beats an unboundedly skewed exchange).")

WAREHOUSE_DIR = register(
    "spark_tpu.sql.warehouse.dir", "spark-warehouse",
    doc="Directory for persistent tables (CREATE TABLE / INSERT INTO): "
        "one subdirectory of parquet parts + a JSON metadata sidecar per "
        "table. The metastore seat of SessionCatalog.scala:1, minus the "
        "Hive process: a fresh session over the same dir sees every "
        "table.")

DEVICE_MEMORY_BUDGET = register(
    "spark_tpu.sql.memory.deviceBudget", 0,
    doc="Device (HBM) byte budget for a single query's resident working "
        "set. Scans whose estimated post-prune footprint exceeds it are "
        "executed out-of-core: chunked through device-resident build "
        "sides with partial-aggregate spill to host Arrow buffers (the "
        "UnsafeExternalSorter.java / ExternalAppendOnlyMap.scala:55 "
        "analog — host RAM plays the role of executor disk). 0 = "
        "unbounded (whole-input residency).")

DEVICE_CACHE_BYTES = register(
    "spark_tpu.sql.io.deviceCacheBytes", 6 << 30,
    doc="Byte budget for the device-resident table cache: loaded scans "
        "(post column-prune/filter-pushdown) stay in HBM and are reused "
        "across queries, LRU-evicted past the budget. 0 disables. The "
        "storage-memory-pool analog of UnifiedMemoryManager.scala:49 + "
        "CacheManager.scala.")

RUNTIME_FILTER_ENABLED = register(
    "spark_tpu.sql.runtimeFilter.enabled", True,
    doc="Inject runtime join filters: when a join's build side is "
        "selective, build a device Bloom filter (+ min/max key bounds "
        "for ordered keys) from the build-side join keys in-stage and "
        "prune probe rows BELOW the probe-side exchange, so pruned rows "
        "never cross ICI. The InjectRuntimeFilter.scala:1 / "
        "spark.sql.optimizer.runtime.bloomFilter.enabled analog. "
        "Results are identical on/off; only row movement changes.")

RUNTIME_FILTER_CREATION_THRESHOLD = register(
    "spark_tpu.sql.runtimeFilter.creationSideThreshold", 256 << 20,
    doc="Max estimated creation-side bytes (rows x 8 x columns, "
        "pre-filter upper bound) for building a runtime filter; larger "
        "build sides skip injection — re-computing the creation chain "
        "plus the Bloom build must stay cheap relative to the probe "
        "exchange it prunes. The bloomFilter.creationSideThreshold "
        "analog.")

RUNTIME_FILTER_SEMI_AWARE = register(
    "spark_tpu.sql.runtimeFilter.semiAwareCreation", True,
    doc="When a creation-side descent passes through an equi-join whose "
        "OTHER side is selective and cheap to recompute, synthesize a "
        "left-semi join in the creation chain instead of dropping the "
        "other side's effect (Q5: customer inherits the nation-region "
        "semi, so ~4/5 of customers never enter the filter). The "
        "synthesized semi only ever NARROWS the creation keys toward "
        "the true build keys — pruning stays sound, it just prunes "
        "more. Single-chip only: under a mesh the creation scans are "
        "sharded, and a per-shard semi could drop keys whose partner "
        "rows live on another shard.")

RUNTIME_FILTER_FPP = register(
    "spark_tpu.sql.runtimeFilter.expectedFpp", 0.03,
    doc="Expected false-positive probability for runtime-filter Bloom "
        "sketches (sizing follows BloomFilter.optimalNumOfBits). False "
        "positives only reduce pruning, never correctness.",
    validator=lambda v: 0.0 < v < 1.0)

CBO_JOIN_REORDER = register(
    "spark_tpu.sql.cbo.joinReorder", True,
    doc="Cost-based join reorder (plan/join_reorder.py, the "
        "CostBasedJoinReorder.scala analog): re-sequence maximal "
        "regions of inner equi-joins by estimated cost — source row "
        "counts x filter selectivities (Parquet-footer min/max "
        "interpolation for ranges when stats.parquetFooter is on), "
        "left-deep DP minimizing the sum of intermediate sizes. "
        "Results are identical on/off (only join order changes); off "
        "restores the frontend order. Decisions land in the event "
        "log's `reorder` records and explain(); per-join estimates "
        "are graded by history.prediction_report (basis cbo-reorder).")

CBO_MAX_RELATIONS = register(
    "spark_tpu.sql.cbo.maxReorderRelations", 8,
    doc="Upper bound on relations per reordered join region: the "
        "left-deep DP enumerates connected subsets (2^n states), so "
        "larger regions keep the frontend order. The "
        "spark.sql.cbo.joinReorder.dp.threshold seat.",
    validator=lambda v: 2 <= v <= 14)

STATS_PARQUET_FOOTER = register(
    "spark_tpu.sql.stats.parquetFooter", True,
    doc="Read per-column min/max (and row-group counts) from Parquet "
        "footers (io/sources.py column_stats), cached per source. "
        "Consumers: the reorder cost model's range selectivities and "
        "the analyzer's SUM_I64_OVERFLOW magnitude bounds (a column "
        "whose footer max is small cannot overflow an int64 "
        "accumulator at any plausible row count). Reading footers "
        "touches no row data.")

ADAPTIVE_ENABLED = register(
    "spark_tpu.sql.adaptive.enabled", True,
    doc="Enable the stats->re-jit retry loop for join/exchange/aggregate "
        "capacity overflows (analog of spark.sql.adaptive.enabled). "
        "Disabled, an overflow raises instead of re-planning.")

CASE_SENSITIVE = register(
    "spark_tpu.sql.caseSensitive", False,
    doc="Whether column resolution is case sensitive (analog of "
        "spark.sql.caseSensitive).")

# NOTE: no ANSI mode entry — ANSI error semantics (overflow/invalid-cast
# errors instead of NULLs) are not implemented; registering a flag that
# silently does nothing would be worse than absent (round-2 ADVICE).

METRICS_ENABLED = register(
    "spark_tpu.sql.metrics.enabled", True,
    doc="Record per-operator output row counts during execution "
        "(surfaced by explain(runtime=True); analog of SQLMetrics).")

PROFILE_DIR = register(
    "spark_tpu.sql.profile.dir", "",
    doc="When set, wrap query execution in a jax.profiler trace written "
        "to this directory (one trace per execute).")

EVENT_LOG_DIR = register(
    "spark_tpu.sql.eventLog.dir", "",
    doc="When set, append one JSON line per query execution (plan "
        "fingerprint, phase timings, per-operator metrics, spans, XLA "
        "stage costs, fault summary) to <dir>/app-<session>.jsonl — "
        "the EventLoggingListener analog; read back with "
        "spark_tpu.history.read_event_log.")

EVENT_LOG_MAX_BYTES = register(
    "spark_tpu.sql.eventLog.maxBytes", 0,
    doc="Event-log rotation threshold: when the live app-<session>.jsonl "
        "reaches this size, it rolls to app-<session>.N.jsonl and a "
        "fresh live file starts (read_event_log replays rolled files in "
        "N order). 0 disables rotation (unbounded file, the reference's "
        "spark.eventLog.rolling.enabled=false default).")

TRACE_DIR = register(
    "spark_tpu.sql.trace.dir", "",
    doc="When set, write one Chrome-trace-event JSON per query "
        "execution (<dir>/query-<session>-<id>.trace.json) covering the "
        "per-stage spans: analysis -> optimize -> plan -> compile -> "
        "ingest -> dispatch -> AQE-replan -> retry. Load in Perfetto "
        "or chrome://tracing.")

METRICS_SINK = register(
    "spark_tpu.sql.metrics.sink", "",
    doc="Comma-separated metrics sinks flushed at every query end: "
        "'jsonl' (snapshot lines appended to metrics.jsonl) and/or "
        "'prometheus' (text exposition atomically rewritten to "
        "metrics.prom, scrapeable via a textfile collector). Empty "
        "disables. The MetricsSystem/sink-configuration analog.",
    validator=lambda v: all(
        s.strip() in ("jsonl", "prometheus")
        for s in str(v).split(",") if s.strip()))

METRICS_DIR = register(
    "spark_tpu.sql.metrics.dir", "spark-metrics",
    doc="Output directory for the metrics sinks "
        "(spark_tpu.sql.metrics.sink).")

XLA_COST_MODE = register(
    "spark_tpu.sql.observability.xlaCost", "auto",
    doc="Capture XLA cost_analysis()/memory_analysis() (flops, bytes "
        "accessed, argument/output/temp sizes, derived peak-HBM demand) "
        "per compiled stage, memoized per stage key. Capture pays a "
        "second XLA compile of the stage (the jit and AOT paths don't "
        "share executables), hence the gate: 'auto' captures only when "
        "an observability output is configured (eventLog.dir, "
        "trace.dir, metrics.sink) or the OOM ladder is descending (so "
        "the rung-3 diagnostic can cite measured HBM demand); 'on' "
        "always; 'off' never.",
    validator=lambda v: v in ("auto", "on", "off"))

MAX_SPANS = register(
    "spark_tpu.sql.observability.maxSpans", 1000,
    doc="Per-query bound on recorded lifecycle spans (a pathological "
        "retry loop must not grow the trace unboundedly; the recorder "
        "counts what it drops).")

SHARD_SPANS = register(
    "spark_tpu.sql.observability.shardSpans", "auto",
    doc="Per-shard telemetry for mesh runs (observability/spans.py "
        "ShardStreamTelemetry): the mesh chunk drivers buffer "
        "device-side per-shard row counts and flush them at chunk "
        "boundaries into per-(shard, chunk) timing + bytes records "
        "(shard id, host, ingest/compute/transfer phases) — no "
        "host-sync on the hot path. Records land in the event log "
        "('shards', schema v3), feed the StragglerMonitor and the "
        "history.shard_summary()/straggler_report() views. 'auto' "
        "records only when an observability output or a user listener "
        "is active; 'on' always; 'off' never.",
    validator=lambda v: v in ("auto", "on", "off"))

MAX_SHARD_RECORDS = register(
    "spark_tpu.sql.observability.maxShardRecords", 4096,
    doc="Per-query bound on buffered per-shard telemetry records (a "
        "long mesh stream over many chunks must not grow the event "
        "line unboundedly; the recorder counts what it drops).",
    validator=lambda v: v >= 0)

STRAGGLER_FACTOR = register(
    "spark_tpu.sql.straggler.factor", 3.0,
    doc="Straggler detection threshold for the StragglerMonitor "
        "(observability/straggler.py): a shard whose rolling median "
        "per-chunk latency exceeds factor x the median of all shards' "
        "medians is flagged (straggler_flagged counter + on_straggler "
        "listener event). The speculation-threshold seat of "
        "spark.speculation.multiplier — detection only; chunk-range "
        "rebalancing is the elastic-mesh follow-on. <= 0 disables "
        "detection.",
    type_=float)

STRAGGLER_MIN_CHUNKS = register(
    "spark_tpu.sql.straggler.minChunks", 4,
    doc="Minimum per-shard chunk-latency samples before the "
        "StragglerMonitor may flag a shard (spark.speculation.quantile "
        "seat: early chunks are compile/warmup-noisy).",
    validator=lambda v: v >= 1)

STRAGGLER_MIN_LATENCY_MS = register(
    "spark_tpu.sql.straggler.minLatencyMs", 10.0,
    doc="Noise floor for straggler flagging: a shard is only flagged "
        "when its median per-chunk wait is at least this many "
        "milliseconds — near-zero medians (every shard keeping up) "
        "must not flag on ratio alone.",
    validator=lambda v: v >= 0)

ANALYSIS_ENABLED = register(
    "spark_tpu.sql.analysis.enabled", True,
    doc="Run the pre-compile static analyzer (spark_tpu/analysis/): "
        "after planning and before stage compile, walk the physical "
        "plan for dtype-overflow, host-sync, recompile, mesh and x64 "
        "hazards and emit typed findings (listener bus on_analysis -> "
        "event log; explain(analysis=True)). The plan walk is a pure "
        "host-side tree traversal (microseconds); findings never "
        "change results.")

ANALYSIS_STRICT = register(
    "spark_tpu.sql.analysis.strict", False,
    doc="Fail fast on analysis: raise a structured AnalysisFindingError "
        "BEFORE compiling/dispatching any stage when the analyzer "
        "produced error-severity findings (accumulator overflow, x64 "
        "truncation) — the CheckAnalysis seat. Warn/info findings "
        "never raise.")

ANALYSIS_JAXPR = register(
    "spark_tpu.sql.analysis.jaxpr", "auto",
    doc="Jaxpr half of the analyzer: abstractly evaluate the stage "
        "callable (jax.make_jaxpr, no XLA compile) and scan the "
        "equation graph for all_gather replication, host callbacks and "
        "int32 accumulators. Costs one extra trace per unique stage "
        "key (memoized): 'auto' traces only when an observability "
        "output is configured (eventLog.dir / trace.dir / "
        "metrics.sink) or analysis.strict is on; 'on' always; 'off' "
        "never.",
    validator=lambda v: v in ("auto", "on", "off"))

PLAN_VALIDATION = register(
    "spark_tpu.sql.planChangeValidation", _os.environ.get(
        "SPARK_TPU_PLAN_VALIDATION", "off"),
    doc="Verify plan integrity after every effective optimizer-rule "
        "application (analysis/plan_integrity.py; the reference's "
        "spark.sql.planChangeValidation + LogicalPlanIntegrity): "
        "column-reference resolution with unique origins, output-schema "
        "preservation against the Rule.schema_preserving contract, "
        "duplicate output names, aggregate coherence, join-key dtype "
        "compatibility, and per-batch determinism (a replay over a "
        "cloned input must reproduce the plan). 'full' raises a typed "
        "PlanIntegrityError naming the rule/batch/node; 'lite' surfaces "
        "PLAN_INTEGRITY findings through the analyzer flow instead; "
        "'off' skips verification. The default honors the "
        "SPARK_TPU_PLAN_VALIDATION environment variable (the test "
        "suite pins it to 'full').",
    validator=lambda v: v in ("off", "lite", "full"))

PLAN_CHANGE_LOG = register(
    "spark_tpu.sql.planChangeLog", False,
    doc="Capture a unified before/after tree diff of each rule's first "
        "effective application into the rule_trace records "
        "(analysis/plan_integrity.py PlanChangeTracer; the reference's "
        "spark.sql.planChangeLog.level). Off keeps rule_trace to "
        "per-rule counters/timings only.")

OPTIMIZER_EXCLUDED_RULES = register(
    "spark_tpu.sql.optimizer.excludedRules", "",
    doc="Comma-separated optimizer rule names to skip (the reference's "
        "spark.sql.optimizer.excludedRules); '*' disables every rule. "
        "The differential plan fuzzer (testing/plan_fuzz.py) uses this "
        "as its optimizer-off baseline and per-rule ablation lever.")

FUZZ_SEEDS = register(
    "spark_tpu.sql.fuzz.seeds", 64,
    doc="Default seed count for the differential plan fuzzer "
        "(scripts/plan_fuzz.py): each seed generates one random "
        "table set + query and runs it optimizer-on vs -off vs "
        "per-rule-ablated.",
    validator=lambda v: v > 0)

FUZZ_MAX_ROWS = register(
    "spark_tpu.sql.fuzz.maxRows", 40,
    doc="Max rows per generated fuzz table (testing/plan_fuzz.py); "
        "small tables keep the 500-seed CPU campaign tractable while "
        "still covering nulls, NaN/-0.0 floats, decimals and "
        "dictionary strings.",
    validator=lambda v: v > 0)

CHECKPOINT_DIR = register(
    "spark_tpu.sql.checkpoint.dir", "",
    doc="Directory for df.checkpoint(): when set, checkpoints write "
        "Parquet (survive the process, ReliableCheckpointRDD analog); "
        "otherwise they materialize in memory (localCheckpoint).")

CLUSTER_COORDINATOR = register(
    "spark_tpu.sql.cluster.coordinator", "",
    doc="host:port of the jax.distributed coordinator for multi-host "
        "meshes (empty = single host). Every host runs the same engine "
        "process; parallel.mesh.init_distributed dials in.")

CLUSTER_NUM_PROCESSES = register(
    "spark_tpu.sql.cluster.numProcesses", 1,
    doc="Number of engine processes (hosts) in the multi-host cluster.")

CLUSTER_PROCESS_ID = register(
    "spark_tpu.sql.cluster.processId", 0,
    doc="This process's rank within the multi-host cluster.")

SERVICE_MAX_CONCURRENT = register(
    "spark_tpu.service.maxConcurrent", 2,
    doc="Admission control: maximum queries executing simultaneously in "
        "the SQL service (spark_tpu/service/). Further submissions queue "
        "up to service.queueDepth, then reject with a structured "
        "ADMISSION_REJECTED error. The "
        "hive-thriftserver async-pool-size seat.",
    validator=lambda v: v >= 1)

SERVICE_QUEUE_DEPTH = register(
    "spark_tpu.service.queueDepth", 16,
    doc="Admission control: maximum queries waiting for an execution "
        "slot. A submission arriving with the queue full is rejected "
        "immediately (HTTP 429 / AdmissionRejected) instead of growing "
        "an unbounded backlog.",
    validator=lambda v: v >= 0)

SERVICE_QUEUE_TIMEOUT_MS = register(
    "spark_tpu.service.queueTimeoutMs", 30000,
    doc="Admission control: maximum milliseconds a queued query waits "
        "for an execution slot before failing with a structured "
        "ADMISSION_TIMEOUT error. 0 waits forever.",
    validator=lambda v: v >= 0)

SERVICE_HOST = register(
    "spark_tpu.service.host", "127.0.0.1",
    doc="Bind address for the SQL service HTTP endpoint "
        "(spark_tpu/service/server.py).")

SERVICE_PORT = register(
    "spark_tpu.service.port", 0,
    doc="Bind port for the SQL service HTTP endpoint. 0 picks an "
        "ephemeral port (exposed as SqlService.port after start).")

SERVICE_HBM_BUDGET = register(
    "spark_tpu.service.hbmBudget", 0,
    doc="Shared device (HBM) byte budget the cross-query resource "
        "arbiter (service/arbiter.py) hands out as per-scan residency "
        "leases across ALL concurrent queries — the "
        "UnifiedMemoryManager.scala:49 analog of one pool shared by "
        "every task, replacing the per-query "
        "spark_tpu.sql.memory.deviceBudget read. A query whose scan "
        "cannot lease its estimated footprint takes the out-of-core "
        "spill/streaming paths instead of crashing; lease pressure "
        "first evicts the device table cache (storage pool). 0 "
        "disables the arbiter (legacy per-query budget semantics). "
        "An explicitly-set per-query deviceBudget (the OOM ladder's "
        "rung-2 overlay) still takes precedence.")

SERVICE_RESULT_CACHE_BYTES = register(
    "spark_tpu.service.resultCacheBytes", 256 << 20,
    doc="Byte bound for the plan-fingerprint result cache (the "
        "CacheManager/InMemoryRelation seat): materialized Arrow tables "
        "for cache()-marked plans, LRU-evicted past the bound. The "
        "service promotes this to ONE arbiter-owned cache shared by "
        "every pooled session. Standalone sessions keep an unbounded "
        "private cache (the pre-service behavior) unless this key is "
        "explicitly set. 0 disables bounding.")

SERVICE_MAX_SESSIONS = register(
    "spark_tpu.service.maxSessions", 16,
    doc="Maximum pooled sessions the SQL service keeps (one per "
        "distinct `session` name in POST /sql). A request naming a new "
        "session past the bound is rejected with a structured error.",
    validator=lambda v: v >= 1)

SERVICE_SESSION_MAX_CONCURRENT = register(
    "spark_tpu.service.session.maxConcurrent", 0,
    doc="Per-session admission quota: maximum in-flight submissions "
        "(running + waiting, sync and async) a single session name may "
        "hold at once. Exceeding it rejects with a structured "
        "SESSION_QUOTA_EXCEEDED error (HTTP 429) and counts "
        "session_quota_rejections — one greedy session cannot consume "
        "every admission-queue slot and starve the pool. 0 disables "
        "(service-wide maxConcurrent/queueDepth still bound totals).",
    validator=lambda v: v >= 0)

SERVICE_SESSION_HBM_SHARE = register(
    "spark_tpu.service.session.hbmShare", 0.0,
    doc="Per-session share of the service.hbmBudget arbiter pool "
        "(fraction, 0 < share <= 1): one session's residency leases "
        "may not exceed share * hbmBudget in total. A scan whose lease "
        "would push its session past the share is DENIED immediately "
        "(counted in session_quota_rejections) and takes the "
        "out-of-core spill/streaming paths — degraded, never starved, "
        "and the rest of the pool stays available to other sessions. "
        "0 disables the share cap.",
    validator=lambda v: 0 <= v <= 1)

SERVICE_ID_PREFIX = register(
    "spark_tpu.service.idPrefix", "",
    doc="Namespace prefix for service query ids (q-<prefix><seq>). "
        "Empty for a standalone service; the fleet supervisor "
        "(service/fleet.py) sets 'w<idx>g<gen>-' per worker so the "
        "router can map an id back to the worker (and generation) "
        "that owns its record.")

FLEET_WORKERS = register(
    "spark_tpu.service.fleet.workers", 2,
    doc="Number of SqlService worker subprocesses the fleet "
        "supervisor (service/fleet.py) runs. Each worker binds an "
        "ephemeral port and shares the persistent compile-cache dir, "
        "so a respawned worker opens hot.",
    validator=lambda v: v >= 1)

FLEET_RESTART_MAX_PER_WINDOW = register(
    "spark_tpu.service.fleet.restartMaxPerWindow", 3,
    doc="Flap breaker: a worker crashing this many times within "
        "fleet.restartWindowMs is QUARANTINED — no further restarts, "
        "its ring share re-homes to the surviving workers and excess "
        "load sheds through their admission 429/503 bounds (graceful "
        "degradation, never a hang).",
    validator=lambda v: v >= 1)

FLEET_RESTART_WINDOW_MS = register(
    "spark_tpu.service.fleet.restartWindowMs", 60000,
    doc="Flap-breaker crash-counting window (milliseconds) for "
        "fleet.restartMaxPerWindow.",
    validator=lambda v: v >= 1)

FLEET_RESTART_BACKOFF_MS = register(
    "spark_tpu.service.fleet.restartBackoffMs", 200,
    doc="Base delay of the worker-restart exponential-backoff ladder "
        "(the execution RetryPolicy reused supervisor-side): crash n "
        "within a window waits ~backoff * 2^n (jittered) before the "
        "respawn.",
    validator=lambda v: v >= 0)

FLEET_DRAIN_TIMEOUT_MS = register(
    "spark_tpu.service.fleet.drainTimeoutMs", 10000,
    doc="Bounded drain budget (milliseconds): on SIGTERM the "
        "supervisor stops admitting (structured FLEET_DRAINING 503), "
        "waits this long for in-flight proxied requests, SIGTERMs the "
        "workers (each drains its own in-flight queries under the "
        "same bound, on top of their queryDeadlineMs budgets), then "
        "SIGKILLs stragglers and exits 0. Also the default budget of "
        "SqlService.drain().",
    validator=lambda v: v >= 0)

FLEET_FAILOVER_READS = register(
    "spark_tpu.service.fleet.failoverReads", True,
    doc="Transparently retry an idempotent read query (SELECT / WITH "
        "/ VALUES / EXPLAIN / SHOW / DESCRIBE) exactly once on the "
        "re-homed worker when its worker dies mid-request — byte "
        "parity is guaranteed by the deterministic engine + shared "
        "compile cache. Off (and for every non-read), the client gets "
        "a structured 503 WORKER_LOST instead.")

FLEET_HEALTH_INTERVAL_MS = register(
    "spark_tpu.service.fleet.healthIntervalMs", 250,
    doc="Supervisor health-check cadence (milliseconds): each tick "
        "polls worker liveness (subprocess exit + HTTP ping) and "
        "readiness (GET /healthz/ready — warm-start replay done), "
        "re-homes traffic off non-ready workers, and runs the "
        "restart ladder for due respawns.",
    validator=lambda v: v >= 10)

FLEET_SPAWN_TIMEOUT_MS = register(
    "spark_tpu.service.fleet.spawnTimeoutMs", 90000,
    doc="Budget (milliseconds) for a spawned worker to hand its port "
        "back and report ready; a worker exceeding it is killed and "
        "counts as a crash in the flap-breaker window.",
    validator=lambda v: v >= 1)

FLEET_PROXY_TIMEOUT_MS = register(
    "spark_tpu.service.fleet.proxyTimeoutMs", 600000,
    doc="Socket timeout (milliseconds) on one proxied worker request; "
        "queries bound their own wall-clock via queryDeadlineMs, so "
        "this is the backstop against a wedged worker socket.",
    validator=lambda v: v >= 1)

FLEET_DIR = register(
    "spark_tpu.service.fleet.dir", "",
    doc="Directory for fleet runtime artifacts: worker-death "
        "diagnostic bundles (MANIFEST.json + stderr tail + restart "
        "history per bundle-worker<idx>-g<gen>-<reason>/). Empty uses "
        "<tmpdir>/spark-tpu-fleet.")

FLEET_INIT = register(
    "spark_tpu.service.fleet.init", "",
    doc="Worker session-init hook as an import spec "
        "('module:function'); each worker resolves it and passes the "
        "callable to SqlService(init_session=...) — table "
        "registration must survive respawn, so it ships as a spec, "
        "not a closure. Empty for no init hook.")

SERVICE_QUERY_LOG_SIZE = register(
    "spark_tpu.service.queryLogSize", 512,
    doc="Bound on the service's in-memory query status registry "
        "(GET /queries/<id> and the GET /queries listing): oldest "
        "finished records are dropped past it.",
    validator=lambda v: v >= 1)

STATUS_ENABLED = register(
    "spark_tpu.sql.status.enabled", True,
    doc="Feed the engine status store: record end-to-end and per-phase "
        "query latency histograms (status_latency_ms / "
        "status_phase_ms_*) and SLO burn counters at every query end, "
        "and let the service's status heartbeat sample health gauges "
        "into its ring time-series (GET /status, /status/timeseries). "
        "Off silences the recording, not the endpoints (they serve "
        "whatever was recorded).")

STATUS_HEARTBEAT_MS = register(
    "spark_tpu.sql.status.heartbeatMs", 1000,
    doc="Interval of the status store's heartbeat thread (the "
        "Heartbeater analog): every tick samples queries in flight, "
        "admission queue depth, arbiter lease occupancy, cache hit "
        "rates, streaming lag and UDF pool size into the fixed-"
        "capacity ring time-series behind GET /status/timeseries.",
    validator=lambda v: v >= 10)

STATUS_RING_SIZE = register(
    "spark_tpu.sql.status.ringSize", 360,
    doc="Capacity of each status-store ring time-series (oldest "
        "samples drop past it); 360 x the 1s default heartbeat = a "
        "rolling 6-minute window per series.",
    validator=lambda v: v >= 2)

SERVICE_SLO_LATENCY_MS = register(
    "spark_tpu.service.slo.latencyMs", 0,
    doc="End-to-end query latency SLO target in ms. When > 0, every "
        "query end counts slo_queries_total and a query slower than "
        "the target burns slo_burned_total / slo_burn_ms_total — the "
        "counters a fleet router sheds on. 0 disables burn counting "
        "(the latency histograms record regardless).",
    validator=lambda v: v >= 0)

FLIGHTREC_ENABLED = register(
    "spark_tpu.sql.flightRecorder.enabled", True,
    doc="Keep the always-on flight recorder ring (recent events/spans/"
        "fault records per subsystem, bounded, near-zero hot-path "
        "cost) and dump a diagnostic bundle on FATAL errors, OOM-"
        "ladder exhaustion, non-convergent recovery, or on demand "
        "(GET /debug/bundle). Off disables both ring and dumps.")

FLIGHTREC_DIR = register(
    "spark_tpu.sql.flightRecorder.dir", "",
    doc="Directory diagnostic bundles are dumped under (one versioned "
        "bundle-<app>-<n>-<reason>/ per dump). Empty uses "
        "<tmpdir>/spark-tpu-flightrec.")

FLIGHTREC_RING_SIZE = register(
    "spark_tpu.sql.flightRecorder.ringSize", 256,
    doc="Per-subsystem bound on flight-recorder ring records (oldest "
        "drop past it).",
    validator=lambda v: v >= 8)

FLIGHTREC_EVENT_TAIL = register(
    "spark_tpu.sql.flightRecorder.eventLogTail", 200,
    doc="How many trailing event-log lines a diagnostic bundle "
        "includes (when eventLog.dir is set).",
    validator=lambda v: v >= 0)

SERVICE_HISTORY_SIZE = register(
    "spark_tpu.service.historySize", 128,
    doc="Bound on the service's in-memory per-query detail store "
        "(QueryHistoryStore, fed by the listener bus at query end): "
        "spans, stage XLA costs, per-shard records and the runtime "
        "plan tree behind GET /queries/<id>/{timeline,plan}. Detail "
        "records are much heavier than status records, hence the "
        "separate (smaller) bound; oldest entries drop past it.",
    validator=lambda v: v >= 1)

STREAMING_SNAPSHOT_EVERY = register(
    "spark_tpu.streaming.stateStore.snapshotEveryDeltas", 10,
    doc="Incremental streaming state store "
        "(execution/state_store.py): write a FULL state snapshot "
        "every N versions; the versions between persist as deltas "
        "(only the groups whose accumulators changed that batch). "
        "Restore = newest snapshot <= the committed version + replay "
        "of at most N-1 deltas. 1 snapshots every version (the "
        "pre-incremental behavior).",
    validator=lambda v: v >= 1)

STREAMING_RETAIN = register(
    "spark_tpu.streaming.retainBatches", 2,
    doc="Streaming checkpoint retention window (the "
        "minBatchesToRetain seat): offset/commit log entries and "
        "state files needed only by versions older than "
        "committed - retain are compacted away. Recovery reads only "
        "the last committed version; the window exists so a torn "
        "newest log entry can fall back one version.",
    validator=lambda v: v >= 1)

STREAMING_FILE_STRICT = register(
    "spark_tpu.streaming.source.file.strict", False,
    doc="File stream source corrupt-file policy: by default a file "
        "that fails to decode (torn write, wrong schema, not the "
        "source's format) is QUARANTINED — marked in the source's "
        "seen-file log, counted in streaming_files_quarantined, "
        "skipped by the batch and by every replay — so one bad file "
        "cannot wedge the stream. true fails the batch instead "
        "(at-least-once delivery of every file byte wins over "
        "availability).")

STREAMING_NET_MAX_RECONNECTS = register(
    "spark_tpu.streaming.source.network.maxReconnects", 8,
    doc="Network stream source (io/network_source.py) reconnect "
        "ladder: maximum reconnect attempts per poll after the peer "
        "dies mid-stream (EOF, reset, or a mid-frame stall), under "
        "exponential backoff + jitter (failures.RetryPolicy over "
        "source.network.backoffMs). Every successful reconnect "
        "handshakes the durable frame offset back to the producer, so "
        "the stream resumes with zero loss and zero duplication. "
        "Exhausting the ladder fails the poll with a TRANSIENT "
        "connection error for the trigger supervisor to classify.",
    validator=lambda v: v >= 0)

STREAMING_NET_CONNECT_TIMEOUT_MS = register(
    "spark_tpu.streaming.source.network.connectTimeoutMs", 2000,
    doc="Network stream source: milliseconds each socket connect "
        "attempt may take before counting as a failed "
        "reconnect-ladder rung.",
    validator=lambda v: v >= 1)

STREAMING_NET_IDLE_TIMEOUT_MS = register(
    "spark_tpu.streaming.source.network.idleTimeoutMs", 50,
    doc="Network stream source idle/stall discriminator: a read that "
        "times out while waiting for the FIRST byte of a new frame "
        "means a quiet producer — the poll returns the offsets drained "
        "so far and keeps the connection. The same timeout landing "
        "MID-frame (header or payload partially read) means a dead or "
        "wedged peer and takes the reconnect ladder instead.",
    validator=lambda v: v >= 1)

STREAMING_NET_BACKOFF_MS = register(
    "spark_tpu.streaming.source.network.backoffMs", 50,
    doc="Network stream source: base backoff milliseconds for the "
        "reconnect ladder; attempt k sleeps backoffMs * 2^k with "
        "+/-50% jitter on the interruptible lifecycle wait.",
    validator=lambda v: v >= 0)

STREAMING_TRIGGER_MAX_RESTARTS = register(
    "spark_tpu.streaming.trigger.maxRestarts", 3,
    doc="Supervised trigger loop (StreamingQuery.start): how many "
        "times a TRANSIENT batch failure may restart within one "
        "failure streak before the query parks in FAILED status. The "
        "streak resets after any successful tick; FATAL failures park "
        "immediately without consuming restarts.",
    validator=lambda v: v >= 0)

STREAMING_TRIGGER_BACKOFF_MS = register(
    "spark_tpu.streaming.trigger.backoffMs", 100,
    doc="Supervised trigger loop: base backoff milliseconds between "
        "TRANSIENT-failure restarts (exponential + jitter via "
        "failures.RetryPolicy, slept on the interruptible lifecycle "
        "wait so stop()/cancel interrupts a parked backoff "
        "immediately).",
    validator=lambda v: v >= 0)

STREAMING_STATE_SPILL_BYTES = register(
    "spark_tpu.streaming.state.spillBytes", 0,
    doc="Host-spill threshold for event-time streaming-aggregate "
        "state: when the committed keyed state exceeds this many "
        "bytes it stops being held resident between triggers and "
        "reroutes through the external keyed backend "
        "(execution/external.py SpillableKeyedState) — hash-"
        "partitioned parquet spill files under the query checkpoint; "
        "each trigger's MERGE touches only the partitions its batch's "
        "keys hash to, and only the touched partitions rewrite at "
        "adoption. Persistence is unchanged (the same delta/snapshot "
        "store commits the same full frames), so crash recovery is "
        "identical; spilled bytes count in streaming_spill_bytes. "
        "0 disables spill (state stays resident).",
    validator=lambda v: v >= 0)

STREAMING_STATE_SPILL_PARTITIONS = register(
    "spark_tpu.streaming.state.spillPartitions", 16,
    doc="Partition count for the host-spill keyed state backend: "
        "state rows hash-route by key to this many parquet spill "
        "files; a trigger rewrites only the partitions its batch's "
        "keys (or evicted windows) touch.",
    validator=lambda v: v >= 1)

COMPILE_CACHE_ENABLED = register(
    "spark_tpu.sql.compileCache.enabled", False,
    doc="Persistent cross-process AOT compile cache "
        "(execution/compile_cache.py): on an in-memory stage-cache "
        "miss, compile the stage through the AOT path, serialize the "
        "executable and write it under compileCache.dir; a later "
        "PROCESS's miss of the same (stage key, environment "
        "fingerprint, call signature) deserializes instead of "
        "compiling — a warm serving process never jits a known shape "
        "twice. Entries are atomic-rename published and a "
        "corrupt/truncated entry falls back to a fresh compile "
        "(compile_cache_corrupt), never failing the query. The "
        "CodeGenerator-cache seat, made cross-process (SURVEY §7: XLA "
        "compile time is the new Janino compile time).")

COMPILE_CACHE_DIR = register(
    "spark_tpu.sql.compileCache.dir", "spark-compile-cache",
    doc="Directory for the persistent compile cache: cc-<hash>.pkl "
        "serialized executables + manifest.jsonl (the warm-start "
        "replay log) + xla/ (JAX's native compilation cache, wired as "
        "the secondary seat when unset by the operator). Empty "
        "disables the cache even when compileCache.enabled is true.")

COMPILE_CACHE_MAX_BYTES = register(
    "spark_tpu.sql.compileCache.maxBytes", 1 << 30,
    doc="Size bound for the compile-cache directory's entry files, "
        "LRU-evicted by mtime (loads touch their entry, so hot shapes "
        "survive). The just-written entry is never its own victim. "
        "0 = unbounded.")

COMPILE_CACHE_WARM_START = register(
    "spark_tpu.sql.compileCache.warmStart", True,
    doc="SQL-service warm start: when the compile cache is enabled, "
        "SqlService.start() replays the manifest of recently-seen "
        "stage keys into the sessions-shared stage cache, so a "
        "restarted serving process opens hot (deserialization only — "
        "no compiles). session.warmup() is the explicit per-session "
        "form and ignores this flag.")

MESH_SIZE = register(
    "spark_tpu.sql.mesh.size", 0,
    doc="Number of devices on the data axis of the SPMD mesh. 0 or 1 "
        "runs single-chip; >1 shards leaves over the mesh and lowers "
        "exchanges to ICI collectives (all_to_all/all_gather/psum). "
        "The SPMD analog of spark.default.parallelism.")

UDF_MODE = register(
    "spark_tpu.sql.udf.mode", "inprocess",
    doc="Where Python UDFs evaluate. 'inprocess': the original lane — "
        "user code runs in the engine process over the whole "
        "materialized table (fast for tiny inputs; a hung or crashing "
        "UDF takes the serving process with it). 'worker': the "
        "ArrowEvalPythonExec/PythonRunner seat — input is sliced by "
        "udf.arrow.maxRecordsPerBatch and pipelined through a pool of "
        "reusable subprocess workers (udf_worker/), each batch "
        "individually retryable (udf_batch fault site), cancellable "
        "between and DURING batches, and a worker crash replays only "
        "the in-flight batch. Results are byte-identical across "
        "modes.",
    validator=lambda v: v in ("inprocess", "worker"))

UDF_MAX_RECORDS_PER_BATCH = register(
    "spark_tpu.sql.udf.arrow.maxRecordsPerBatch", 10000,
    doc="Rows per Arrow batch streamed to a UDF worker (the "
        "spark.sql.execution.arrow.maxRecordsPerBatch seat). Smaller "
        "batches mean finer retry/cancel granularity and lower "
        "per-batch replay cost; larger batches amortize pipe framing "
        "and pandas call overhead. Worker mode only.",
    validator=lambda v: v >= 1)

UDF_POOL_MAX_WORKERS = register(
    "spark_tpu.sql.udf.pool.maxWorkers", 2,
    doc="Upper bound on live UDF worker subprocesses per session pool. "
        "Checkouts beyond the bound wait (cooperatively — cancel and "
        "deadline land within ~50ms) for a checkin. Workers are "
        "reused across queries; the spawn cost (interpreter + "
        "numpy/pandas/pyarrow import, udf_worker_spawn_ms) is paid "
        "once per worker, not per query.",
    validator=lambda v: v >= 1)

UDF_BATCH_TIMEOUT_MS = register(
    "spark_tpu.sql.udf.batchTimeoutMs", 0,
    doc="Per-batch wall-clock deadline for one worker EVAL round-trip. "
        "A wedged worker (infinite loop in user code, stuck import) "
        "is killed at the deadline and the batch replays on a fresh "
        "worker under the TIMEOUT retry budget. 0 disables. Worker "
        "mode only.",
    validator=lambda v: v >= 0)

UDF_POOL_IDLE_TIMEOUT_MS = register(
    "spark_tpu.sql.udf.pool.idleTimeoutMs", 60000,
    doc="Idle reap: a pooled worker unused this long is killed at the "
        "next checkout (lazily — no reaper thread). 0 keeps idle "
        "workers forever. Dead idle workers are always reaped at "
        "checkout regardless of this bound, so a worker that died "
        "between queries never surfaces as a stale-pipe error.",
    validator=lambda v: v >= 0)

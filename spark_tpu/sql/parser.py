"""SQL frontend: recursive-descent parser + lowering to logical plans.

Covers the SELECT subset the engine executes: projections, arithmetic /
boolean / comparison expressions, CASE, BETWEEN, IN, LIKE, IS NULL,
CAST, DATE and INTERVAL literals, aggregate functions (incl. aggregates
inside arithmetic, extracted into the Aggregate node), WHERE, explicit
JOIN ... ON and TPC-H-style implicit comma joins (equi-keys are pulled
out of the WHERE conjunction), GROUP BY (names, aliases, positions),
HAVING, ORDER BY (names, positions, expressions), LIMIT, UNION ALL, and
subqueries in FROM.

The reference parses with a generated ANTLR grammar + AstBuilder
(`sql/catalyst/.../parser/SqlBase.g4`, `AstBuilder.scala`); here a Pratt
-style descent over ~20 productions is enough, and lowering happens
inline because the DataFrame-facing logical plan resolves eagerly.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import types as T
from ..expr import (Alias, AnalysisError, And, CaseWhen, Cast, Coalesce,
                    ColumnRef, DateAdd, EQ, Expression, ExtractDay,
                    ExtractMonth, ExtractYear, GE, GT, In, IsNull, LE, LT,
                    Like, Literal, Lower, Mod, NE, Neg, Not, Or, SortOrder,
                    StringLength, Substring, Trim, Upper, date_literal)
from ..expr_agg import (AggExpr, AggregateFunction, AnyValue, Avg,
                        AvgDistinct, BoolAnd, BoolOr, Corr, Count,
                        CountDistinct, CountIf, CovarPop, CovarSamp, First,
                        Kurtosis, Last, Max, Min, Skewness, StddevPop,
                        StddevSamp, Sum, SumDistinct, VariancePop,
                        VarianceSamp)
from ..plan import logical as L
from .lexer import ParseError, Token, tokenize

_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "AS", "AND", "OR", "NOT", "IN", "LIKE", "BETWEEN", "IS", "NULL",
    "CASE", "WHEN", "THEN", "ELSE", "END", "CAST", "JOIN", "INNER",
    "LEFT", "RIGHT", "FULL", "OUTER", "CROSS", "SEMI", "ANTI", "ON",
    "ASC", "DESC", "UNION", "ALL", "DISTINCT", "DATE", "INTERVAL",
    "EXTRACT", "TRUE", "FALSE", "EXISTS", "WITH", "INTERSECT", "EXCEPT",
}


@dataclass
class _Interval:
    """A parsed INTERVAL literal; only valid inside date +/- interval."""
    days: int = 0
    months: int = 0
    years: int = 0


class _AggCall(Expression):
    """Parse-time wrapper so aggregate calls can sit inside scalar
    expression trees; lowering extracts them into the Aggregate node and
    substitutes a ColumnRef (the reference does the same extraction in
    `Analyzer.ResolveAggregateFunctions`)."""

    def __init__(self, func: AggregateFunction):
        self.func = func
        self.children = ()

    def dtype(self, schema):
        return self.func.result_type(schema)

    def nullable(self, schema):
        return True

    def references(self):
        return self.func.references()

    def __repr__(self):
        return repr(self.func)


def _contains_agg(e: Expression) -> bool:
    if isinstance(e, _AggCall):
        return True
    return any(_contains_agg(c) for c in e.children)


class Parser:
    def __init__(self, text: str, session=None):
        self.text = text
        self.tokens = tokenize(text)
        self.i = 0
        self.session = session  # for session-registered UDF lookup

    # -- token helpers ------------------------------------------------------

    def peek(self, k: int = 0) -> Token:
        return self.tokens[min(self.i + k, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in words

    def eat_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.eat_kw(word):
            t = self.peek()
            raise ParseError(
                f"expected {word} at position {t.pos}, got {t.value!r}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            t = self.peek()
            raise ParseError(
                f"expected {op!r} at position {t.pos}, got {t.value!r}")

    # -- statements ---------------------------------------------------------

    def parse_statement(self) -> "_Select":
        ctes = []
        if self.at_kw("WITH"):
            self.next()
            while True:
                name = self._ident()
                col_aliases = None
                if self.at_op("("):
                    self.next()
                    col_aliases = [self._ident()]
                    while self.eat_op(","):
                        col_aliases.append(self._ident())
                    self.expect_op(")")
                self.expect_kw("AS")
                self.expect_op("(")
                body = self.parse_query_expr()
                self.expect_op(")")
                ctes.append((name, col_aliases, body))
                if not self.eat_op(","):
                    break
        sel = self.parse_query_expr()
        sel.ctes = ctes
        self.eat_op(";")
        t = self.peek()
        if t.kind != "eof":
            raise ParseError(f"unexpected trailing input at {t.pos}: "
                             f"{t.value!r}")
        return sel

    def parse_query_expr(self) -> "_Select":
        """select [UNION ALL select]... — the query-expression body used
        at top level AND inside CTE bodies/subqueries, so set operations
        work in every position."""
        def combine(left, right, kind):
            # a trailing ORDER BY / LIMIT binds to the WHOLE set
            # operation, not the right arm (standard SQL precedence)
            out = _Select(union_of=(left, right), set_op=kind,
                          order_by=right.order_by, limit=right.limit)
            right.order_by = None
            right.limit = None
            return out

        def intersect_term():
            # INTERSECT binds tighter than UNION/EXCEPT (standard SQL)
            t = self.parse_select()
            while self.at_kw("INTERSECT"):
                self.next()
                self.eat_kw("DISTINCT")
                t = combine(t, self.parse_select(), "intersect")
            return t

        sel = intersect_term()
        while self.at_kw("UNION", "EXCEPT"):
            op = self.next().upper
            if op == "UNION":
                if not self.eat_kw("ALL"):
                    raise ParseError("only UNION ALL is supported (UNION "
                                     "DISTINCT needs dropDuplicates)")
                kind = "union_all"
            else:
                self.eat_kw("DISTINCT")  # the default for set ops
                kind = "except"
            sel = combine(sel, intersect_term(), kind)
        return sel

    def parse_select(self) -> "_Select":
        self.expect_kw("SELECT")
        if self.eat_kw("DISTINCT"):
            distinct = True
        else:
            self.eat_kw("ALL")
            distinct = False
        items: List[Tuple[Expression, Optional[str]]] = []
        star = False
        while True:
            if self.at_op("*"):
                self.next()
                star = True
            else:
                e = self.parse_expr()
                alias = None
                if self.eat_kw("AS"):
                    alias = self._ident()
                elif self.peek().kind == "ident" and \
                        self.peek().upper not in _KEYWORDS:
                    alias = self._ident()
                items.append((e, alias))
            if not self.eat_op(","):
                break

        sel = _Select(items=items, star=star, distinct=distinct)
        if self.eat_kw("FROM"):
            sel.relations, sel.joins = self.parse_from()
        if self.eat_kw("WHERE"):
            sel.where = self.parse_expr()
        if self.at_kw("GROUP"):
            self.next()
            self.expect_kw("BY")
            if self.at_kw("ROLLUP", "CUBE", "GROUPING"):
                sel.group_by, sel.grouping_sets = \
                    self._parse_grouping_analytics()
            else:
                sel.group_by = [self.parse_expr()]
                while self.eat_op(","):
                    sel.group_by.append(self.parse_expr())
        if self.eat_kw("HAVING"):
            sel.having = self.parse_expr()
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            sel.order_by = [self.parse_sort_item()]
            while self.eat_op(","):
                sel.order_by.append(self.parse_sort_item())
        if self.eat_kw("LIMIT"):
            t = self.next()
            if t.kind != "number":
                raise ParseError(f"LIMIT expects a number at {t.pos}")
            sel.limit = int(t.value)
        return sel

    def _parse_grouping_analytics(self):
        """ROLLUP(a, b) / CUBE(a, b) / GROUPING SETS((a, b), (a), ())
        -> (full column list, list of name subsets). Reference:
        SqlBase.g4 groupingAnalytics -> Expand planning; here each set
        lowers to its own aggregate union-ed together (ExpandExec.scala
        semantics without the row-expansion operator)."""
        kind = self.next().upper
        cols: List[str] = []
        sets: List[List[str]] = []

        def ident_list():
            names = []
            self.expect_op("(")
            if not self.at_op(")"):
                names.append(self._ident())
                while self.eat_op(","):
                    names.append(self._ident())
            self.expect_op(")")
            return names

        if kind in ("ROLLUP", "CUBE"):
            cols = ident_list()
            if kind == "ROLLUP":
                sets = [cols[:i] for i in range(len(cols), -1, -1)]
            else:
                import itertools
                sets = [list(c) for r in range(len(cols), -1, -1)
                        for c in itertools.combinations(cols, r)]
        else:
            self.expect_kw("SETS")
            self.expect_op("(")
            while True:
                if self.at_op("("):
                    sets.append(ident_list())
                else:
                    # bare column = a single-column grouping set
                    sets.append([self._ident()])
                if not self.eat_op(","):
                    break
            self.expect_op(")")
            seen = []
            for s_ in sets:
                for n in s_:
                    if n not in seen:
                        seen.append(n)
            cols = seen
        return [ColumnRef(n) for n in cols], sets

    def _ident(self) -> str:
        t = self.next()
        if t.kind != "ident":
            raise ParseError(f"expected identifier at {t.pos}, "
                             f"got {t.value!r}")
        return t.value

    def parse_sort_item(self) -> Tuple[Expression, bool, Optional[bool]]:
        e = self.parse_expr()
        asc = True
        if self.eat_kw("DESC"):
            asc = False
        else:
            self.eat_kw("ASC")
        nulls_first: Optional[bool] = None
        if self.at_kw("NULLS"):
            self.next()
            if self.eat_kw("FIRST"):
                nulls_first = True
            elif self.eat_kw("LAST"):
                nulls_first = False
            else:
                raise ParseError("expected FIRST or LAST after NULLS")
        return (e, asc, nulls_first)

    # -- FROM clause --------------------------------------------------------

    def parse_from(self):
        relations: List[Tuple[object, Optional[str]]] = []
        joins: List[Tuple[str, object, Optional[str], Optional[Expression]]] = []
        relations.append(self.parse_table_ref())
        while True:
            if self.eat_op(","):
                relations.append(self.parse_table_ref())
                continue
            how = None
            if self.at_kw("JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS",
                          "SEMI", "ANTI"):
                w = self.next().upper
                if w == "JOIN":
                    how = "inner"
                else:
                    how = {"INNER": "inner", "LEFT": "left", "RIGHT": "right",
                           "FULL": "full", "CROSS": "cross",
                           "SEMI": "left_semi", "ANTI": "left_anti"}[w]
                    self.eat_kw("OUTER")
                    if w == "LEFT" and self.eat_kw("SEMI"):
                        how = "left_semi"
                    elif w == "LEFT" and self.eat_kw("ANTI"):
                        how = "left_anti"
                    elif w == "RIGHT" and self.at_kw("SEMI", "ANTI"):
                        raise ParseError(
                            "RIGHT SEMI/ANTI JOIN is not supported; "
                            "swap the operands and use LEFT SEMI/ANTI")
                    self.expect_kw("JOIN")
                ref, alias = self.parse_table_ref()
                cond = None
                if self.eat_kw("ON"):
                    cond = self.parse_expr()
                joins.append((how, ref, alias, cond))
                continue
            break
        return relations, joins

    def parse_table_ref(self):
        if self.at_op("("):
            self.next()
            sub = self.parse_query_expr()
            self.expect_op(")")
            self.eat_kw("AS")
            alias = self._ident()
            return (sub, alias)
        name = self._ident()
        alias = None
        if self.eat_kw("AS"):
            alias = self._ident()
        elif self.peek().kind == "ident" and \
                self.peek().upper not in _KEYWORDS:
            alias = self._ident()
        return (name, alias)

    # -- expressions (precedence climbing) ----------------------------------

    def parse_expr(self) -> Expression:
        return self.parse_or()

    def parse_or(self) -> Expression:
        e = self.parse_and()
        while self.eat_kw("OR"):
            e = Or(e, self.parse_and())
        return e

    def parse_and(self) -> Expression:
        e = self.parse_not()
        while self.eat_kw("AND"):
            e = And(e, self.parse_not())
        return e

    def parse_not(self) -> Expression:
        if self.eat_kw("NOT"):
            return Not(self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> Expression:
        e = self.parse_additive()
        negate = False
        if self.at_kw("NOT"):
            nxt = self.peek(1)
            if nxt.kind == "ident" and nxt.upper in ("IN", "LIKE", "BETWEEN"):
                self.next()
                negate = True
        if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
            op = self.next().value
            rhs = self.parse_additive()
            cls = {"=": EQ, "<>": NE, "!=": NE, "<": LT, "<=": LE,
                   ">": GT, ">=": GE}[op]
            e = self._fold_interval_cmp(cls, e, rhs)
        elif self.eat_kw("BETWEEN"):
            lo = self.parse_additive()
            self.expect_kw("AND")
            hi = self.parse_additive()
            e = And(GE(e, lo), LE(e, hi))
        elif self.eat_kw("IN"):
            self.expect_op("(")
            if self.at_kw("SELECT"):
                sub = self.parse_query_expr()
                self.expect_op(")")
                e = _InSubquery(e, sub)
            else:
                values = [self._literal_value()]
                while self.eat_op(","):
                    values.append(self._literal_value())
                self.expect_op(")")
                e = In(e, tuple(values))
        elif self.eat_kw("LIKE"):
            t = self.next()
            if t.kind != "string":
                raise ParseError(f"LIKE expects a string pattern at {t.pos}")
            e = Like(e, t.value)
        elif self.eat_kw("IS"):
            neg = self.eat_kw("NOT")
            self.expect_kw("NULL")
            e = IsNull(e)
            if neg:
                e = Not(e)
        if negate:
            e = Not(e)
        return e

    def _literal_value(self):
        t = self.next()
        if t.kind == "string":
            return t.value
        if t.kind == "number":
            return self._number(t.value)
        raise ParseError(f"expected literal at {t.pos}, got {t.value!r}")

    @staticmethod
    def _number(text: str):
        if "." in text or "e" in text or "E" in text:
            return float(text)
        return int(text)

    def _fold_interval_cmp(self, cls, lhs, rhs):
        return cls(lhs, rhs)

    def parse_additive(self) -> Expression:
        e = self.parse_multiplicative()
        while self.at_op("+", "-"):
            op = self.next().value
            rhs = self.parse_multiplicative()
            if isinstance(rhs, _IntervalExpr):
                e = _shift_date(e, rhs.interval, -1 if op == "-" else 1)
            elif op == "+":
                e = e + rhs
            else:
                e = e - rhs
        return e

    def parse_multiplicative(self) -> Expression:
        e = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            rhs = self.parse_unary()
            if op == "*":
                e = e * rhs
            elif op == "/":
                e = e / rhs
            else:
                e = Mod(e, rhs)
        return e

    def parse_unary(self) -> Expression:
        if self.eat_op("-"):
            e = self.parse_unary()
            if isinstance(e, Literal) and isinstance(e.value, (int, float)):
                return Literal(-e.value, e._dtype)
            return Neg(e)
        if self.eat_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Expression:
        t = self.peek()
        if self.eat_op("("):
            if self.at_kw("SELECT"):
                sub = self.parse_query_expr()
                self.expect_op(")")
                return _ScalarSubquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "number":
            self.next()
            return Literal(self._number(t.value))
        if t.kind == "string":
            self.next()
            return Literal(t.value)
        if t.kind != "ident":
            raise ParseError(f"unexpected token {t.value!r} at {t.pos}")

        u = t.upper
        if u == "NULL":
            self.next()
            return Literal(None)
        if u in ("TRUE", "FALSE"):
            self.next()
            return Literal(u == "TRUE")
        if u == "DATE":
            nxt = self.peek(1)
            if nxt.kind == "string":
                self.next()
                self.next()
                return date_literal(nxt.value)
        if u == "INTERVAL":
            self.next()
            return _IntervalExpr(self._parse_interval())
        if u == "EXISTS":
            self.next()
            self.expect_op("(")
            sub = self.parse_select()
            self.expect_op(")")
            return _ExistsSubquery(sub)
        if u == "CASE":
            return self.parse_case()
        if u == "CAST":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            dt = self.parse_type()
            self.expect_op(")")
            return Cast(e, dt)
        if u == "EXTRACT":
            self.next()
            self.expect_op("(")
            field = self._ident().upper()
            self.expect_kw("FROM")
            e = self.parse_expr()
            self.expect_op(")")
            if field == "YEAR":
                return ExtractYear(e)
            raise ParseError(f"EXTRACT({field}) is not supported")

        if u in _KEYWORDS:
            raise ParseError(f"unexpected keyword {t.value!r} at {t.pos}")
        # function call or (qualified) column reference
        if self.peek(1).kind == "op" and self.peek(1).value == "(":
            e = self.parse_function()
            if self.at_kw("OVER"):
                return self._parse_over(e)
            if isinstance(e, _RankingCall):
                raise ParseError(
                    f"{e.kind}() requires an OVER (...) clause")
            return e
        self.next()
        name = t.value
        if self.at_op(".") and self.peek(1).kind == "ident":
            self.next()
            return _QualifiedRef(name, self._ident())
        return ColumnRef(name)

    def _parse_interval(self) -> _Interval:
        t = self.next()
        if t.kind == "string":
            qty = int(t.value)
        elif t.kind == "number":
            qty = int(t.value)
        else:
            raise ParseError(f"INTERVAL expects a quantity at {t.pos}")
        unit = self._ident().upper().rstrip("S")
        if unit == "DAY":
            return _Interval(days=qty)
        if unit == "MONTH":
            return _Interval(months=qty)
        if unit == "YEAR":
            return _Interval(years=qty)
        raise ParseError(f"unsupported INTERVAL unit {unit!r}")

    def parse_case(self) -> Expression:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.eat_kw("WHEN"):
            cond = self.parse_expr()
            if operand is not None:
                cond = EQ(operand, cond)
            self.expect_kw("THEN")
            branches.append((cond, self.parse_expr()))
        otherwise = None
        if self.eat_kw("ELSE"):
            otherwise = self.parse_expr()
        self.expect_kw("END")
        return CaseWhen(branches, otherwise)

    def parse_type(self) -> T.DataType:
        name = self._ident().upper()
        simple = {
            "INT": T.INT, "INTEGER": T.INT, "BIGINT": T.LONG, "LONG": T.LONG,
            "SMALLINT": T.SHORT, "TINYINT": T.BYTE, "DOUBLE": T.DOUBLE,
            "FLOAT": T.FLOAT, "REAL": T.FLOAT, "BOOLEAN": T.BOOLEAN,
            "DATE": T.DATE, "STRING": T.STRING, "VARCHAR": T.STRING,
            "CHAR": T.STRING, "TIMESTAMP": T.TIMESTAMP,
        }
        if name in simple:
            if name in ("VARCHAR", "CHAR") and self.eat_op("("):
                self.next()
                self.expect_op(")")
            return simple[name]
        if name in ("DECIMAL", "NUMERIC"):
            p, s = 10, 0
            if self.eat_op("("):
                p = int(self.next().value)
                if self.eat_op(","):
                    s = int(self.next().value)
                self.expect_op(")")
            return T.DecimalType(p, s)
        raise ParseError(f"unknown type {name!r}")

    _AGGS = {"SUM": Sum, "AVG": Avg, "MEAN": Avg, "MIN": Min, "MAX": Max,
             "STDDEV": StddevSamp, "STDDEV_SAMP": StddevSamp,
             "STDDEV_POP": StddevPop, "VARIANCE": VarianceSamp,
             "VAR_SAMP": VarianceSamp, "VAR_POP": VariancePop}

    #: DISTINCT-capable rewrite markers (RewriteDistinctAggregates)
    _DISTINCT_AGGS = {"SUM": SumDistinct, "AVG": AvgDistinct,
                      "MEAN": AvgDistinct}

    #: single-argument extended aggregates
    _AGGS_EXT = {"FIRST": First, "FIRST_VALUE": First, "LAST": Last,
                 "LAST_VALUE": Last, "ANY_VALUE": AnyValue,
                 "SKEWNESS": Skewness, "KURTOSIS": Kurtosis,
                 "BOOL_AND": BoolAnd, "EVERY": BoolAnd, "BOOL_OR": BoolOr,
                 "ANY": BoolOr, "SOME": BoolOr, "COUNT_IF": CountIf}

    #: two-argument aggregates (corr/covar)
    _AGGS2 = {"CORR": Corr, "COVAR_SAMP": CovarSamp, "COVAR_POP": CovarPop}

    def parse_function(self) -> Expression:
        name = self._ident().upper()
        self.expect_op("(")
        if name == "COUNT":
            if self.eat_op("*"):
                self.expect_op(")")
                return _AggCall(Count(None))
            if self.eat_kw("DISTINCT"):
                e = self.parse_expr()
                self.expect_op(")")
                return _AggCall(CountDistinct(e))
            e = self.parse_expr()
            self.expect_op(")")
            return _AggCall(Count(e))
        if name in self._AGGS:
            if self.eat_kw("DISTINCT"):
                marker = self._DISTINCT_AGGS.get(name)
                if marker is None:
                    raise ParseError(
                        f"{name}(DISTINCT ...) is not supported")
                e = self.parse_expr()
                self.expect_op(")")
                return _AggCall(marker(e))
            e = self.parse_expr()
            self.expect_op(")")
            return _AggCall(self._AGGS[name](e))
        if name in self._AGGS_EXT:
            e = self.parse_expr()
            self.expect_op(")")
            return _AggCall(self._AGGS_EXT[name](e))
        if name in self._AGGS2:
            x = self.parse_expr()
            self.expect_op(",")
            y = self.parse_expr()
            self.expect_op(")")
            return _AggCall(self._AGGS2[name](x, y))
        if name in ("PERCENTILE", "PERCENTILE_APPROX",
                    "APPROX_PERCENTILE"):
            from ..expr_agg import Percentile
            e = self.parse_expr()
            self.expect_op(",")
            q = self.parse_expr()
            if not isinstance(q, Literal):
                raise ParseError(f"{name} fraction must be a literal")
            if self.eat_op(","):
                self.parse_expr()  # accuracy: exact anyway
            self.expect_op(")")
            return _AggCall(Percentile(e, float(q.value)))
        if name == "MEDIAN":
            from ..expr_agg import Median
            e = self.parse_expr()
            self.expect_op(")")
            return _AggCall(Median(e))
        if name in ("COLLECT_LIST", "COLLECT_SET", "ARRAY_AGG"):
            from ..expr_agg import CollectList, CollectSet
            e = self.parse_expr()
            self.expect_op(")")
            cls = CollectSet if name == "COLLECT_SET" else CollectList
            return _AggCall(cls(e))
        if name in ("ROW_NUMBER", "RANK", "DENSE_RANK"):
            self.expect_op(")")
            return _RankingCall(name.lower(), None, 0, None)
        if name in ("LAG", "LEAD"):
            arg = self.parse_expr()
            offset, default = 1, None
            if self.eat_op(","):
                off = self.parse_expr()
                if not (isinstance(off, Literal)
                        and isinstance(off.value, int)):
                    raise ParseError(f"{name} offset must be a literal int")
                offset = off.value
                if self.eat_op(","):
                    dflt = self.parse_expr()
                    if not isinstance(dflt, Literal):
                        raise ParseError(f"{name} default must be a literal")
                    default = dflt.value
            self.expect_op(")")
            return _RankingCall(name.lower(), arg,
                                offset if name == "LAG" else -offset,
                                default)
        args: List[Expression] = []
        if not self.at_op(")"):
            args.append(self.parse_expr())
            while self.eat_op(","):
                args.append(self.parse_expr())
        self.expect_op(")")
        return self._scalar_function(name, args)

    def _parse_frame_clause(self):
        """ROWS|RANGE BETWEEN <bound> AND <bound> (or the single-bound
        short form `ROWS n PRECEDING`), reference SqlBase.g4
        windowFrame."""
        from ..window import (CURRENT_ROW, UNBOUNDED_FOLLOWING,
                              UNBOUNDED_PRECEDING)
        kind = "rows" if self.eat_kw("ROWS") else "range"
        if kind == "range":
            self.expect_kw("RANGE")

        def bound(default_end=False):
            if self.eat_kw("UNBOUNDED"):
                if self.eat_kw("PRECEDING"):
                    return UNBOUNDED_PRECEDING
                self.expect_kw("FOLLOWING")
                return UNBOUNDED_FOLLOWING
            if self.eat_kw("CURRENT"):
                self.expect_kw("ROW")
                return CURRENT_ROW
            t = self.next()
            if t.kind != "number":
                raise ParseError(
                    f"expected a frame bound at {t.pos}, got {t.value!r}")
            try:
                n = int(t.value)
            except ValueError:
                raise ParseError(
                    f"frame bounds must be integers, got {t.value!r} "
                    f"at {t.pos}") from None
            if self.eat_kw("PRECEDING"):
                return -n
            self.expect_kw("FOLLOWING")
            return n

        if self.eat_kw("BETWEEN"):
            start = bound()
            self.expect_kw("AND")
            end = bound()
        else:
            start = bound()
            end = CURRENT_ROW
        return (kind, start, end)

    def _parse_over(self, call: Expression) -> Expression:
        """fn(...) OVER ([PARTITION BY ...] [ORDER BY ...])."""
        from ..window import WindowExpr, WindowSpec
        self.expect_kw("OVER")
        self.expect_op("(")
        partition: List[Expression] = []
        order: List[SortOrder] = []
        if self.at_kw("PARTITION"):
            self.next()
            self.expect_kw("BY")
            partition.append(self.parse_expr())
            while self.eat_op(","):
                partition.append(self.parse_expr())
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            while True:
                e, asc, nf = self.parse_sort_item()
                order.append(SortOrder(e, ascending=asc, nulls_first=nf))
                if not self.eat_op(","):
                    break
        frame = None
        if self.at_kw("ROWS", "RANGE"):
            frame = self._parse_frame_clause()
        self.expect_op(")")
        spec = WindowSpec(tuple(partition), tuple(order), frame)
        if isinstance(call, _RankingCall):
            if not order:
                raise ParseError(
                    f"{call.kind}() requires ORDER BY in its OVER clause")
            return WindowExpr(call.kind, call.arg, spec,
                              offset=call.offset, default=call.default)
        if isinstance(call, _AggCall):
            from ..window import AGG_WINDOW_KINDS
            kind = AGG_WINDOW_KINDS.get(type(call.func).__name__)
            if kind is None:
                raise ParseError(
                    f"{type(call.func).__name__} is not supported as a "
                    f"window function")
            return WindowExpr(kind, call.func.child, spec)
        raise ParseError("OVER applies to window or aggregate functions")

    def _scalar_function(self, name: str, args: List[Expression]) -> Expression:
        if name in ("SUBSTRING", "SUBSTR") and len(args) == 3:
            start = args[1]
            length = args[2]
            if not (isinstance(start, Literal) and isinstance(length, Literal)):
                raise ParseError("SUBSTRING requires literal start/length")
            return Substring(args[0], int(start.value), int(length.value))
        # registry-driven dispatch (reference: FunctionRegistry.scala);
        # replaces the round-3 hand list
        from .registry import lookup
        out = lookup(name, args)
        if out is not None:
            return out
        # session-registered Python UDFs (UDFRegistration.scala analog)
        if self.session is not None:
            u = self.session.udf.lookup(name)
            if u is not None:
                return u(*args)
        raise ParseError(f"unknown function {name!r}")


def _classify_side_multi(e: Expression, per_alias: dict,
                         all_inner) -> str:
    """'inner' | 'outer' | 'mixed' | 'none' for a subquery conjunct,
    honoring table qualifiers (references() drops them, which
    misclassified `bounds.k = tiny.k`-style correlation). Unqualified
    names resolve inner-first (the inner scope shadows the outer)."""
    saw_inner = saw_outer = False

    def walk(node):
        nonlocal saw_inner, saw_outer
        if isinstance(node, _QualifiedRef):
            names = per_alias.get(node.qualifier)
            if names is not None and node.col in names:
                saw_inner = True
            else:
                saw_outer = True
            return
        if isinstance(node, ColumnRef):
            if node.name() in all_inner:
                saw_inner = True
            else:
                saw_outer = True
            return
        for c in node.children:
            walk(c)

    walk(e)
    if saw_inner and saw_outer:
        return "mixed"
    if saw_inner:
        return "inner"
    if saw_outer:
        return "outer"
    return "none"


class _SubqueryExpr(Expression):
    """Base for parse-time subquery expressions; consumed by the
    Lowerer's rewrite passes (reference: `optimizer/subquery.scala`
    RewritePredicateSubquery / RewriteCorrelatedScalarSubquery)."""

    def __init__(self, select: "_Select", child: Optional[Expression] = None):
        self.select = select
        self.children = () if child is None else (child,)

    def references(self):
        return set() if not self.children else self.children[0].references()

    def dtype(self, schema):
        raise AnalysisError(
            f"{type(self).__name__} must be rewritten before analysis")


class _InSubquery(_SubqueryExpr):
    def __init__(self, child: Expression, select: "_Select"):
        super().__init__(select, child)

    def __repr__(self):
        return f"({self.children[0]!r} IN (<subquery>))"


class _ExistsSubquery(_SubqueryExpr):
    def __repr__(self):
        return "EXISTS(<subquery>)"


class _ScalarSubquery(_SubqueryExpr):
    def __repr__(self):
        return "(<scalar subquery>)"


def _contains_subquery(e: Expression) -> bool:
    if isinstance(e, _SubqueryExpr):
        return True
    return any(_contains_subquery(c) for c in e.children)


class _RankingCall(Expression):
    """Parse-time sentinel for row_number/rank/dense_rank/lag/lead —
    only valid immediately followed by OVER."""

    def __init__(self, kind: str, arg, offset: int, default):
        self.kind = kind
        self.arg = arg
        self.offset = offset
        self.default = default
        self.children = () if arg is None else (arg,)

    def dtype(self, schema):
        raise AnalysisError(f"{self.kind}() requires an OVER clause")


class _QualifiedRef(Expression):
    """`alias.col` — resolved against the FROM-clause relations during
    lowering, then rewritten to a plain ColumnRef (the engine's plans
    resolve flat names; the reference resolves qualifiers in
    `Analyzer.ResolveReferences`)."""

    def __init__(self, qualifier: str, col: str):
        self.qualifier = qualifier
        self.col = col
        self.children = ()

    def dtype(self, schema):
        raise AnalysisError(
            f"unresolved qualified reference {self.qualifier}.{self.col}")

    def references(self):
        return {self.col}

    def __repr__(self):
        return f"{self.qualifier}.{self.col}"


class _IntervalExpr(Expression):
    """Transient node produced for INTERVAL literals; must be consumed by
    date +/- interval folding before lowering."""

    def __init__(self, interval: _Interval):
        self.interval = interval
        self.children = ()

    def dtype(self, schema):
        raise AnalysisError("INTERVAL is only valid in date +/- interval")


def _shift_date(e: Expression, iv: _Interval, sign: int) -> Expression:
    """Fold `date_literal +/- interval` into a new DATE literal."""
    if not (isinstance(e, Literal) and isinstance(e._dtype, T.DateType)):
        raise AnalysisError("date +/- INTERVAL requires a DATE literal "
                            "on the left")
    days = int(e.value)
    d = (np.datetime64("1970-01-01", "D") + np.timedelta64(days, "D")
         ).astype(datetime.date)
    if iv.years or iv.months:
        months = d.year * 12 + (d.month - 1) + sign * (iv.years * 12 + iv.months)
        y, m = divmod(months, 12)
        # clamp the day to the target month's length (SQL add_months)
        import calendar
        day = min(d.day, calendar.monthrange(y, m + 1)[1])
        d = datetime.date(y, m + 1, day)
    if iv.days:
        d = d + datetime.timedelta(days=sign * iv.days)
    return date_literal(d.isoformat())


# ---------------------------------------------------------------------------
# Lowering
# ---------------------------------------------------------------------------

@dataclass
class _Select:
    items: List[Tuple[Expression, Optional[str]]] = None
    star: bool = False
    distinct: bool = False
    relations: List = None
    joins: List = None
    where: Optional[Expression] = None
    group_by: Optional[List[Expression]] = None
    having: Optional[Expression] = None
    order_by: Optional[List[Tuple[Expression, bool, Optional[bool]]]] = None
    limit: Optional[int] = None
    union_of: Optional[Tuple["_Select", "_Select"]] = None
    set_op: str = "union_all"  # union_all | intersect | except
    grouping_sets: Optional[List[List[str]]] = None  # ROLLUP/CUBE/SETS
    ctes: Optional[List] = None  # (name, col_aliases, _Select) triples


def _conjuncts(e: Optional[Expression]) -> List[Expression]:
    if e is None:
        return []
    if isinstance(e, And):
        return _conjuncts(e.children[0]) + _conjuncts(e.children[1])
    return [e]


def _and_all(es: Sequence[Expression]) -> Optional[Expression]:
    out = None
    for e in es:
        out = e if out is None else And(out, e)
    return out


class _Scope:
    """Name resolution over the FROM-clause relations: tracks which
    relation owns each column, and each (relation, column)'s CURRENT
    output name as joins rename collisions with the `_r` suffix."""

    def __init__(self):
        self.rels: Dict[str, List[str]] = {}        # alias -> column names
        self.current: Dict[Tuple[str, str], str] = {}  # (alias, col) -> name

    def add(self, alias: str, names: Sequence[str]) -> None:
        if alias in self.rels:
            raise AnalysisError(f"duplicate relation alias {alias!r}")
        self.rels[alias] = list(names)
        for n in names:
            self.current[(alias, n)] = n

    def qrefs(self, e: Expression, within: Set[str]) -> Set[Tuple[str, str]]:
        """All (alias, col) pairs an expression references, resolving
        unqualified names against `within` (raises on ambiguity)."""
        out: Set[Tuple[str, str]] = set()

        def walk(node):
            if isinstance(node, _QualifiedRef):
                if node.qualifier not in self.rels:
                    raise AnalysisError(
                        f"unknown relation {node.qualifier!r}")
                if node.col not in self.rels[node.qualifier]:
                    raise AnalysisError(
                        f"column {node.col!r} not in {node.qualifier!r}")
                out.add((node.qualifier, node.col))
                return
            if isinstance(node, ColumnRef):
                owners = [a for a in within
                          if node.name() in self.rels.get(a, ())]
                if len(owners) > 1:
                    raise AnalysisError(
                        f"ambiguous column {node.name()!r} (in "
                        f"{sorted(owners)}); qualify it")
                if owners:
                    out.add((owners[0], node.name()))
                return
            if isinstance(node, _AggCall):
                for c in node.func.children:
                    walk(c)
                return
            for c in node.children:
                walk(c)

        walk(e)
        return out

    def rewrite(self, e: Expression) -> Expression:
        """Replace qualified refs (and renamed unqualified refs) with the
        current flat output names."""
        if isinstance(e, _QualifiedRef):
            key = (e.qualifier, e.col)
            if key not in self.current:
                raise AnalysisError(f"cannot resolve {e!r}")
            return ColumnRef(self.current[key])
        if isinstance(e, ColumnRef):
            owners = [a for a in self.rels if e.name() in self.rels[a]]
            if len(owners) > 1:
                raise AnalysisError(
                    f"ambiguous column {e.name()!r} (in {sorted(owners)}); "
                    f"qualify it")
            if len(owners) == 1:
                return ColumnRef(self.current[(owners[0], e.name())])
            return e
        if isinstance(e, _AggCall):
            if e.func.children:
                return _AggCall(e.func.with_args(
                    [self.rewrite(c) for c in e.func.children]))
            return e
        return e.map_children(self.rewrite)

    def apply_rename(self, rename: Dict[str, str],
                     right_aliases: Set[str]) -> None:
        """Record the `_r`-suffix renames a join applied to the build-side
        relations' columns (rename maps pre-join name -> post-join name)."""
        for alias in right_aliases:
            for col in self.rels[alias]:
                cur = self.current[(alias, col)]
                if cur in rename and rename[cur] != cur:
                    self.current[(alias, col)] = rename[cur]


def _split_equi(conds: List[Expression], scope: _Scope,
                bound: Set[str], new: Set[str]):
    """Partition join conjuncts into equi key pairs (left side bound,
    right side the newly-joined relation) and residuals."""
    lk, rk, residual = [], [], []
    within = bound | new
    for c in conds:
        if isinstance(c, EQ):
            a, b = c.children
            ar = {al for al, _ in scope.qrefs(a, within)}
            br = {al for al, _ in scope.qrefs(b, within)}
            if ar and ar <= bound and br and br <= new:
                lk.append(a)
                rk.append(b)
                continue
            if ar and ar <= new and br and br <= bound:
                lk.append(b)
                rk.append(a)
                continue
        residual.append(c)
    return lk, rk, residual


class Lowerer:
    def __init__(self, session):
        self.session = session
        self._agg_counter = 0
        self._sq_counter = 0
        # WITH-clause views: name -> lowered plan, shared across every
        # reference in the statement (FROM and subqueries alike)
        self._ctes: Dict[str, L.LogicalPlan] = {}

    def lower(self, sel: _Select) -> L.LogicalPlan:
        for name, col_aliases, body in (sel.ctes or []):
            plan = self.lower(body)
            if col_aliases:
                names = plan.schema().names
                if len(col_aliases) != len(names):
                    raise AnalysisError(
                        f"CTE {name!r} declares {len(col_aliases)} "
                        f"columns but its query yields {len(names)}")
                plan = L.Project(plan, [Alias(ColumnRef(n), a)
                                        for n, a in zip(names,
                                                        col_aliases)])
            self._ctes[name] = plan
            # mark for the plan-fingerprint cache: a CTE referenced
            # more than once (Q15's FROM + scalar subquery) materializes
            # once on first use instead of re-executing per reference.
            # implicit=True scopes the entry to one statement execution
            # (evicted afterwards — no staleness, no unbounded growth)
            self.session.mark_cache(plan, implicit=True)
        if getattr(sel, "grouping_sets", None):
            return self._lower_grouping_sets(sel)
        if sel.union_of is not None:
            lplan = self.lower(sel.union_of[0])
            rplan = self.lower(sel.union_of[1])
            if sel.set_op == "union_all":
                plan = L.Union(lplan, rplan)
            else:
                from ..dataframe import set_op_plan
                plan = set_op_plan(lplan, rplan,
                                   "left_semi" if sel.set_op ==
                                   "intersect" else "left_anti")
            plan = self._lower_order_limit(sel, plan)
            if sel.limit is not None:
                plan = L.Limit(plan, sel.limit)
            return plan
        plan, remaining, scope = self._lower_from(sel)
        plain = [c for c in remaining if not _contains_subquery(c)]
        subq = [c for c in remaining if _contains_subquery(c)]
        if plain:
            plan = L.Filter(plan, _and_all([scope.rewrite(c)
                                            for c in plain]))
        for c in subq:
            plan = self._rewrite_subquery_conjunct(plan, c, scope)
        sel = _Select(
            items=[(scope.rewrite(e), a) for e, a in (sel.items or [])],
            star=sel.star, distinct=sel.distinct,
            group_by=None if sel.group_by is None
            else [scope.rewrite(g) for g in sel.group_by],
            having=None if sel.having is None else scope.rewrite(sel.having),
            order_by=None if sel.order_by is None
            else [(scope.rewrite(e), asc, nf)
                  for e, asc, nf in sel.order_by],
            limit=sel.limit)
        plan = self._lower_projection(sel, plan)
        if sel.limit is not None:
            plan = L.Limit(plan, sel.limit)
        return plan

    # -- FROM/WHERE: relations + join extraction ---------------------------

    def _rel_plan(self, ref) -> L.LogicalPlan:
        if isinstance(ref, _Select):
            return self.lower(ref)
        if ref in self._ctes:
            return self._ctes[ref]
        if ref not in self.session.catalog:
            raise AnalysisError(
                f"table {ref!r} not found; known: "
                f"{sorted(self._ctes) + sorted(self.session.catalog)}")
        return L.Scan(self.session.catalog[ref])

    def _lower_from(self, sel: _Select):
        where = _conjuncts(sel.where)
        agg_where = [c for c in where if _contains_agg(c)]
        if agg_where:
            raise AnalysisError("aggregate functions are not allowed in "
                                "WHERE (use HAVING)")
        scope = _Scope()
        if not sel.relations:
            if sel.where is not None or sel.joins:
                raise AnalysisError("WHERE/JOIN without FROM")
            return L.Range(0, 1), [], scope

        def rel_alias(ref, alias) -> str:
            if alias:
                return alias
            if isinstance(ref, str):
                return ref
            raise AnalysisError("a subquery in FROM needs an alias")

        rels: List[Tuple[str, L.LogicalPlan]] = []
        for ref, alias in sel.relations:
            p = self._rel_plan(ref)
            a = rel_alias(ref, alias)
            scope.add(a, p.schema().names)
            rels.append((a, p))
        join_rels = []
        for how, ref, alias, cond in (sel.joins or []):
            p = self._rel_plan(ref)
            a = rel_alias(ref, alias)
            scope.add(a, p.schema().names)
            join_rels.append((how, a, p, cond))

        all_aliases = set(scope.rels)

        def refs(c) -> Set[str]:
            return {al for al, _ in scope.qrefs(c, all_aliases)}

        # single-table predicates push below the joins (the optimizer also
        # does this, but doing it here keeps implicit-join search simple
        # and cross-join intermediates small)
        def push_single(alias, plan):
            nonlocal where
            # subquery conjuncts must survive to the rewrite pass — their
            # inner references are invisible to references()
            mine = [c for c in where
                    if not _contains_subquery(c) and refs(c) == {alias}]
            if mine:
                # identity-based removal: Expression.__eq__ is the DSL EQ
                # constructor, so `c in mine` would match everything
                where = [c for c in where
                         if not any(c is m for m in mine)]
                return L.Filter(plan, _and_all([scope.rewrite(c)
                                                for c in mine]))
            return plan

        rels = [(a, push_single(a, p)) for a, p in rels]

        def make_join(plan, bound, right_alias, right_plan, how,
                      lk, rk, residual):
            """Build the join — flipping sides for inner joins when the new
            relation is the bigger one, so fact tables land on the probe
            (left) side and dimensions on the build side (the
            `JoinSelection`-style size heuristic) — then record the `_r`
            renames it applies and rewrite the residual against the
            post-join names."""
            from ..plan.planner import estimate_rows
            lk = [scope.rewrite(k) for k in lk]
            rk = [scope.rewrite(k) for k in rk]
            left, right = plan, right_plan
            left_aliases, right_aliases = set(bound), {right_alias}
            if how == "inner":
                eb = estimate_rows(plan)
                en = estimate_rows(right_plan)
                if en is not None and (eb is None or en > eb):
                    left, right = right_plan, plan
                    lk, rk = rk, lk
                    left_aliases, right_aliases = right_aliases, left_aliases
            join = L.Join(left, right, lk, rk, how, None)
            if how not in ("left_semi", "left_anti"):
                scope.apply_rename(join.right_name_map(), right_aliases)
            if residual:
                join = L.Join(left, right, lk, rk, how,
                              _and_all([scope.rewrite(c)
                                        for c in residual]))
            return join

        (alias0, plan) = rels[0]
        bound = {alias0}
        pending = list(rels[1:])
        while pending:
            progressed = False
            for i, (a, p) in enumerate(pending):
                linking = [c for c in where
                           if not _contains_subquery(c)
                           and refs(c) and refs(c) <= (bound | {a})
                           and a in refs(c)
                           and (refs(c) & bound)]
                lk, rk, residual = _split_equi(linking, scope, bound, {a})
                if lk:
                    where = [c for c in where
                             if not any(c is m for m in linking)]
                    plan = make_join(plan, bound, a, p, "inner",
                                     lk, rk, residual)
                    bound.add(a)
                    pending.pop(i)
                    progressed = True
                    break
            if progressed:
                continue
            # no equi link: cross join the next relation, conditions stay
            # in WHERE and apply after (the optimizer cannot save a truly
            # unlinked product — that is the query's semantics)
            a, p = pending.pop(0)
            from ..expr import Literal as Lit
            plan = make_join(plan, bound, a, p, "inner",
                             [Lit(1)], [Lit(1)], [])
            bound.add(a)

        for how, a, p, cond in join_rels:
            if how == "cross":
                from ..expr import Literal as Lit
                plan = make_join(plan, bound, a, p, "inner",
                                 [Lit(1)], [Lit(1)], _conjuncts(cond))
                bound.add(a)
                continue
            lk, rk, residual = _split_equi(_conjuncts(cond), scope,
                                           bound, {a})
            if not lk:
                raise AnalysisError(
                    f"JOIN ON requires at least one equi-condition "
                    f"(got {cond!r})")
            plan = make_join(plan, bound, a, p, how, lk, rk, residual)
            bound.add(a)

        return plan, where, scope

    # -- SELECT/GROUP BY/HAVING/ORDER BY ------------------------------------

    def _fresh_agg_name(self) -> str:
        self._agg_counter += 1
        return f"_agg{self._agg_counter}"

    def _extract_aggs(self, e: Expression, aggs: List[AggExpr],
                      top_alias: Optional[str] = None) -> Expression:
        """Replace _AggCall nodes with ColumnRefs, appending AggExprs.
        Reuses an existing output for structurally equal aggregates."""
        if isinstance(e, _AggCall):
            for existing in aggs:
                if repr(existing.func) == repr(e.func):
                    return ColumnRef(existing.out_name)
            name = top_alias or self._fresh_agg_name()
            aggs.append(AggExpr(e.func, name))
            return ColumnRef(name)
        if isinstance(e, Alias):
            inner = self._extract_aggs(e.child, aggs, top_alias=e.name())
            if isinstance(inner, ColumnRef) and inner.name() == e.name():
                return inner
            return Alias(inner, e.name())
        return e.map_children(lambda c: self._extract_aggs(c, aggs))

    def _lower_projection(self, sel: _Select, plan: L.LogicalPlan
                          ) -> L.LogicalPlan:
        child_names = plan.schema().names
        items: List[Tuple[Expression, Optional[str]]] = list(sel.items or [])
        if sel.star:
            star_items = [(ColumnRef(n), None) for n in child_names]
            items = star_items + items

        # uncorrelated scalar subqueries are legal anywhere an expression
        # is (SELECT items, HAVING thresholds — TPC-H Q11): lower them to
        # executor-resolved ScalarSubqueryExpr nodes up front. Correlated
        # ones outside WHERE stay unsupported (raise at analysis).
        def scalarize(e: Expression) -> Expression:
            if isinstance(e, _ScalarSubquery):
                if self._subquery_is_correlated(e.select):
                    raise AnalysisError(
                        "correlated scalar subqueries are only supported "
                        "in WHERE conjuncts (not SELECT/HAVING)")
                return L.ScalarSubqueryExpr(self.lower(e.select))
            return e.map_children(scalarize)

        items = [(scalarize(e), a) for e, a in items]
        if sel.having is not None:
            sel.having = scalarize(sel.having)

        has_agg = any(_contains_agg(e) for e, _ in items) or \
            sel.group_by is not None or \
            (sel.having is not None and _contains_agg(sel.having))

        from ..window import contains_window
        from ..expr_array import contains_explode
        has_window = any(contains_window(e) for e, _ in items)
        has_gen = any(contains_explode(e) for e, _ in items)
        if has_window or has_gen:
            if has_agg:
                raise AnalysisError(
                    "window functions / explode with GROUP BY in one "
                    "SELECT are not supported yet (use a FROM subquery)")
            plan, items = self._extract_window_items(plan, items)

        if sel.distinct and has_agg:
            raise AnalysisError(
                "SELECT DISTINCT with aggregates is not supported yet")
        if sel.having is not None and not has_agg:
            raise AnalysisError(
                "HAVING requires GROUP BY or aggregate functions "
                "(use WHERE for row filters)")

        def out_name(e: Expression, alias: Optional[str], idx: int) -> str:
            if alias:
                return alias
            if isinstance(e, (ColumnRef, Alias)):
                return e.name()
            if isinstance(e, _AggCall):
                return repr(e.func)
            return f"col{idx}"

        if not has_agg:
            exprs = [Alias(e, out_name(e, a, i)) if not (
                isinstance(e, ColumnRef) and a is None) else e
                for i, (e, a) in enumerate(items)]
            out_names = {out_name(e, a, i)
                         for i, (e, a) in enumerate(items)}
            if sel.order_by:
                # resolve ORDER BY ordinals against the SELECT list here —
                # the hidden-sort path below would otherwise bind them to
                # the pre-projection child schema
                resolved = []
                for k, asc, nf in sel.order_by:
                    if isinstance(k, Literal) and isinstance(k.value, int):
                        idx = k.value - 1
                        if not (0 <= idx < len(items)):
                            raise AnalysisError(
                                f"ORDER BY position {k.value} out of range")
                        k = ColumnRef(out_name(items[idx][0],
                                               items[idx][1], idx))
                    resolved.append((k, asc, nf))
                sel.order_by = resolved
            if sel.distinct and sel.order_by and any(
                    (k.references() - out_names)
                    and k.references() <= set(child_names)
                    for k, _, _ in sel.order_by):
                # the dedupe would have to run between the hidden sort and
                # the projection, destroying the requested order
                raise AnalysisError(
                    "SELECT DISTINCT: ORDER BY must reference select-list "
                    "columns")
            if sel.order_by and any(
                    (k.references() - out_names)
                    and k.references() <= set(child_names)
                    for k, _, _ in sel.order_by):
                # ORDER BY keys hidden by the projection: sort below it
                # (reference: Analyzer.ResolveSortReferences adds a hidden
                # projection; ordering is stable through Project). Keys on
                # select aliases substitute the aliased expression.
                subst = {a: e for (e, a) in items if a}

                def desugar(k: Expression) -> Expression:
                    if isinstance(k, ColumnRef) and k.name() in subst \
                            and k.name() not in child_names:
                        return subst[k.name()]
                    return k.map_children(desugar)

                sorted_below = self._lower_order_limit(
                    sel, plan, key_rewrite=desugar)
                return L.Project(sorted_below, exprs)
            plan = L.Project(plan, exprs)
            if sel.distinct:
                plan = L.Aggregate(
                    plan, [ColumnRef(n) for n in plan.schema().names], [])
            plan = self._lower_order_limit(sel, plan)
            return plan

        # aggregate query: resolve group expressions (positions / aliases /
        # expressions), split each select item into group-key or aggregate
        groups: List[Expression] = []
        for g in (sel.group_by or []):
            if isinstance(g, Literal) and isinstance(g.value, int):
                idx = g.value - 1
                if not (0 <= idx < len(items)):
                    raise AnalysisError(f"GROUP BY position {g.value} out "
                                        f"of range")
                e, a = items[idx]
                groups.append(Alias(e, out_name(e, a, idx))
                              if not isinstance(e, ColumnRef) or a else e)
                continue
            if isinstance(g, ColumnRef) and g.name() not in child_names:
                # group by a select alias
                for i, (e, a) in enumerate(items):
                    if a == g.name():
                        groups.append(Alias(e, a))
                        break
                else:
                    raise AnalysisError(
                        f"GROUP BY column {g.name()!r} not found")
                continue
            groups.append(g)

        def group_key_name(g: Expression) -> str:
            return g.name() if isinstance(g, (ColumnRef, Alias)) else repr(g)

        group_names = [group_key_name(g) for g in groups]
        aggs: List[AggExpr] = []
        post: List[Expression] = []
        for i, (e, a) in enumerate(items):
            name = out_name(e, a, i)
            if not _contains_agg(e):
                # must be a group key (SQL: non-aggregated select columns
                # must appear in GROUP BY)
                matched = None
                for g, gname in zip(groups, group_names):
                    from ..expr import structurally_equal
                    ge = g.child if isinstance(g, Alias) else g
                    ee = e.child if isinstance(e, Alias) else e
                    if structurally_equal(ge, ee) or gname == name:
                        matched = gname
                        break
                if matched is None:
                    raise AnalysisError(
                        f"column {name!r} must appear in GROUP BY or inside "
                        f"an aggregate")
                post.append(ColumnRef(matched) if matched == name
                            else Alias(ColumnRef(matched), name))
                continue
            replaced = self._extract_aggs(e, aggs, top_alias=a
                                          if isinstance(e, _AggCall) else None)
            if isinstance(replaced, ColumnRef) and replaced.name() == name:
                post.append(replaced)
            else:
                post.append(Alias(replaced, name))

        having_expr = None
        if sel.having is not None:
            having_expr = self._extract_aggs(sel.having, aggs)

        plan = L.Aggregate(plan, groups, aggs)
        if having_expr is not None:
            plan = L.Filter(plan, having_expr)
        plan = L.Project(plan, post)
        return self._lower_order_limit(sel, plan)

    # -- subquery rewrites (reference: optimizer/subquery.scala) ------------

    def _inner_universe(self, sub: _Select):
        """(aliases, per-alias column names) over every FROM relation and
        explicit join of a subquery — inner scope shadows outer for
        unqualified names (standard SQL name resolution)."""
        per_alias = {}
        refs = list(sub.relations or [])
        refs += [(ref, alias) for _how, ref, alias, _c
                 in (sub.joins or [])]
        for ref, alias in refs:
            if isinstance(ref, _Select):
                raise AnalysisError(
                    "FROM subqueries inside correlated subqueries are "
                    "not supported")
            a = alias or ref
            per_alias[a] = set(self._rel_plan(ref).schema().names)
        return per_alias

    def _split_correlation(self, sub: _Select, outer_scope: _Scope):
        """Split a (possibly multi-relation) subquery's WHERE into local
        conjuncts (RAW — the inner query's own lowering resolves them)
        and (outer_expr_rewritten, inner_expr_raw) equi-correlation
        pairs. Returns (local_conjuncts, pairs)."""
        if not sub.relations:
            raise AnalysisError("correlated subqueries need a FROM clause")
        if sub.group_by or sub.having or sub.limit is not None \
                or sub.order_by:
            raise AnalysisError(
                "GROUP BY/HAVING/ORDER BY/LIMIT inside a correlated "
                "predicate subquery is not supported")
        per_alias = self._inner_universe(sub)
        all_inner = set().union(*per_alias.values()) if per_alias else set()

        def side(e: Expression) -> str:
            return _classify_side_multi(e, per_alias, all_inner)

        local, pairs, residuals = [], [], []
        self._last_inner_universe = (per_alias, all_inner)
        for c in _conjuncts(sub.where):
            s = side(c)
            if s in ("inner", "none"):
                local.append(c)
                continue
            if isinstance(c, EQ):
                a, b = c.children
                for inner_e, outer_e in ((a, b), (b, a)):
                    if side(inner_e) == "inner" and \
                            side(outer_e) == "outer":
                        pairs.append((outer_scope.rewrite(outer_e),
                                      inner_e))
                        break
                else:
                    residuals.append(c)
            else:
                # non-equi correlation (e.g. l2.l_suppkey <> l1.l_suppkey
                # in Q21): EXISTS carries these as a join residual; the
                # scalar-aggregate rewrite cannot
                residuals.append(c)
        return local, pairs, residuals

    def _rewrite_subquery_conjunct(self, plan: L.LogicalPlan,
                                   c: Expression, scope: _Scope
                                   ) -> L.LogicalPlan:
        """Turn one WHERE conjunct containing a subquery into joins
        (IN -> left_semi, NOT IN -> left_anti, EXISTS likewise; scalar
        subqueries substitute an executed literal when uncorrelated, or
        a grouped-aggregate join when equi-correlated)."""
        negate = False
        e = c
        while isinstance(e, Not) and isinstance(e.children[0],
                                                (_InSubquery,
                                                 _ExistsSubquery)):
            negate = not negate
            e = e.children[0]

        if isinstance(e, _InSubquery):
            sub_plan = self.lower(e.select)
            sub_schema = sub_plan.schema()
            out_cols = sub_schema.names
            if len(out_cols) != 1:
                raise AnalysisError(
                    "IN (subquery) requires exactly one output column")
            how = "left_anti" if negate else "left_semi"
            probe = scope.rewrite(e.children[0])
            # NOT IN lowers to the NULL-AWARE anti-join (SQL three-valued
            # logic: one NULL in the subquery output empties the result;
            # a NULL probe survives only an empty subquery) — round-3
            # ADVICE low; reference: the NAAJ path in JoinSelection
            return L.Join(plan, sub_plan, [probe],
                          [ColumnRef(out_cols[0])], how,
                          null_aware=negate)

        if isinstance(e, _ExistsSubquery):
            if any(_contains_agg(ie) for ie, _a in (e.select.items or [])):
                # `EXISTS (SELECT count(*) ...)` is ALWAYS true (the
                # aggregate yields one row); a semi-join would wrongly
                # drop non-matching outer rows
                raise AnalysisError(
                    "aggregates inside an EXISTS subquery are not "
                    "supported (the aggregate always yields one row)")
            local, pairs, residuals = self._split_correlation(
                e.select, scope)
            if not pairs:
                if residuals:
                    raise AnalysisError(
                        "EXISTS with only non-equi correlation is not "
                        "supported (at least one equi-correlated "
                        "conjunct is required)")
                raise AnalysisError(
                    "uncorrelated EXISTS is not supported (it is a "
                    "constant — filter host-side instead)")
            # project the correlation keys and lower the inner query
            # normally (its own scope resolves qualified/local names;
            # duplicates are harmless under a semi/anti join)
            self._sq_counter += 1
            sq = self._sq_counter
            key_items = [(ie, f"__sq{sq}_key{i}")
                         for i, (_oe, ie) in enumerate(pairs)]
            # non-equi correlated conjuncts become the join's residual:
            # inner leaf refs project as uniquely-aliased columns so the
            # pair-batch condition never hits a rename collision
            per_alias, all_inner = self._last_inner_universe
            res_items: List[Tuple[Expression, str]] = []

            def residualize(node: Expression) -> Expression:
                if isinstance(node, (_QualifiedRef, ColumnRef)) and \
                        _classify_side_multi(node, per_alias,
                                             all_inner) == "inner":
                    alias = f"__sq{sq}_res{len(res_items)}"
                    res_items.append((node, alias))
                    return ColumnRef(alias)
                if isinstance(node, (_QualifiedRef, ColumnRef)):
                    return scope.rewrite(node)
                return node.map_children(residualize)

            residual_cond = None
            if residuals:
                residual_cond = _and_all([residualize(c)
                                          for c in residuals])
            inner_sel = _Select(items=list(key_items) + res_items,
                                relations=list(e.select.relations),
                                joins=list(e.select.joins or []),
                                where=_and_all(local))
            inner = self.lower(inner_sel)
            how = "left_anti" if negate else "left_semi"
            return L.Join(plan, inner, [p[0] for p in pairs],
                          [ColumnRef(nm) for _ie, nm in key_items], how,
                          condition=residual_cond)

        # comparison (or expression) containing scalar subqueries
        return self._rewrite_scalar_in_conjunct(plan, c, scope)

    def _subquery_is_correlated(self, sub: _Select) -> bool:
        if not sub.relations:
            return False
        try:
            per_alias = self._inner_universe(sub)
        except AnalysisError:
            return False  # FROM-subquery inners: treated uncorrelated
        all_inner = set().union(*per_alias.values()) if per_alias else set()
        return any(
            _classify_side_multi(cc, per_alias, all_inner)
            in ("outer", "mixed")
            for cc in _conjuncts(sub.where))

    def _rewrite_scalar_in_conjunct(self, plan, c: Expression,
                                    scope: _Scope) -> L.LogicalPlan:
        def rewrite(e: Expression) -> Expression:
            nonlocal plan
            if isinstance(e, _ScalarSubquery):
                sub = e.select
                if not self._subquery_is_correlated(sub):
                    return L.ScalarSubqueryExpr(self.lower(sub))
                # correlated scalar aggregate -> grouped aggregate joined
                # on the correlation keys (RewriteCorrelatedScalarSubquery)
                local, pairs, residuals = self._split_correlation(
                    sub, scope)
                if residuals:
                    raise AnalysisError(
                        "correlated scalar subqueries support "
                        "equi-correlation only")
                if len(sub.items or []) != 1:
                    raise AnalysisError(
                        "correlated scalar subquery needs exactly one "
                        "select item")
                # session-unique generated names: two correlated
                # subqueries in one query must not collide (the join
                # would rename the second to __sq_valN_r while the
                # filter still referenced __sq_valN)
                self._sq_counter += 1
                sq = self._sq_counter
                key_items = [(ie, f"__sq{sq}_key{i}")
                             for i, (_oe, ie) in enumerate(pairs)]
                val_name = f"__sq{sq}_val"
                inner_sel = _Select(
                    items=[(ie, nm) for ie, nm in key_items]
                    + [(sub.items[0][0], val_name)],
                    relations=list(sub.relations),
                    joins=list(sub.joins or []),
                    where=_and_all(local),
                    group_by=[ie for ie, _nm in key_items])
                sub_plan = self.lower(inner_sel)
                # LEFT join: outer rows without a matching group keep a
                # NULL subquery value (SQL semantics; an inner join
                # would wrongly drop them under OR-combined predicates)
                plan = L.Join(plan, sub_plan,
                              [oe for oe, _ie in pairs],
                              [ColumnRef(nm) for _ie, nm in key_items],
                              "left")
                return ColumnRef(val_name)
            return e.map_children(rewrite)

        cond = scope.rewrite(rewrite(c))
        return L.Filter(plan, cond)

    def _lower_grouping_sets(self, sel: _Select) -> L.LogicalPlan:
        """ROLLUP/CUBE/GROUPING SETS: one aggregate per grouping set,
        missing keys re-projected as typed NULLs, UNION ALL of the lot
        (the reference's Expand + single-aggregate plan produces the
        same relation — `ExpandExec.scala:1`; the union form trades one
        wide scan for set-count scans but keeps every aggregate on the
        fast grouped path)."""
        import copy as _c
        group_names = [g.name() for g in sel.group_by]
        if sel.items is None:
            raise AnalysisError(
                "grouping analytics need an explicit select list")
        out_names = []
        for e, a in sel.items:
            if a:
                out_names.append(a)
            elif isinstance(e, _QualifiedRef):
                out_names.append(e.col)  # t1.a projects as "a"
            else:
                out_names.append(e.name() if hasattr(e, "name")
                                 else repr(e))
        # input schema for typed NULL placeholders
        probe = _c.copy(sel)
        probe.ctes = None
        from_plan, _, _ = self._lower_from(probe)
        from_schema = from_plan.schema()

        plans = []
        for gset in sel.grouping_sets:
            sub = _c.copy(sel)
            sub.grouping_sets = None
            sub.ctes = None
            sub.order_by = None
            sub.limit = None
            sub.group_by = [ColumnRef(n) for n in gset] or None
            kept = []   # (expr, alias) | ("__null__", source_col_name)
            gset_bare = {n.split(".")[-1].lower() for n in gset}
            for e, a in sel.items:
                # match plain AND table-qualified refs on the bare name
                if isinstance(e, ColumnRef):
                    bare = e._name.split(".")[-1].lower()
                elif isinstance(e, _QualifiedRef):
                    bare = e.col.lower()
                else:
                    bare = None
                hit = bare is not None and any(
                    g.split(".")[-1].lower() == bare
                    for g in group_names)
                if hit and bare not in gset_bare:
                    kept.append(("__null__", bare))
                else:
                    kept.append((e, a))
            sub.items = [k for k in kept
                         if not isinstance(k[0], str)]
            p = self.lower(sub)
            sub_names = p.schema().names
            exprs = []
            pos = 0
            for k, out_name in zip(kept, out_names):
                if isinstance(k[0], str):  # "__null__" marker
                    dt = ColumnRef(k[1]).dtype(from_schema)
                    exprs.append(Alias(Literal(None, dt), out_name))
                else:
                    exprs.append(Alias(ColumnRef(sub_names[pos]),
                                       out_name))
                    pos += 1
            plans.append(L.Project(p, exprs))
        plan = plans[0]
        for q in plans[1:]:
            plan = L.Union(plan, q)
        plan = self._lower_order_limit(sel, plan)
        if sel.limit is not None:
            plan = L.Limit(plan, sel.limit)
        return plan

    def _extract_window_items(self, plan: L.LogicalPlan, items):
        """Pull WindowExpr nodes into Window plan nodes below the
        projection (shared with the DataFrame layer: one node — one
        sort — per distinct spec; collision-safe names)."""
        from ..window import extract_window_exprs
        from ..expr_array import contains_explode, extract_generators
        exprs = [Alias(e, a) if a else e for e, a in items]
        plan, out = extract_window_exprs(plan, exprs)
        if any(contains_explode(e) for e in out):
            plan, out = extract_generators(plan, out)
        rebuilt = []
        for (orig_e, a), new_e in zip(items, out):
            if a and isinstance(new_e, Alias):
                rebuilt.append((new_e.child, a))
            else:
                rebuilt.append((new_e, a))
        return plan, rebuilt

    def _lower_order_limit(self, sel: _Select, plan: L.LogicalPlan,
                           key_rewrite=None) -> L.LogicalPlan:
        if not sel.order_by:
            return plan
        out_names = plan.schema().names
        orders = []
        for e, asc, nulls_first in sel.order_by:
            if isinstance(e, Literal) and isinstance(e.value, int):
                idx = e.value - 1
                if not (0 <= idx < len(out_names)):
                    raise AnalysisError(f"ORDER BY position {e.value} out "
                                        f"of range")
                e = ColumnRef(out_names[idx])
            if _contains_agg(e):
                raise AnalysisError("ORDER BY aggregate expressions must "
                                    "use their select alias")
            if key_rewrite is not None:
                e = key_rewrite(e)
            orders.append(SortOrder(e, ascending=asc,
                                    nulls_first=nulls_first))
        return L.Sort(plan, orders)


def parse_sql(query: str, session) -> L.LogicalPlan:
    """Parse one statement into a logical plan bound to the session
    catalog (the `SparkSession.sql:613` entry point). DDL/DML commands
    (CREATE/DROP/INSERT/SHOW/DESCRIBE) run eagerly at parse time — the
    reference's RunnableCommand contract — and lower to a scan over
    their small result relation."""
    p = Parser(query, session)
    t = p.peek()
    if t.kind == "ident" and t.upper in ("CREATE", "DROP", "INSERT",
                                         "SHOW", "DESCRIBE", "DESC"):
        from .ddl import execute_command
        from ..io.sources import ArrowTableSource
        table = execute_command(p, session)
        return L.Scan(ArrowTableSource("__command__", table))
    sel = p.parse_statement()
    return Lowerer(session).lower(sel)

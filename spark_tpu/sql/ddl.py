"""DDL / DML commands: CREATE TABLE [AS SELECT], INSERT INTO, DROP
TABLE, SHOW TABLES, DESCRIBE.

Reference: the eager command layer in
`sql/core/.../execution/command/tables.scala:1` (+ `AstBuilder`'s DDL
rules). Commands run at parse time — the reference's RunnableCommand
contract — and return a small Arrow result table the session wraps as a
DataFrame, so ``spark.sql("SHOW TABLES").to_pandas()`` works the same
way it does there.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import pyarrow as pa

from ..expr import AnalysisError, Literal
from . import parser as _p


_TYPES = {
    "BIGINT": pa.int64(), "LONG": pa.int64(),
    "INT": pa.int32(), "INTEGER": pa.int32(),
    "SMALLINT": pa.int32(), "TINYINT": pa.int32(),
    "DOUBLE": pa.float64(), "FLOAT": pa.float32(), "REAL": pa.float32(),
    "STRING": pa.string(), "VARCHAR": pa.string(), "CHAR": pa.string(),
    "TEXT": pa.string(),
    "BOOLEAN": pa.bool_(), "BOOL": pa.bool_(),
    "DATE": pa.date32(), "TIMESTAMP": pa.timestamp("us"),
}


def _parse_type(p: "_p.Parser") -> pa.DataType:
    t = p.next()
    name = t.upper if t.kind == "ident" else None
    if name in ("DECIMAL", "NUMERIC"):
        prec, scale = 10, 0
        if p.eat_op("("):
            prec = int(p.next().value)
            if p.eat_op(","):
                scale = int(p.next().value)
            p.expect_op(")")
        return pa.decimal128(prec, scale)
    if name in ("VARCHAR", "CHAR"):
        if p.eat_op("("):
            p.next()
            p.expect_op(")")
        return pa.string()
    if name in _TYPES:
        return _TYPES[name]
    raise _p.ParseError(f"unknown column type {t.value!r}")


def _run_query(p: "_p.Parser", session) -> pa.Table:
    sel = p.parse_statement()
    plan = _p.Lowerer(session).lower(sel)
    from ..execution.executor import QueryExecution
    return QueryExecution(session, plan).collect()


def _literal_value(e):
    from ..expr import Neg
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, Neg) and isinstance(e.children[0], Literal):
        return -e.children[0].value
    raise _p.ParseError("INSERT ... VALUES requires literal values")


def _parse_values(p: "_p.Parser", session) -> pa.Table:
    rows: List[Tuple] = []
    while True:
        p.expect_op("(")
        row = []
        while True:
            e = p.parse_expr()
            row.append(_literal_value(e))
            if not p.eat_op(","):
                break
        p.expect_op(")")
        rows.append(tuple(row))
        if not p.eat_op(","):
            break
    width = len(rows[0])
    if any(len(r) != width for r in rows):
        raise _p.ParseError("VALUES rows differ in arity")
    cols = [pa.array([r[i] for r in rows]) for i in range(width)]
    return pa.table(cols, names=[f"col{i}" for i in range(width)])


def _ok(**cols) -> pa.Table:
    if not cols:
        return pa.table({"result": pa.array([], type=pa.string())})
    return pa.table({k: pa.array(v) for k, v in cols.items()})


def execute_command(p: "_p.Parser", session) -> pa.Table:
    """Parse + eagerly run one command statement; returns its result
    relation (RunnableCommand.run analog)."""
    cat = session.catalog
    if p.eat_kw("SHOW"):
        p.expect_kw("TABLES")
        p.eat_op(";")
        rows = cat.list_tables()
        return _ok(tableName=[r["name"] for r in rows],
                   isTemporary=[r["isTemporary"] for r in rows])

    if p.eat_kw("DESCRIBE") or p.eat_kw("DESC"):
        p.eat_kw("TABLE")
        name = p._ident()
        p.eat_op(";")
        rows = cat.describe(name)
        return _ok(col_name=[r["col_name"] for r in rows],
                   data_type=[r["data_type"] for r in rows],
                   nullable=[r["nullable"] for r in rows])

    if p.eat_kw("DROP"):
        is_view = p.eat_kw("VIEW")
        if not is_view:
            p.expect_kw("TABLE")
        if_exists = False
        if p.eat_kw("IF"):
            p.expect_kw("EXISTS")
            if_exists = True
        name = p._ident()
        p.eat_op(";")
        cat.drop_table(name, if_exists=if_exists, temp_only=is_view)
        return _ok()

    if p.eat_kw("INSERT"):
        p.expect_kw("INTO")
        p.eat_kw("TABLE")
        name = p._ident()
        if p.eat_kw("VALUES"):
            data = _parse_values(p, session)
            p.eat_op(";")
        else:
            data = _run_query(p, session)
        cat.insert_into(name, data)
        return _ok(inserted=[data.num_rows])

    if p.eat_kw("CREATE"):
        or_replace = False
        if p.eat_kw("OR"):
            p.expect_kw("REPLACE")
            or_replace = True
        p.expect_kw("TABLE")
        if_not_exists = False
        if p.eat_kw("IF"):
            p.expect_kw("NOT")
            p.expect_kw("EXISTS")
            if_not_exists = True
        name = p._ident()
        schema: Optional[pa.Schema] = None
        if p.at_op("("):
            p.next()
            fields = []
            while True:
                col = p._ident()
                typ = _parse_type(p)
                fields.append(pa.field(col, typ))
                if not p.eat_op(","):
                    break
            p.expect_op(")")
            schema = pa.schema(fields)
        if p.eat_kw("USING"):
            fmt = p._ident()
            if fmt.lower() != "parquet":
                raise AnalysisError(
                    f"only USING parquet is supported, got {fmt!r}")
        data = None
        if p.at_kw("AS") or p.at_kw("SELECT") or p.at_kw("WITH"):
            p.eat_kw("AS")
            data = _run_query(p, session)
        else:
            p.eat_op(";")
        cat.create_table(name, schema=schema, data=data,
                         if_not_exists=if_not_exists,
                         or_replace=or_replace)
        return _ok()

    t = p.peek()
    raise _p.ParseError(f"unsupported command at {t.pos}: {t.value!r}")

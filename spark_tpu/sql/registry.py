"""Function registry: one declarative table driving SQL function
resolution (reference: `analysis/FunctionRegistry.scala` — expression
builders keyed by name with arity checking), shared by the SQL parser
and the DataFrame `functions` module.

Each entry: NAME -> (builder, min_args, max_args). Builders receive
already-parsed Expression args; entries whose parameters must be
literals (regexp patterns, pad strings, trunc formats) unwrap them and
raise AnalysisError otherwise.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from .. import expr_fns as X
from ..expr import (AnalysisError, Cast, CaseWhen, Coalesce, ConcatLit,
                    DateAdd, EqNullSafe, Expression, ExtractDay,
                    ExtractMonth, ExtractYear, IsNull, Like, Literal, Lower,
                    Neg, Not, Pmod, StringLength, Substring, Trim, Upper)
from .. import types as T


def _lit_str(e: Expression, fn: str) -> str:
    if isinstance(e, Literal) and isinstance(e.value, str):
        return e.value
    raise AnalysisError(f"{fn} requires a string literal argument")


def _lit_int(e: Expression, fn: str) -> int:
    if isinstance(e, Literal) and isinstance(e.value, int):
        return int(e.value)
    raise AnalysisError(f"{fn} requires an integer literal argument")


#: NAME -> (builder(args) -> Expression, min_args, max_args)
REGISTRY: Dict[str, Tuple[Callable, int, int]] = {}


def register(name: str, builder: Callable, lo: int, hi: int) -> None:
    REGISTRY[name.upper()] = (builder, lo, hi)


def lookup(name: str, args: List[Expression]) -> Optional[Expression]:
    """Build the expression for `name(args)`, or None when unknown.
    Raises AnalysisError on arity mismatch for a known function."""
    entry = REGISTRY.get(name.upper())
    if entry is None:
        return None
    builder, lo, hi = entry
    if not (lo <= len(args) <= hi):
        want = str(lo) if lo == hi else f"{lo}..{hi}"
        raise AnalysisError(
            f"{name} expects {want} arguments, got {len(args)}")
    return builder(args)


def _u(cls):
    return lambda a: cls(a[0])


def _b(cls):
    return lambda a: cls(a[0], a[1])


# -- math -------------------------------------------------------------------
register("ABS", _u(X.Abs), 1, 1)
register("SQRT", _u(X.Sqrt), 1, 1)
register("CBRT", _u(X.Cbrt), 1, 1)
register("EXP", _u(X.Exp), 1, 1)
register("EXPM1", _u(X.Expm1), 1, 1)
register("LN", _u(X.Ln), 1, 1)
register("LOG10", _u(X.Log10), 1, 1)
register("LOG2", _u(X.Log2), 1, 1)
register("LOG1P", _u(X.Log1p), 1, 1)
register("LOG", lambda a: X.Ln(a[0]) if len(a) == 1
         else X.Logarithm(a[0], a[1]), 1, 2)
register("POW", _b(X.Pow), 2, 2)
register("POWER", _b(X.Pow), 2, 2)
register("SIN", _u(X.Sin), 1, 1)
register("COS", _u(X.Cos), 1, 1)
register("TAN", _u(X.Tan), 1, 1)
register("COT", _u(X.Cot), 1, 1)
register("ASIN", _u(X.Asin), 1, 1)
register("ACOS", _u(X.Acos), 1, 1)
register("ATAN", _u(X.Atan), 1, 1)
register("ATAN2", _b(X.Atan2), 2, 2)
register("SINH", _u(X.Sinh), 1, 1)
register("COSH", _u(X.Cosh), 1, 1)
register("TANH", _u(X.Tanh), 1, 1)
register("HYPOT", _b(X.Hypot), 2, 2)
register("DEGREES", _u(X.Degrees), 1, 1)
register("RADIANS", _u(X.Radians), 1, 1)
register("RINT", _u(X.Rint), 1, 1)
register("SIGN", _u(X.Signum), 1, 1)
register("SIGNUM", _u(X.Signum), 1, 1)
register("CEIL", _u(X.Ceil), 1, 1)
register("CEILING", _u(X.Ceil), 1, 1)
register("FLOOR", _u(X.Floor), 1, 1)
register("ROUND", lambda a: X.Round(
    a[0], _lit_int(a[1], "ROUND") if len(a) == 2 else 0), 1, 2)
register("FACTORIAL", _u(X.Factorial), 1, 1)
register("PMOD", _b(Pmod), 2, 2)
register("MOD", lambda a: a[0] % a[1], 2, 2)
register("SHIFTLEFT", _b(X.ShiftLeft), 2, 2)
register("SHIFTRIGHT", _b(X.ShiftRight), 2, 2)
register("BIT_COUNT", _u(X.BitCount), 1, 1)
register("GREATEST", lambda a: X.Greatest(*a), 2, 64)
register("LEAST", lambda a: X.Least(*a), 2, 64)

# -- null / conditional -----------------------------------------------------
register("COALESCE", lambda a: Coalesce(*a), 1, 64)
register("NVL", lambda a: X.Nvl(a[0], a[1]), 2, 2)
register("IFNULL", lambda a: X.Nvl(a[0], a[1]), 2, 2)
register("NVL2", lambda a: X.Nvl2(a[0], a[1], a[2]), 3, 3)
register("NULLIF", _b(X.NullIf), 2, 2)
register("IF", lambda a: X.If(a[0], a[1], a[2]), 3, 3)
register("ISNULL", lambda a: IsNull(a[0]), 1, 1)
register("ISNOTNULL", lambda a: Not(IsNull(a[0])), 1, 1)
register("ISNAN", _u(X.IsNan), 1, 1)
register("NANVL", lambda a: X.Nanvl(a[0], a[1]), 2, 2)

# -- datetime ---------------------------------------------------------------
register("YEAR", _u(ExtractYear), 1, 1)
register("MONTH", _u(ExtractMonth), 1, 1)
register("DAY", _u(ExtractDay), 1, 1)
register("DAYOFMONTH", _u(ExtractDay), 1, 1)
register("QUARTER", _u(X.Quarter), 1, 1)
register("DAYOFWEEK", _u(X.DayOfWeek), 1, 1)
register("WEEKDAY", _u(X.WeekDay), 1, 1)
register("DAYOFYEAR", _u(X.DayOfYear), 1, 1)
register("WEEKOFYEAR", _u(X.WeekOfYear), 1, 1)
register("LAST_DAY", _u(X.LastDay), 1, 1)
register("NEXT_DAY", lambda a: X.NextDay(
    a[0], _lit_str(a[1], "NEXT_DAY")), 2, 2)
register("ADD_MONTHS", _b(X.AddMonths), 2, 2)
register("MONTHS_BETWEEN", _b(X.MonthsBetween), 2, 2)
register("DATEDIFF", _b(X.DateDiff), 2, 2)
register("DATE_ADD", _b(DateAdd), 2, 2)
register("DATE_SUB", lambda a: DateAdd(a[0], Neg(a[1])), 2, 2)
register("TRUNC", lambda a: X.TruncDate(
    a[0], _lit_str(a[1], "TRUNC")), 2, 2)
register("MAKE_DATE", lambda a: X.MakeDate(a[0], a[1], a[2]), 3, 3)

# -- strings ----------------------------------------------------------------
register("UPPER", _u(Upper), 1, 1)
register("UCASE", _u(Upper), 1, 1)
register("LOWER", _u(Lower), 1, 1)
register("LCASE", _u(Lower), 1, 1)
register("TRIM", _u(Trim), 1, 1)
register("LTRIM", _u(X.Ltrim), 1, 1)
register("RTRIM", _u(X.Rtrim), 1, 1)
register("LENGTH", _u(StringLength), 1, 1)
register("CHAR_LENGTH", _u(StringLength), 1, 1)
register("REVERSE", _u(X.Reverse), 1, 1)
register("INITCAP", _u(X.InitCap), 1, 1)
register("LPAD", lambda a: X.Lpad(
    a[0], _lit_int(a[1], "LPAD"),
    _lit_str(a[2], "LPAD") if len(a) == 3 else " "), 2, 3)
register("RPAD", lambda a: X.Rpad(
    a[0], _lit_int(a[1], "RPAD"),
    _lit_str(a[2], "RPAD") if len(a) == 3 else " "), 2, 3)
register("REPLACE", lambda a: X.StringReplace(
    a[0], _lit_str(a[1], "REPLACE"),
    _lit_str(a[2], "REPLACE") if len(a) == 3 else ""), 2, 3)
register("TRANSLATE", lambda a: X.Translate(
    a[0], _lit_str(a[1], "TRANSLATE"), _lit_str(a[2], "TRANSLATE")), 3, 3)
register("REPEAT", lambda a: X.Repeat(
    a[0], _lit_int(a[1], "REPEAT")), 2, 2)
register("INSTR", lambda a: X.Instr(
    a[0], _lit_str(a[1], "INSTR")), 2, 2)
register("LOCATE", lambda a: X.Instr(
    a[1], _lit_str(a[0], "LOCATE")), 2, 2)
register("ASCII", _u(X.Ascii), 1, 1)
register("RLIKE", lambda a: X.RLike(
    a[0], _lit_str(a[1], "RLIKE")), 2, 2)
register("REGEXP_LIKE", lambda a: X.RLike(
    a[0], _lit_str(a[1], "REGEXP_LIKE")), 2, 2)
register("REGEXP_REPLACE", lambda a: X.RegexpReplace(
    a[0], _lit_str(a[1], "REGEXP_REPLACE"),
    _lit_str(a[2], "REGEXP_REPLACE")), 3, 3)
register("REGEXP_EXTRACT", lambda a: X.RegexpExtract(
    a[0], _lit_str(a[1], "REGEXP_EXTRACT"),
    _lit_int(a[2], "REGEXP_EXTRACT") if len(a) == 3 else 1), 2, 3)
register("CONTAINS", lambda a: X.Contains(
    a[0], _lit_str(a[1], "CONTAINS")), 2, 2)
register("STARTSWITH", lambda a: X.StartsWith(
    a[0], _lit_str(a[1], "STARTSWITH")), 2, 2)
register("ENDSWITH", lambda a: X.EndsWith(
    a[0], _lit_str(a[1], "ENDSWITH")), 2, 2)


def _concat(args: List[Expression]) -> Expression:
    if any(isinstance(p, Literal) and p.value is None for p in args):
        # reference semantics: concat is NULL if ANY argument is NULL
        return Literal(None, T.STRING)
    non_lit = [i for i, p in enumerate(args) if not isinstance(p, Literal)]
    if len(non_lit) != 1:
        raise AnalysisError(
            "CONCAT supports exactly one non-literal string argument "
            "(general column-column concat needs a product dictionary)")
    i = non_lit[0]
    prefix = "".join(str(p.value) for p in args[:i])
    suffix = "".join(str(p.value) for p in args[i + 1:])
    return ConcatLit(args[i], prefix, suffix)


register("CONCAT", _concat, 1, 64)


# -- arrays (collectionOperations.scala) ------------------------------------

from .. import expr_array as _arr  # noqa: E402

register("ARRAY", lambda a: _arr.MakeArray(*a), 1, 64)
register("SIZE", lambda a: _arr.Size(a[0]), 1, 1)
register("CARDINALITY", lambda a: _arr.Size(a[0]), 1, 1)
register("ARRAY_CONTAINS", lambda a: _arr.ArrayContains(a[0], a[1]), 2, 2)
register("ELEMENT_AT", lambda a: _arr.ElementAt(a[0], a[1]), 2, 2)
register("EXPLODE", lambda a: _arr.Explode(a[0]), 1, 1)
register("EXPLODE_OUTER", lambda a: _arr.Explode(a[0], outer=True), 1, 1)

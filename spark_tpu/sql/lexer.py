"""SQL lexer: a hand-rolled tokenizer for the SELECT subset.

The reference generates its lexer from the ANTLR grammar
(`sql/catalyst/src/main/antlr4/.../parser/SqlBase.g4`); this engine's
grammar is small enough that a direct scanner is simpler and yields
better error messages (token + position).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional


class ParseError(Exception):
    pass


@dataclass(frozen=True)
class Token:
    kind: str       # 'ident' | 'number' | 'string' | 'op' | 'eof'
    value: str      # normalized text (idents/keywords upper-cased in .upper)
    pos: int        # character offset in the source (for error messages)

    @property
    def upper(self) -> str:
        return self.value.upper()


# multi-char operators first so the scanner is greedy
_OPS = ("<>", "<=", ">=", "!=", "||", "=", "<", ">", "+", "-", "*", "/",
        "%", "(", ")", ",", ".", ";")


def tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and text.startswith("--", i):
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                raise ParseError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if text[j] == "'" and j + 1 < n and text[j + 1] == "'":
                    buf.append("'")
                    j += 2
                    continue
                if text[j] == "'":
                    break
                buf.append(text[j])
                j += 1
            if j >= n:
                raise ParseError(f"unterminated string literal at {i}")
            out.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"' or c == "`":
            close = c
            j = text.find(close, i + 1)
            if j < 0:
                raise ParseError(f"unterminated quoted identifier at {i}")
            out.append(Token("ident", text[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = text[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j + 1 < n and \
                        (text[j + 1].isdigit() or text[j + 1] in "+-"):
                    seen_exp = True
                    j += 2 if text[j + 1] in "+-" else 1
                else:
                    break
            out.append(Token("number", text[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            out.append(Token("ident", text[i:j], i))
            i = j
            continue
        for op in _OPS:
            if text.startswith(op, i):
                out.append(Token("op", op, i))
                i += len(op)
                break
        else:
            raise ParseError(f"unexpected character {c!r} at {i}")
    out.append(Token("eof", "", n))
    return out

"""Probabilistic sketches on device: Bloom filter + Count-Min.

The reference ships JVM implementations (`common/sketch/BloomFilter.java`,
`CountMinSketch.java`) used by DataFrame stat functions and runtime join
filters. Here both are jnp bit/scatter kernels over device arrays: the
Bloom filter stores one bit per byte (scatter-max is the TPU-friendly
"bitwise or"; 8x the memory of a packed bitmap, all of it HBM-cheap),
and Count-Min is a [depth, width] scatter-add table.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_MIX_MUL = np.uint64(0xBF58476D1CE4E5B9)
_MIX_MUL2 = np.uint64(0x94D049BB133111EB)


def _mix64(x, seed: int):
    salt = (seed * 0x9E3779B97F4A7C15 or 1) & 0xFFFFFFFFFFFFFFFF
    u = x.astype(jnp.uint64) ^ np.uint64(salt)
    u = (u ^ (u >> 30)) * _MIX_MUL
    u = (u ^ (u >> 27)) * _MIX_MUL2
    return u ^ (u >> 31)


class BloomFilter:
    """Membership sketch over int64 values.

    `num_bits` per expected item follows the reference's sizing
    (`BloomFilter.optimalNumOfBits`): m = -n ln(fpp) / ln(2)^2,
    k = m/n ln(2) hash functions."""

    def __init__(self, bits, num_hashes: int):
        self.bits = bits          # uint8[m], one logical bit per byte
        self.num_hashes = num_hashes

    @staticmethod
    def sizing(expected_items: int, fpp: float = 0.03):
        m = int(max(64, -expected_items * np.log(fpp) / (np.log(2) ** 2)))
        k = int(max(1, round(m / max(1, expected_items) * np.log(2))))
        return m, min(k, 8)

    @classmethod
    def build(cls, values, expected_items: Optional[int] = None,
              fpp: float = 0.03, mask=None) -> "BloomFilter":
        n = int(values.shape[0])
        m, k = cls.sizing(expected_items or n, fpp)
        bits = jnp.zeros((m,), jnp.uint8)
        x = values.astype(jnp.int64)
        for s in range(k):
            idx = (_mix64(x, s) % np.uint64(m)).astype(jnp.int32)
            if mask is not None:
                idx = jnp.where(mask, idx, m)
            bits = bits.at[idx].max(jnp.ones_like(idx, jnp.uint8),
                                    mode="drop")
        return cls(bits, k)

    def might_contain(self, values):
        """Vectorized membership probe: False is definite, True is
        probabilistic (the join-prefilter contract)."""
        m = self.bits.shape[0]
        x = values.astype(jnp.int64)
        out = jnp.ones(values.shape, jnp.bool_)
        for s in range(self.num_hashes):
            idx = (_mix64(x, s) % np.uint64(m)).astype(jnp.int32)
            out = out & (jnp.take(self.bits, idx) > 0)
        return out


class CountMinSketch:
    """Frequency sketch: [depth, width] counters, point query = min over
    rows (reference: CountMinSketch.java)."""

    def __init__(self, table, depth: int, width: int):
        self.table = table
        self.depth = depth
        self.width = width

    @staticmethod
    def sizing(eps: float = 0.001, confidence: float = 0.99):
        width = int(np.ceil(2.0 / eps))
        depth = int(np.ceil(-np.log(1.0 - confidence) / np.log(2.0)))
        return max(1, depth), max(16, width)

    @classmethod
    def build(cls, values, eps: float = 0.001, confidence: float = 0.99,
              mask=None) -> "CountMinSketch":
        depth, width = cls.sizing(eps, confidence)
        table = jnp.zeros((depth, width), jnp.int64)
        x = values.astype(jnp.int64)
        ones = jnp.ones(values.shape, jnp.int64)
        for d in range(depth):
            idx = (_mix64(x, d) % np.uint64(width)).astype(jnp.int32)
            if mask is not None:
                idx = jnp.where(mask, idx, width)
            table = table.at[d].set(
                table[d].at[idx].add(ones, mode="drop"))
        return cls(table, depth, width)

    def estimate(self, values):
        x = values.astype(jnp.int64)
        est = None
        for d in range(self.depth):
            idx = (_mix64(x, d) % np.uint64(self.width)).astype(jnp.int32)
            row = jnp.take(self.table[d], idx)
            est = row if est is None else jnp.minimum(est, row)
        return est

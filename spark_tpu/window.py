"""Window function API + expression node.

Mirrors pyspark's `Window`/`WindowSpec` builder surface
(`python/pyspark/sql/window.py`) and the reference's WindowExpression
(`sql/catalyst/.../expressions/windowExpressions.scala`): a window
function + its spec travel as ONE expression; the DataFrame layer (and
the SQL frontend) extract them into a `Window` plan node, and
`WindowExec` evaluates every function of a shared spec over one sorted
permutation (execution/window.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from . import types as T
from .expr import (AnalysisError, ColumnRef, Expression, SortOrder)

RANKING_KINDS = ("row_number", "rank", "dense_rank")
SHIFT_KINDS = ("lag", "lead")
AGG_KINDS = ("sum", "count", "min", "max", "avg")


#: frame boundary sentinels (pyspark's Window.unboundedPreceding /
#: unboundedFollowing / currentRow values)
UNBOUNDED_PRECEDING = -(1 << 63)
UNBOUNDED_FOLLOWING = (1 << 63) - 1
CURRENT_ROW = 0


class WindowSpec:
    def __init__(self, partition_by: Sequence[Expression] = (),
                 order_by: Sequence[SortOrder] = (),
                 frame: Optional[Tuple[str, int, int]] = None):
        self._partition = tuple(partition_by)
        self._order = tuple(order_by)
        # ("rows"|"range", start, end) with UNBOUNDED_* sentinels, or
        # None for the Spark default (RANGE UNBOUNDED PRECEDING ..
        # CURRENT ROW when ordered, the whole partition otherwise)
        self._frame = frame

    def partition_by(self, *cols) -> "WindowSpec":
        from .functions import _expr
        return WindowSpec(tuple(_expr(c) for c in cols), self._order,
                          self._frame)

    partitionBy = partition_by

    def order_by(self, *orders) -> "WindowSpec":
        from .functions import _expr
        os = []
        for o in orders:
            os.append(o if isinstance(o, SortOrder)
                      else SortOrder(_expr(o), ascending=True))
        return WindowSpec(self._partition, tuple(os), self._frame)

    orderBy = order_by

    def rows_between(self, start: int, end: int) -> "WindowSpec":
        """ROWS BETWEEN: physical row offsets relative to the current
        row (reference: SpecifiedWindowFrame RowFrame)."""
        return WindowSpec(self._partition, self._order,
                          ("rows", int(start), int(end)))

    rowsBetween = rows_between

    def range_between(self, start: int, end: int) -> "WindowSpec":
        """RANGE BETWEEN: offsets in ORDER-BY key value space; needs a
        single numeric order key (reference: RangeFrame)."""
        return WindowSpec(self._partition, self._order,
                          ("range", int(start), int(end)))

    rangeBetween = range_between


class Window:
    """pyspark-style entry points: Window.partitionBy(...).orderBy(...)."""

    unboundedPreceding = UNBOUNDED_PRECEDING
    unboundedFollowing = UNBOUNDED_FOLLOWING
    currentRow = CURRENT_ROW

    @staticmethod
    def partition_by(*cols) -> WindowSpec:
        return WindowSpec().partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*orders) -> WindowSpec:
        return WindowSpec().order_by(*orders)

    orderBy = order_by


class WindowExpr(Expression):
    """One window function over a spec. `children` flattens
    [arg?] + partition exprs + order SortOrders so generic tree
    transforms (qualified-name rewriting, constant folding) reach every
    sub-expression; `map_children` rebuilds the structure."""

    def __init__(self, kind: str, arg: Optional[Expression],
                 spec: WindowSpec, offset: int = 1, default=None):
        self.kind = kind
        self.arg = arg
        self.spec = spec
        self.offset = offset
        self.default = default
        kids: List[Expression] = [] if arg is None else [arg]
        kids += list(spec._partition)
        kids += list(spec._order)
        self.children = tuple(kids)

    def map_children(self, f):
        kids = [f(c) for c in self.children]
        i = 0
        arg = None
        if self.arg is not None:
            arg = kids[0]
            i = 1
        np_ = len(self.spec._partition)
        partition = tuple(kids[i:i + np_])
        order = tuple(kids[i + np_:])
        return WindowExpr(self.kind, arg,
                          WindowSpec(partition, order, self.spec._frame),
                          self.offset, self.default)

    def dtype(self, schema: T.Schema) -> T.DataType:
        if self.kind in RANKING_KINDS or self.kind == "count":
            return T.LONG
        if self.kind in SHIFT_KINDS:
            return self.arg.dtype(schema)
        from .expr_agg import Avg, Sum
        if self.kind == "sum":
            return Sum(self.arg).result_type(schema)
        if self.kind == "avg":
            return Avg(self.arg).result_type(schema)
        return self.arg.dtype(schema)  # min/max

    def nullable(self, schema) -> bool:
        if self.kind in RANKING_KINDS or self.kind == "count":
            return False
        return True

    def eval(self, batch):
        raise AnalysisError(
            f"window function {self.kind} must be planned through a "
            f"Window node (use select/withColumn)")

    def over(self, spec: WindowSpec) -> "WindowExpr":
        if self.kind in RANKING_KINDS + SHIFT_KINDS and not spec._order:
            # the reference rejects ranking/offset functions without a
            # window ordering at analysis time; silent arbitrary-order
            # ranks would be worse
            raise AnalysisError(
                f"{self.kind}() requires an ORDER BY in its window "
                f"specification")
        return WindowExpr(self.kind, self.arg, spec, self.offset,
                          self.default)

    def __repr__(self):
        parts = [] if self.arg is None else [repr(self.arg)]
        spec = (f"partition by {list(self.spec._partition)!r} "
                f"order by {list(self.spec._order)!r}")
        if self.spec._frame is not None:
            # the frame MUST be in the fingerprint: the compiled-stage
            # cache keys on describe(), which reprs window expressions
            spec += f" frame {self.spec._frame!r}"
        return f"{self.kind}({', '.join(parts)}) OVER ({spec})"


def contains_window(e: Expression) -> bool:
    if isinstance(e, WindowExpr):
        return True
    return any(contains_window(c) for c in e.children)


#: aggregate class name -> window kind (shared by AggregateFunction.over
#: and the SQL frontend's OVER lowering — keep the one copy)
AGG_WINDOW_KINDS = {"Sum": "sum", "Count": "count", "Min": "min",
                    "Max": "max", "Avg": "avg"}


def _spec_key(w: WindowExpr) -> tuple:
    return (tuple(repr(p) for p in w.spec._partition),
            tuple(repr(o) for o in w.spec._order))


def extract_window_exprs(plan, exprs: Sequence[Expression]):
    """Replace WindowExpr occurrences in `exprs` with column references
    and return (plan wrapped in Window nodes, rewritten exprs).

    - functions sharing a (partition, order) spec share ONE Window node,
      so one sorted permutation serves them all;
    - generated output names never collide with existing columns (a
      desired alias that would collide gets a fresh internal name and is
      re-aliased by the enclosing projection)."""
    from .expr import Alias, ColumnRef
    from .plan import logical as L
    if not any(contains_window(e) for e in exprs):
        # keep the window-free fast path lazy: no schema() walk
        return plan, list(exprs)
    taken = set(plan.schema().names)
    collected: List[tuple] = []  # (WindowExpr, out_name)
    counter = [0]

    def fresh(want: Optional[str]) -> str:
        if want and want not in taken:
            taken.add(want)
            return want
        while True:
            name = f"_w{counter[0]}"
            counter[0] += 1
            if name not in taken:
                taken.add(name)
                return name

    def extract(e: Expression, top_name: Optional[str]) -> Expression:
        if isinstance(e, WindowExpr):
            name = fresh(top_name)
            collected.append((e, name))
            return ColumnRef(name)
        return e.map_children(lambda c: extract(c, None))

    out: List[Expression] = []
    for e in exprs:
        if not contains_window(e):
            out.append(e)
        elif isinstance(e, Alias):
            inner = extract(e.child, e.name())
            out.append(inner if isinstance(inner, ColumnRef)
                       and inner.name() == e.name() else
                       Alias(inner, e.name()))
        else:
            out.append(extract(e, None))

    groups: dict = {}
    order: List[tuple] = []
    for w, name in collected:
        k = _spec_key(w)
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append((w, name))
    for k in order:
        plan, group = _project_computed_keys(plan, groups[k], fresh)
        plan = L.WindowPlan(plan, group)
    return plan, out


def _project_computed_keys(plan, group, fresh):
    """Computed partition/order keys get projected into named columns
    below the Window node, so WindowExec can declare a hash-clustered
    distribution instead of degrading to AllTuples (gathering the whole
    dataset to every shard — round-4 VERDICT weak #8)."""
    from .expr import Alias, ColumnRef
    from .plan import logical as L
    spec = group[0][0].spec
    added: List[Expression] = []

    def as_ref(e: Expression) -> Expression:
        base = e
        while isinstance(base, Alias):
            base = base.child
        if isinstance(base, ColumnRef):
            return base
        name = fresh(None)
        added.append(Alias(e, name))
        return ColumnRef(name)

    new_partition = tuple(as_ref(p) for p in spec._partition)
    new_order = tuple(SortOrder(as_ref(o.child), o.ascending,
                                o.nulls_first) for o in spec._order)
    if not added:
        return plan, group
    keep = [ColumnRef(n) for n in plan.schema().names]
    plan = L.Project(plan, keep + added)
    # frames are per-FUNCTION: rebuild each spec with its own frame
    new_group = [(WindowExpr(w.kind, w.arg,
                             WindowSpec(new_partition, new_order,
                                        w.spec._frame),
                             w.offset, w.default), name)
                 for w, name in group]
    return plan, new_group


def row_number() -> WindowExpr:
    return WindowExpr("row_number", None, WindowSpec())


def rank() -> WindowExpr:
    return WindowExpr("rank", None, WindowSpec())


def dense_rank() -> WindowExpr:
    return WindowExpr("dense_rank", None, WindowSpec())


def lag(e, offset: int = 1, default=None) -> WindowExpr:
    from .functions import _expr
    return WindowExpr("lag", _expr(e), WindowSpec(), offset=offset,
                      default=default)


def lead(e, offset: int = 1, default=None) -> WindowExpr:
    from .functions import _expr
    return WindowExpr("lead", _expr(e), WindowSpec(), offset=-offset,
                      default=default)

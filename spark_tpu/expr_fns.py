"""Expression-library breadth: math, datetime, string, bitwise, and
conditional functions.

Reference coverage: `sql/catalyst/.../expressions/mathExpressions.scala`,
`datetimeExpressions.scala`, `stringExpressions.scala`,
`regexpExpressions.scala`, `bitwiseExpressions.scala`,
`nullExpressions.scala` — re-designed for the TPU substrate:

- numeric/date functions lower to whole-column jnp ops (XLA-fused);
- string functions run on the HOST DICTIONARY, not per row: a
  dictionary-encoded column makes upper/regexp/replace a rewrite of the
  (small) dictionary plus an O(1) per-row code remap or table gather —
  including full Python `re` regexps, which the reference needs codegen
  + UTF8String machinery for (SURVEY.md section 7 'Strings on TPU').

Null semantics follow the reference: NULL in -> NULL out unless
documented otherwise (coalesce/greatest/least skip NULLs; ln/log of
non-positive values is NULL, matching Spark's `Logarithm`).
"""

from __future__ import annotations

import math
import re
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from . import types as T
from .columnar import Batch
from .expr import (AnalysisError, CaseWhen, Cast, Coalesce, EQ, Expression,
                   IsNull, Literal, Not, Vec, _and_valid, _civil_from_days,
                   _wrap, cast_vec)


def _to_f64(v: Vec) -> Vec:
    return cast_vec(v, T.DOUBLE)


# ---------------------------------------------------------------------------
# Math
# ---------------------------------------------------------------------------

class _MathUnary(Expression):
    """f(x) -> DOUBLE elementwise; rows outside `_domain` become NULL
    (Spark's Logarithm & friends return NULL, not NaN, off-domain)."""

    _fn: Callable = None
    _domain: Optional[Callable] = None  # data -> bool mask of valid inputs

    def __init__(self, child: Expression):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return T.DOUBLE

    def eval(self, batch):
        v = _to_f64(self.children[0].eval(batch))
        data = type(self)._fn(v.data)
        validity = v.validity
        if type(self)._domain is not None:
            ok = type(self)._domain(v.data)
            data = jnp.where(ok, data, 0.0)
            validity = ok if validity is None else (validity & ok)
        return Vec(data, T.DOUBLE, validity)

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r})"


def _make_unary(name: str, fn, domain=None):
    cls = type(name, (_MathUnary,), {"_fn": staticmethod(fn)})
    if domain is not None:
        cls._domain = staticmethod(domain)
    return cls


Sqrt = _make_unary("Sqrt", jnp.sqrt)          # sqrt(-x) = NaN like Spark
Exp = _make_unary("Exp", jnp.exp)
Expm1 = _make_unary("Expm1", jnp.expm1)
Ln = _make_unary("Ln", jnp.log, domain=lambda x: x > 0)
Log10 = _make_unary("Log10", jnp.log10, domain=lambda x: x > 0)
Log2 = _make_unary("Log2", jnp.log2, domain=lambda x: x > 0)
Log1p = _make_unary("Log1p", jnp.log1p, domain=lambda x: x > -1)
Sin = _make_unary("Sin", jnp.sin)
Cos = _make_unary("Cos", jnp.cos)
Tan = _make_unary("Tan", jnp.tan)
Cot = _make_unary("Cot", lambda x: 1.0 / jnp.tan(x))
Asin = _make_unary("Asin", jnp.arcsin)
Acos = _make_unary("Acos", jnp.arccos)
Atan = _make_unary("Atan", jnp.arctan)
Sinh = _make_unary("Sinh", jnp.sinh)
Cosh = _make_unary("Cosh", jnp.cosh)
Tanh = _make_unary("Tanh", jnp.tanh)
Cbrt = _make_unary("Cbrt", jnp.cbrt)
Degrees = _make_unary("Degrees", jnp.degrees)
Radians = _make_unary("Radians", jnp.radians)
Rint = _make_unary("Rint", jnp.rint)
Signum = _make_unary("Signum", jnp.sign)


class _MathBinary(Expression):
    _fn: Callable = None

    def __init__(self, left, right):
        self.children = (_wrap(left), _wrap(right))

    def dtype(self, schema):
        return T.DOUBLE

    def eval(self, batch):
        l = _to_f64(self.children[0].eval(batch))
        r = _to_f64(self.children[1].eval(batch))
        return Vec(type(self)._fn(l.data, r.data), T.DOUBLE,
                   _and_valid(l.validity, r.validity))

    def __repr__(self):
        return (f"{type(self).__name__.lower()}"
                f"({self.children[0]!r}, {self.children[1]!r})")


class Pow(_MathBinary):
    _fn = staticmethod(jnp.power)


class Atan2(_MathBinary):
    _fn = staticmethod(jnp.arctan2)


class Hypot(_MathBinary):
    _fn = staticmethod(jnp.hypot)


class Logarithm(_MathBinary):
    """log(base, x): NULL when x <= 0 or base <= 0 (reference:
    mathExpressions.scala Logarithm)."""

    def eval(self, batch):
        b = _to_f64(self.children[0].eval(batch))
        x = _to_f64(self.children[1].eval(batch))
        ok = (x.data > 0) & (b.data > 0)
        data = jnp.where(ok, jnp.log(jnp.where(x.data > 0, x.data, 1.0))
                         / jnp.log(jnp.where(b.data > 0, b.data, 2.0)), 0.0)
        validity = _and_valid(_and_valid(b.validity, x.validity), ok)
        return Vec(data, T.DOUBLE, validity)


class Abs(Expression):
    """Type-preserving |x| (decimal scale preserved: scaled-int abs)."""

    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return self.children[0].dtype(schema)

    def eval(self, batch):
        v = self.children[0].eval(batch)
        return Vec(jnp.abs(v.data), v.dtype, v.validity)

    def __repr__(self):
        return f"abs({self.children[0]!r})"


def _half_up(data, scale_pow: float):
    """HALF_UP rounding of float data to `scale_pow` = 10^d (Spark's
    `round`, away from zero on ties)."""
    scaled = data * scale_pow
    return jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5) / scale_pow


class Round(Expression):
    """round(x, d) HALF_UP (reference: mathExpressions.scala Round).
    Integers pass through for d >= 0; decimals round exactly on the
    scaled-int representation; floats via f64."""

    def __init__(self, child, d: int = 0):
        self.children = (_wrap(child),)
        self.d = int(d)

    def dtype(self, schema):
        dt = self.children[0].dtype(schema)
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(dt.precision, max(0, min(dt.scale, self.d)))
        if isinstance(dt, T.IntegralType):
            return dt
        return T.DOUBLE

    def eval(self, batch):
        v = self.children[0].eval(batch)
        dt = v.dtype
        if isinstance(dt, T.DecimalType):
            out_scale = max(0, min(dt.scale, self.d))
            drop = dt.scale - out_scale
            if drop <= 0:
                return Vec(v.data, T.DecimalType(dt.precision, out_scale),
                           v.validity)
            p = np.int64(10 ** drop)
            absd = jnp.abs(v.data)
            q = (absd + p // 2) // p  # HALF_UP on the scaled int
            return Vec(jnp.sign(v.data) * q,
                       T.DecimalType(dt.precision, out_scale), v.validity)
        if isinstance(dt, T.IntegralType):
            if self.d >= 0:
                return v
            p = np.int64(10 ** (-self.d))
            absd = jnp.abs(v.data)
            q = ((absd + p // 2) // p) * p
            return Vec((jnp.sign(v.data) * q).astype(v.data.dtype), dt,
                       v.validity)
        f = _to_f64(v)
        return Vec(_half_up(f.data, float(10.0 ** self.d)), T.DOUBLE,
                   f.validity)

    def __repr__(self):
        return f"round({self.children[0]!r}, {self.d})"


class _CeilFloor(Expression):
    _fn = None
    _name = "ceil"

    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        dt = self.children[0].dtype(schema)
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(dt.precision, 0)
        if isinstance(dt, T.IntegralType):
            return dt
        return T.LONG  # reference: ceil/floor of double -> LONG

    def eval(self, batch):
        v = self.children[0].eval(batch)
        dt = v.dtype
        if isinstance(dt, T.IntegralType):
            return v
        if isinstance(dt, T.DecimalType):
            p = np.int64(10 ** dt.scale)
            if type(self)._fn is jnp.ceil:
                q = -((-v.data) // p)
            else:
                q = v.data // p
            return Vec(q, T.DecimalType(dt.precision, 0), v.validity)
        f = _to_f64(v)
        return Vec(type(self)._fn(f.data).astype(jnp.int64), T.LONG,
                   f.validity)

    def __repr__(self):
        return f"{self._name}({self.children[0]!r})"


class Ceil(_CeilFloor):
    _fn = staticmethod(jnp.ceil)
    _name = "ceil"


class Floor(_CeilFloor):
    _fn = staticmethod(jnp.floor)
    _name = "floor"


class Factorial(Expression):
    """factorial(n) for n in [0, 20], NULL outside (reference:
    mathExpressions.scala Factorial) — a 21-entry table gather."""

    _TABLE = np.array([math.factorial(i) for i in range(21)], np.int64)

    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return T.LONG

    def eval(self, batch):
        v = self.children[0].eval(batch)
        idx = v.data.astype(jnp.int32)
        ok = (idx >= 0) & (idx <= 20)
        data = jnp.take(jnp.asarray(self._TABLE), jnp.clip(idx, 0, 20))
        return Vec(data, T.LONG, _and_valid(v.validity, ok))

    def __repr__(self):
        return f"factorial({self.children[0]!r})"


class _BitwiseBinary(Expression):
    _op = None
    _sym = "&"

    def __init__(self, left, right):
        self.children = (_wrap(left), _wrap(right))

    def dtype(self, schema):
        return self.children[0].dtype(schema)

    def eval(self, batch):
        l = self.children[0].eval(batch)
        r = self.children[1].eval(batch)
        rd = r.data.astype(l.data.dtype)
        return Vec(type(self)._op(l.data, rd), l.dtype,
                   _and_valid(l.validity, r.validity))

    def __repr__(self):
        return f"({self.children[0]!r} {self._sym} {self.children[1]!r})"


class BitwiseAnd(_BitwiseBinary):
    _op = staticmethod(lambda a, b: a & b)
    _sym = "&"


class BitwiseOr(_BitwiseBinary):
    _op = staticmethod(lambda a, b: a | b)
    _sym = "|"


class BitwiseXor(_BitwiseBinary):
    _op = staticmethod(lambda a, b: a ^ b)
    _sym = "^"


class ShiftLeft(_BitwiseBinary):
    _op = staticmethod(lambda a, b: a << b)
    _sym = "<<"


class ShiftRight(_BitwiseBinary):
    _op = staticmethod(lambda a, b: a >> b)
    _sym = ">>"


class BitwiseNot(Expression):
    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return self.children[0].dtype(schema)

    def eval(self, batch):
        v = self.children[0].eval(batch)
        return Vec(~v.data, v.dtype, v.validity)

    def __repr__(self):
        return f"~{self.children[0]!r}"


class BitCount(Expression):
    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return T.INT

    def eval(self, batch):
        v = self.children[0].eval(batch)
        # the reference widens to long and counts 64 bits
        # (Long.bitCount), so negative narrow ints sign-extend:
        # bit_count(-1) = 64 for every integral width
        x = v.data.astype(jnp.int64).view(jnp.uint64)
        cnt = jax.lax.population_count(x).astype(jnp.int32)
        return Vec(cnt, T.INT, v.validity)

    def __repr__(self):
        return f"bit_count({self.children[0]!r})"


# ---------------------------------------------------------------------------
# Null / conditional
# ---------------------------------------------------------------------------

class NullIf(Expression):
    """nullif(a, b): NULL when a == b else a."""

    def __init__(self, a, b):
        self.children = (_wrap(a), _wrap(b))

    def dtype(self, schema):
        return self.children[0].dtype(schema)

    def nullable(self, schema):
        return True

    def eval(self, batch):
        a = self.children[0].eval(batch)
        # reuse the engine's comparison semantics (dictionary strings,
        # decimals, NULLs) instead of raw-data equality
        eqv = EQ(self.children[0], self.children[1]).eval(batch)
        equal = eqv.data
        if eqv.validity is not None:  # NULL comparison never equals
            equal = equal & eqv.validity
        validity = (~equal) if a.validity is None else (a.validity & ~equal)
        return Vec(a.data, a.dtype, validity, a.dictionary)

    def __repr__(self):
        return f"nullif({self.children[0]!r}, {self.children[1]!r})"


def Nvl(a, b) -> Expression:
    return Coalesce(_wrap(a), _wrap(b))


def Nvl2(a, b, c) -> Expression:
    return CaseWhen([(Not(IsNull(_wrap(a))), _wrap(b))], _wrap(c))


def If(cond, a, b) -> Expression:
    return CaseWhen([(_wrap(cond), _wrap(a))], _wrap(b))


class _GreatestLeast(Expression):
    _pick = None
    _name = "greatest"

    def __init__(self, *args):
        if len(args) < 2:
            raise AnalysisError(f"{self._name} requires >= 2 arguments")
        self.children = tuple(_wrap(a) for a in args)

    def dtype(self, schema):
        dts = [c.dtype(schema) for c in self.children]
        for dt in dts:
            if isinstance(dt, T.StringType):
                raise AnalysisError(
                    f"{self._name} over strings is not supported "
                    f"(dictionary codes have no value order)")
        out = dts[0]
        for dt in dts[1:]:
            out = T.common_type(out, dt)
        return out

    def nullable(self, schema):
        return all(c.nullable(schema) for c in self.children)

    def eval(self, batch):
        out_dt = self.dtype(batch.schema())
        vs = [cast_vec(c.eval(batch), out_dt) for c in self.children]
        data, validity = vs[0].data, vs[0].validity
        floating = jnp.issubdtype(vs[0].data.dtype, jnp.floating)
        pick = type(self)._pick_float if floating else type(self)._pick
        if validity is None:
            validity = jnp.ones(data.shape, jnp.bool_)
        for v in vs[1:]:
            vvalid = v.validity if v.validity is not None else \
                jnp.ones(v.data.shape, jnp.bool_)
            # NULLs are skipped (reference: greatest/least ignore nulls)
            better = vvalid & (~validity | pick(v.data, data))
            data = jnp.where(better, v.data, data)
            validity = validity | vvalid
        return Vec(data, out_dt, validity)

    def __repr__(self):
        return f"{self._name}({', '.join(map(repr, self.children))})"


class Greatest(_GreatestLeast):
    _pick = staticmethod(lambda a, b: a > b)
    _name = "greatest"

    @staticmethod
    def _pick_float(a, b):
        # the reference orders NaN as the LARGEST double: greatest
        # prefers NaN over any number (including +inf)
        return (jnp.isnan(a) & ~jnp.isnan(b)) | (a > b)


class Least(_GreatestLeast):
    _pick = staticmethod(lambda a, b: a < b)
    _name = "least"

    @staticmethod
    def _pick_float(a, b):
        # NaN is the largest double, so least only keeps NaN when every
        # input is NaN — a number always replaces an accumulated NaN
        return ~jnp.isnan(a) & (jnp.isnan(b) | (a < b))


class IsNan(Expression):
    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return T.BOOLEAN

    def nullable(self, schema):
        return False

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if not np.issubdtype(np.dtype(v.data.dtype), np.floating):
            return Vec(jnp.zeros(v.data.shape, jnp.bool_), T.BOOLEAN, None)
        isnan = jnp.isnan(v.data)
        if v.validity is not None:
            isnan = isnan & v.validity  # NULL is not NaN
        return Vec(isnan, T.BOOLEAN, None)

    def __repr__(self):
        return f"isnan({self.children[0]!r})"


class NanToNull(Expression):
    """Internal: NaN -> NULL (used by nanvl lowering)."""

    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return self.children[0].dtype(schema)

    def eval(self, batch):
        v = self.children[0].eval(batch)
        notnan = ~jnp.isnan(v.data)
        return Vec(v.data, v.dtype, _and_valid(v.validity, notnan))

    def __repr__(self):
        return f"nan_to_null({self.children[0]!r})"


def Nanvl(a, b) -> Expression:
    return Coalesce(NanToNull(_wrap(a)), _wrap(b))


# ---------------------------------------------------------------------------
# Datetime (int32 days since epoch; _civil_from_days does the calendar)
# ---------------------------------------------------------------------------

class _DatePart(Expression):
    _name = "quarter"

    def __init__(self, child):
        self.children = (_wrap(child),)

    def dtype(self, schema):
        return T.INT

    def _compute(self, days):
        raise NotImplementedError

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if not isinstance(v.dtype, T.DateType):
            raise AnalysisError(f"{self._name} expects a DATE input")
        return Vec(self._compute(v.data.astype(jnp.int64)).astype(jnp.int32),
                   T.INT, v.validity)

    def __repr__(self):
        return f"{self._name}({self.children[0]!r})"


class Quarter(_DatePart):
    _name = "quarter"

    def _compute(self, days):
        _y, m, _d = _civil_from_days(days)
        return (m - 1) // 3 + 1


class DayOfWeek(_DatePart):
    """1 = Sunday ... 7 = Saturday (reference: DayOfWeek)."""
    _name = "dayofweek"

    def _compute(self, days):
        return (days + 4) % 7 + 1  # 1970-01-01 was a Thursday


class WeekDay(_DatePart):
    """0 = Monday ... 6 = Sunday (reference: WeekDay)."""
    _name = "weekday"

    def _compute(self, days):
        return (days + 3) % 7


class DayOfYear(_DatePart):
    _name = "dayofyear"

    def _compute(self, days):
        y, _m, _d = _civil_from_days(days)
        jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return days - jan1 + 1


class WeekOfYear(_DatePart):
    """ISO-8601 week number (reference: WeekOfYear)."""
    _name = "weekofyear"

    def _compute(self, days):
        # ISO week = week of the year containing this date's Thursday
        thursday = days - ((days + 3) % 7) + 3  # Monday-start week
        y, _m, _d = _civil_from_days(thursday)
        jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return (thursday - jan1) // 7 + 1


def _days_from_civil(y, m, d):
    """Inverse of _civil_from_days (Howard Hinnant's algorithm)."""
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


class LastDay(_DatePart):
    _name = "last_day"

    def dtype(self, schema):
        return T.DATE

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if not isinstance(v.dtype, T.DateType):
            raise AnalysisError("last_day expects a DATE input")
        days = v.data.astype(jnp.int64)
        y, m, _d = _civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        out = _days_from_civil(ny, nm, jnp.ones_like(nm)) - 1
        return Vec(out.astype(jnp.int32), T.DATE, v.validity)


class NextDay(Expression):
    """next_day(date, 'MON'): first date later than `date` falling on
    the given weekday."""

    _DOW = {"SUN": 0, "MON": 1, "TUE": 2, "WED": 3, "THU": 4, "FRI": 5,
            "SAT": 6, "SUNDAY": 0, "MONDAY": 1, "TUESDAY": 2,
            "WEDNESDAY": 3, "THURSDAY": 4, "FRIDAY": 5, "SATURDAY": 6}

    def __init__(self, child, day_name: str):
        self.children = (_wrap(child),)
        key = day_name.strip().upper()
        if key not in self._DOW:
            raise AnalysisError(f"unknown day-of-week {day_name!r}")
        self.target = self._DOW[key]
        self.day_name = day_name

    def dtype(self, schema):
        return T.DATE

    def eval(self, batch):
        v = self.children[0].eval(batch)
        days = v.data.astype(jnp.int64)
        dow = (days + 4) % 7  # 0 = Sunday
        delta = (self.target - dow + 6) % 7 + 1
        return Vec((days + delta).astype(jnp.int32), T.DATE, v.validity)

    def __repr__(self):
        return f"next_day({self.children[0]!r}, {self.day_name!r})"


class AddMonths(Expression):
    """add_months(date, n): calendar month arithmetic with day clamping
    (reference: AddMonths; Jan 31 + 1 month = Feb 28/29)."""

    def __init__(self, child, n):
        self.children = (_wrap(child), _wrap(n))

    def dtype(self, schema):
        return T.DATE

    def eval(self, batch):
        v = self.children[0].eval(batch)
        n = self.children[1].eval(batch)
        days = v.data.astype(jnp.int64)
        y, m, d = _civil_from_days(days)
        total = y * 12 + (m - 1) + n.data.astype(jnp.int64)
        ny = total // 12
        nm = total % 12 + 1
        # clamp day to the target month's length
        nym = jnp.where(nm == 12, ny + 1, ny)
        nmm = jnp.where(nm == 12, 1, nm + 1)
        month_len = (_days_from_civil(nym, nmm, jnp.ones_like(nmm))
                     - _days_from_civil(ny, nm, jnp.ones_like(nm)))
        nd = jnp.minimum(d, month_len)
        out = _days_from_civil(ny, nm, nd)
        return Vec(out.astype(jnp.int32), T.DATE,
                   _and_valid(v.validity, n.validity))

    def __repr__(self):
        return f"add_months({self.children[0]!r}, {self.children[1]!r})"


class MonthsBetween(Expression):
    """months_between(end, start) -> double (reference: MonthsBetween,
    31-day month convention, rounded to 8 digits)."""

    def __init__(self, end, start):
        self.children = (_wrap(end), _wrap(start))

    def dtype(self, schema):
        return T.DOUBLE

    def eval(self, batch):
        e = self.children[0].eval(batch)
        s = self.children[1].eval(batch)
        ed, sd = e.data.astype(jnp.int64), s.data.astype(jnp.int64)
        ey, em, edd = _civil_from_days(ed)
        sy, sm, sdd = _civil_from_days(sd)
        # last-day-of-month pairs count as whole months
        e_last = LastDay(self.children[0]).eval(batch).data.astype(jnp.int64)
        s_last = LastDay(self.children[1]).eval(batch).data.astype(jnp.int64)
        both_last = (ed == e_last) & (sd == s_last)
        whole = (ey - sy) * 12 + (em - sm)
        frac = (edd - sdd).astype(jnp.float64) / 31.0
        out = jnp.where(both_last | (edd == sdd),
                        whole.astype(jnp.float64),
                        whole.astype(jnp.float64) + frac)
        out = jnp.round(out * 1e8) / 1e8
        return Vec(out, T.DOUBLE, _and_valid(e.validity, s.validity))

    def __repr__(self):
        return (f"months_between({self.children[0]!r}, "
                f"{self.children[1]!r})")


class DateDiff(Expression):
    def __init__(self, end, start):
        self.children = (_wrap(end), _wrap(start))

    def dtype(self, schema):
        return T.INT

    def eval(self, batch):
        e = self.children[0].eval(batch)
        s = self.children[1].eval(batch)
        return Vec((e.data.astype(jnp.int32) - s.data.astype(jnp.int32)),
                   T.INT, _and_valid(e.validity, s.validity))

    def __repr__(self):
        return f"datediff({self.children[0]!r}, {self.children[1]!r})"


class TruncDate(Expression):
    """trunc(date, 'year'|'quarter'|'month'|'week') (reference:
    TruncDate)."""

    _FMTS = ("year", "yyyy", "yy", "quarter", "month", "mon", "mm", "week")

    def __init__(self, child, fmt: str):
        self.children = (_wrap(child),)
        self.fmt = fmt.strip().lower()
        if self.fmt not in self._FMTS:
            raise AnalysisError(f"unsupported trunc format {fmt!r}")

    def dtype(self, schema):
        return T.DATE

    def eval(self, batch):
        v = self.children[0].eval(batch)
        days = v.data.astype(jnp.int64)
        y, m, _d = _civil_from_days(days)
        one = jnp.ones_like(m)
        if self.fmt in ("year", "yyyy", "yy"):
            out = _days_from_civil(y, one, one)
        elif self.fmt == "quarter":
            qm = ((m - 1) // 3) * 3 + 1
            out = _days_from_civil(y, qm, one)
        elif self.fmt in ("month", "mon", "mm"):
            out = _days_from_civil(y, m, one)
        else:  # week: Monday start
            out = days - ((days + 3) % 7)
        return Vec(out.astype(jnp.int32), T.DATE, v.validity)

    def __repr__(self):
        return f"trunc({self.children[0]!r}, {self.fmt!r})"


class MakeDate(Expression):
    def __init__(self, y, m, d):
        self.children = (_wrap(y), _wrap(m), _wrap(d))

    def dtype(self, schema):
        return T.DATE

    def eval(self, batch):
        y = self.children[0].eval(batch)
        m = self.children[1].eval(batch)
        d = self.children[2].eval(batch)
        y64 = y.data.astype(jnp.int64)
        m64 = m.data.astype(jnp.int64)
        d64 = d.data.astype(jnp.int64)
        out = _days_from_civil(y64, m64, d64)
        # round-trip through the calendar: invalid dates (make_date(
        # 2023, 2, 30)) would silently roll into the next month; the
        # reference returns NULL (non-ANSI) instead
        ry, rm, rd = _civil_from_days(out)
        ok = (ry.astype(jnp.int64) == y64) & \
            (rm.astype(jnp.int64) == m64) & (rd.astype(jnp.int64) == d64)
        validity = _and_valid(
            _and_valid(y.validity, m.validity),
            _and_valid(d.validity, ok))
        return Vec(out.astype(jnp.int32), T.DATE, validity)

    def __repr__(self):
        return (f"make_date({self.children[0]!r}, {self.children[1]!r}, "
                f"{self.children[2]!r})")


# ---------------------------------------------------------------------------
# Strings: dictionary-table functions
# ---------------------------------------------------------------------------

class _DictPyTransform(Expression):
    """string -> string via a Python function mapped over the (small)
    host dictionary — the escape hatch that makes regexp_replace etc.
    O(|dict|) instead of O(rows) (SURVEY.md section 7)."""

    def __init__(self, child, *params):
        self.children = (_wrap(child),)
        self.params = params

    def dtype(self, schema):
        return T.STRING

    def _py(self, s: str) -> str:
        raise NotImplementedError

    def eval(self, batch):
        from .columnar import apply_code_remap, dedupe_dictionary
        v = self.children[0].eval(batch)
        if v.dictionary is None:
            raise AnalysisError(
                f"{type(self).__name__} requires dictionary-encoded strings")
        d = v.dictionary
        if isinstance(d, pa.ChunkedArray):
            d = d.combine_chunks()
        vals = [None if s is None else self._py(s) for s in d.to_pylist()]
        remap, uniq = dedupe_dictionary(pa.array(vals, type=pa.string()))
        return Vec(apply_code_remap(v.data, remap), T.STRING, v.validity,
                   uniq)

    def __repr__(self):
        ps = ", ".join(repr(p) for p in self.params)
        return (f"{type(self).__name__.lower()}({self.children[0]!r}"
                + (f", {ps}" if ps else "") + ")")


class Ltrim(_DictPyTransform):
    def _py(self, s):
        return s.lstrip()


class Rtrim(_DictPyTransform):
    def _py(self, s):
        return s.rstrip()


class Reverse(_DictPyTransform):
    def _py(self, s):
        return s[::-1]


class InitCap(_DictPyTransform):
    def _py(self, s):
        return " ".join(w[:1].upper() + w[1:].lower() if w else w
                        for w in s.split(" "))


class Lpad(_DictPyTransform):
    def __init__(self, child, length: int, pad: str = " "):
        super().__init__(child, length, pad)
        self.length = int(length)
        self.pad = pad

    def _py(self, s):
        if len(s) >= self.length:
            return s[:self.length]
        need = self.length - len(s)
        fill = (self.pad * need)[:need] if self.pad else ""
        return fill + s


class Rpad(Lpad):
    def _py(self, s):
        if len(s) >= self.length:
            return s[:self.length]
        need = self.length - len(s)
        fill = (self.pad * need)[:need] if self.pad else ""
        return s + fill


class StringReplace(_DictPyTransform):
    def __init__(self, child, search: str, replace: str = ""):
        super().__init__(child, search, replace)
        self.search = search
        self.replace = replace

    def _py(self, s):
        return s.replace(self.search, self.replace)


class Translate(_DictPyTransform):
    def __init__(self, child, matching: str, replace: str):
        super().__init__(child, matching, replace)
        self.table = str.maketrans(
            {m: (replace[i] if i < len(replace) else None)
             for i, m in enumerate(matching)})

    def _py(self, s):
        return s.translate(self.table)


class Repeat(_DictPyTransform):
    def __init__(self, child, n: int):
        super().__init__(child, n)
        self.n = int(n)

    def _py(self, s):
        return s * max(0, self.n)


class RegexpReplace(_DictPyTransform):
    def __init__(self, child, pattern: str, replacement: str):
        super().__init__(child, pattern, replacement)
        self.pattern = re.compile(pattern)
        # Java-style $1 group refs -> Python \1
        self.replacement = re.sub(r"\$(\d+)", r"\\\1", replacement)

    def _py(self, s):
        return self.pattern.sub(self.replacement, s)


class RegexpExtract(_DictPyTransform):
    def __init__(self, child, pattern: str, idx: int = 1):
        super().__init__(child, pattern, idx)
        self.pattern = re.compile(pattern)
        self.idx = int(idx)

    def _py(self, s):
        m = self.pattern.search(s)
        if m is None:
            return ""
        try:
            g = m.group(self.idx)
        except (IndexError, re.error):
            raise AnalysisError(
                f"regexp group {self.idx} out of range for "
                f"{self.pattern.pattern!r}")
        return g if g is not None else ""


class _DictLookup(Expression):
    """string -> scalar via a per-dictionary-entry lookup table gathered
    by code (the StringLength pattern generalized)."""

    _out: T.DataType = T.INT

    def __init__(self, child, *params):
        self.children = (_wrap(child),)
        self.params = params

    def dtype(self, schema):
        return self._out

    def nullable(self, schema):
        return self.children[0].nullable(schema)

    def _table(self, values: List[Optional[str]]) -> np.ndarray:
        raise NotImplementedError

    def eval(self, batch):
        v = self.children[0].eval(batch)
        if v.dictionary is None:
            raise AnalysisError(
                f"{type(self).__name__} requires dictionary-encoded strings")
        d = v.dictionary
        if isinstance(d, pa.ChunkedArray):
            d = d.combine_chunks()
        table = jnp.asarray(self._table(d.to_pylist()))
        if table.shape[0] == 0:
            table = jnp.zeros((1,), table.dtype)
        data = jnp.take(table, jnp.clip(v.data, 0, table.shape[0] - 1))
        return Vec(data, self._out, v.validity)

    def __repr__(self):
        ps = ", ".join(repr(p) for p in self.params)
        return (f"{type(self).__name__.lower()}({self.children[0]!r}"
                + (f", {ps}" if ps else "") + ")")


class Instr(_DictLookup):
    """instr(str, substr): 1-based position, 0 = not found."""
    _out = T.INT

    def __init__(self, child, sub: str):
        super().__init__(child, sub)
        self.sub = sub

    def _table(self, values):
        return np.array([0 if s is None else s.find(self.sub) + 1
                         for s in values], np.int32)


class Ascii(_DictLookup):
    _out = T.INT

    def _table(self, values):
        return np.array([0 if not s else ord(s[0]) for s in values],
                        np.int32)


class RLike(_DictLookup):
    """rlike/regexp_like: full Python regex search over the dictionary."""
    _out = T.BOOLEAN

    def __init__(self, child, pattern: str):
        super().__init__(child, pattern)
        self.pattern = re.compile(pattern)

    def _table(self, values):
        return np.array([False if s is None
                         else self.pattern.search(s) is not None
                         for s in values], np.bool_)


class Contains(_DictLookup):
    _out = T.BOOLEAN

    def __init__(self, child, sub: str):
        super().__init__(child, sub)
        self.sub = sub

    def _table(self, values):
        return np.array([False if s is None else self.sub in s
                         for s in values], np.bool_)


class StartsWith(_DictLookup):
    _out = T.BOOLEAN

    def __init__(self, child, prefix: str):
        super().__init__(child, prefix)
        self.prefix = prefix

    def _table(self, values):
        return np.array([False if s is None else s.startswith(self.prefix)
                         for s in values], np.bool_)


class EndsWith(_DictLookup):
    _out = T.BOOLEAN

    def __init__(self, child, suffix: str):
        super().__init__(child, suffix)
        self.suffix = suffix

    def _table(self, values):
        return np.array([False if s is None else s.endswith(self.suffix)
                         for s in values], np.bool_)


# ---------------------------------------------------------------------------
# Event-time window bucketing (reference: TimeWindow in
# datetimeExpressions.scala / the window() function): the group key is
# the tumbling-window START; streaming reads `duration_us` off the
# expression for watermark eviction (window end = start + duration).
# ---------------------------------------------------------------------------

_DUR_UNITS_US = {
    "microsecond": 1, "microseconds": 1,
    "millisecond": 1000, "milliseconds": 1000,
    "second": 1_000_000, "seconds": 1_000_000,
    "minute": 60_000_000, "minutes": 60_000_000,
    "hour": 3_600_000_000, "hours": 3_600_000_000,
    "day": 86_400_000_000, "days": 86_400_000_000,
}


def parse_duration_us(s) -> int:
    if isinstance(s, (int, float)):
        return int(s)
    parts = str(s).strip().split()
    if len(parts) != 2 or parts[1].lower() not in _DUR_UNITS_US:
        raise AnalysisError(
            f"cannot parse duration {s!r} (want e.g. '10 seconds')")
    return int(float(parts[0]) * _DUR_UNITS_US[parts[1].lower()])


class TumbleWindow(Expression):
    """window(ts, duration): the tumbling-window START timestamp."""

    def __init__(self, child, duration):
        self.children = (_wrap(child),)
        self.duration_us = parse_duration_us(duration)
        if self.duration_us <= 0:
            raise AnalysisError("window duration must be positive")

    def dtype(self, schema):
        dt = self.children[0].dtype(schema)
        if not isinstance(dt, (T.TimestampType, T.LongType,
                               T.IntegerType)):
            raise AnalysisError(
                f"window() needs a timestamp event-time column, "
                f"got {dt!r}")
        return dt

    def name(self):
        return "window"

    def eval(self, batch):
        v = self.children[0].eval(batch)
        d = jnp.asarray(self.duration_us, v.data.dtype)
        start = (v.data // d) * d
        return Vec(start, v.dtype, v.validity)

    def __repr__(self):
        return f"window({self.children[0]!r}, {self.duration_us}us)"

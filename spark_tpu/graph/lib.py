"""Built-in graph algorithms (reference: graphx/lib/PageRank.scala,
ConnectedComponents.scala) on the Pregel loop."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from .graph import Graph


def page_rank(graph: Graph, num_iter: int = 20,
              reset_prob: float = 0.15) -> pd.DataFrame:
    """Iterative PageRank (PageRank.scala `run`): rank flows along out-
    edges weighted 1/outDegree; dangling mass redistributes uniformly.
    One jitted fori_loop — each iteration is a gather + segment_sum."""
    n = graph.num_vertices
    src, dst = graph.src, graph.dst
    deg = jnp.asarray(np.maximum(graph.out_degrees(), 0)
                      .astype(np.float64))
    dangling = deg == 0
    safe_deg = jnp.where(dangling, 1.0, deg)

    @jax.jit
    def run():
        def body(_, r):
            contrib = jnp.take(r / safe_deg, src)
            inflow = jax.ops.segment_sum(contrib, dst, num_segments=n)
            lost = jnp.sum(jnp.where(dangling, r, 0.0))
            return reset_prob / n + (1.0 - reset_prob) * (
                inflow + lost / n)

        r0 = jnp.full((n,), 1.0 / n, jnp.float64)
        return jax.lax.fori_loop(0, num_iter, body, r0)

    ranks = np.asarray(run()) * n  # reference normalization (sum = n)
    return pd.DataFrame({"id": graph.vertex_ids, "pagerank": ranks})


def connected_components(graph: Graph, max_iter: int = 100
                         ) -> pd.DataFrame:
    """Label propagation: every vertex converges to the smallest vertex
    index in its (weakly) connected component
    (ConnectedComponents.scala via Pregel min-messages)."""
    from .graph import pregel
    n = graph.num_vertices
    # undirected propagation: add reversed edges
    both = Graph(graph.vertices,
                 pd.concat([
                     graph.edges[["src", "dst"]],
                     graph.edges[["src", "dst"]].rename(
                         columns={"src": "dst", "dst": "src"})],
                     ignore_index=True))
    labels = pregel(
        both,
        initial=jnp.arange(n, dtype=jnp.int64),
        vprog=lambda s, m: jnp.minimum(s, m),
        send=lambda s_src, s_dst: s_src,
        combine="min",
        max_iter=max_iter)
    # map dense indices back to user vertex ids
    comp = np.asarray(graph.vertex_ids)[labels]
    return pd.DataFrame({"id": graph.vertex_ids, "component": comp})

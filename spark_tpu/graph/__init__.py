"""spark_tpu.graph: the GraphX analog (reference:
`graphx/src/main/scala/org/apache/spark/graphx/Graph.scala`,
`Pregel.scala:59`), re-designed TPU-first: vertices and edges are
device columns; one Pregel superstep = gather (edge-indexed takes) ->
message combine (segment reduce) -> vertex program (elementwise) inside
a single jitted `lax.while_loop`, replacing the reference's per-
iteration RDD joins + shuffles.
"""

from .graph import Graph, pregel
from .lib import connected_components, page_rank

__all__ = ["Graph", "pregel", "page_rank", "connected_components"]

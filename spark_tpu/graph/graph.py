"""Graph container + Pregel loop.

Reference: `graphx/.../Graph.scala` (vertex/edge RDD views),
`Pregel.scala:59` (iterate: send messages along edges, combine per
vertex, run the vertex program until no messages / max iterations).

TPU design: vertex ids normalize to dense [0, n) indices once at
construction (the `VertexRDD` routing-table seat); each superstep is
pure device work — `take` along edge endpoints, `segment_min/sum`
message combine, vectorized vertex program — under one
`lax.while_loop`, so an entire Pregel run is a single XLA program.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd


class Graph:
    """vertices: DataFrame/pandas with an `id` column (+ attrs);
    edges: DataFrame/pandas with `src`, `dst` (+ attrs)."""

    def __init__(self, vertices, edges):
        v = vertices.to_pandas() if hasattr(vertices, "to_pandas") \
            else pd.DataFrame(vertices)
        e = edges.to_pandas() if hasattr(edges, "to_pandas") \
            else pd.DataFrame(edges)
        ids = v["id"].to_numpy()
        self.vertex_ids = ids
        self.num_vertices = len(ids)
        self.vertices = v.reset_index(drop=True)
        self.edges = e.reset_index(drop=True)
        # dense index map (the VertexRDD routing table)
        lookup = pd.Series(np.arange(len(ids)), index=ids)
        missing = ~e["src"].isin(lookup.index) | \
            ~e["dst"].isin(lookup.index)
        if missing.any():
            raise ValueError("edges reference unknown vertex ids")
        self.src = jnp.asarray(lookup[e["src"]].to_numpy(np.int32))
        self.dst = jnp.asarray(lookup[e["dst"]].to_numpy(np.int32))

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    def out_degrees(self) -> np.ndarray:
        deg = np.zeros(self.num_vertices, np.int64)
        np.add.at(deg, np.asarray(self.src), 1)
        return deg

    def in_degrees(self) -> np.ndarray:
        deg = np.zeros(self.num_vertices, np.int64)
        np.add.at(deg, np.asarray(self.dst), 1)
        return deg


def pregel(graph: Graph, initial, vprog: Callable,
           send: Callable, combine: str = "sum",
           max_iter: int = 20, initial_msg=None):
    """Pregel.scala:59 as one jitted while_loop.

    - ``initial``: [n] (or [n, d]) initial vertex state array;
    - ``vprog(state, msg) -> state`` — vectorized over all vertices;
    - ``send(src_state, dst_state) -> msg`` — vectorized over all
      edges (messages flow src -> dst);
    - ``combine``: 'sum' | 'min' | 'max' per-destination reduce;
    - stops when the state reaches a fixed point or after max_iter.
    """
    n = graph.num_vertices
    src, dst = graph.src, graph.dst
    seg = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
           "max": jax.ops.segment_max}[combine]
    state0 = jnp.asarray(initial)
    if initial_msg is not None:
        state0 = vprog(state0, jnp.broadcast_to(
            jnp.asarray(initial_msg), state0.shape))

    def step(state):
        m = send(jnp.take(state, src, axis=0),
                 jnp.take(state, dst, axis=0))
        msgs = seg(m, dst, num_segments=n)
        return vprog(state, msgs)

    def cond(carry):
        i, state, prev, changed = carry
        return (i < max_iter) & changed

    def body(carry):
        i, state, prev, _ = carry
        new = step(state)
        changed = jnp.any(new != state)
        return i + 1, new, state, changed

    @jax.jit
    def run(state0):
        _, final, _, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), state0, state0,
                         jnp.bool_(True)))
        return final

    return np.asarray(run(state0))

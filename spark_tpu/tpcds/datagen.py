"""TPC-DS data generator (vectorized numpy -> pyarrow -> Parquet).

The TPC-DS sibling of `tpch/datagen.py`: the core store-channel tables
with dsdgen-like shapes, types, and value distributions (row counts
scale with `sf`; store_sales ~= 2.88M rows/sf, grouped into multi-line
tickets so the per-ticket queries — q68/q73/q79 — have real ticket
structure). Not bit-identical to dsdgen: golden answers are computed on
THIS data by an independent pandas implementation (golden.py), the
pattern of the reference's golden-file suites
(`TPCDSQueryTestSuite.scala:54`).

Types follow the spec's shape: surrogate keys int64 (nullable on the
fact's dimension FKs, like dsdgen output), money DECIMAL(7,2), dates
DATE32 in date_dim, low-cardinality attributes dictionary strings —
exercising the decimal/date/dictionary ingest tiers end to end.

Fixed-size dimensions (date_dim, time_dim, the demographics tables,
reason) do not scale with `sf`, matching the spec."""

from __future__ import annotations

import os
from typing import Dict

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

EPOCH = np.datetime64("1970-01-01", "D")
#: date_dim coverage: 1996-01-01 .. 2003-12-31 (the sales window plus
#: margin for returns landing after the last sale)
D_START = np.datetime64("1996-01-01", "D")
D_END = np.datetime64("2004-01-01", "D")
#: surrogate key of the first date_dim row (spec base is 2415022 at
#: 1900-01-02; same idea, anchored to our window)
D_BASE_SK = 2450000

CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES_PER_CATEGORY = 4
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
STATES = ["TN", "OH", "TX", "OR", "MN", "KY", "VA", "CA", "MS", "CO",
          "IL", "GA", "NM", "WA", "FL", "MI", "NC", "PA", "SD", "WI"]
CITIES = ["Midway", "Fairview", "Oak Grove", "Glendale", "Centerville",
          "Riverside", "Salem", "Franklin", "Union", "Liberty",
          "Pleasant Hill", "Greenville", "Springdale", "Clinton",
          "Oakdale", "Lakeview"]
COUNTIES = ["Williamson County", "Franklin Parish", "Walker County",
            "Ziebach County", "Luce County", "Richland County",
            "Furnas County", "Daviess County"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
CREDIT = ["Low Risk", "Good", "High Risk", "Unknown"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown"]
STORE_NAMES = ["ese", "ose", "ation", "bar", "able", "anti", "cally",
               "eing"]
PROMO_NAMES = ["ought", "able", "pri", "ese", "anti", "cally", "ation",
               "eing", "n st", "bar"]
COLORS = ["red", "blue", "green", "yellow", "black", "white", "navy",
          "ivory", "plum", "khaki"]
UNITS = ["Each", "Dozen", "Case", "Pallet", "Box", "Bunch"]
SIZES = ["small", "medium", "large", "extra large", "N/A"]
LOCATION_TYPES = ["apartment", "condo", "single family"]


def _dec(cents: np.ndarray, precision: int = 7, scale: int = 2) -> pa.Array:
    """int64 UNSCALED units (cents for scale 2) -> decimal128(p, s),
    built from the little-endian 128-bit buffer (a cast would treat the
    ints as whole units and rescale them) — same device path as the
    tpch generator's DECIMAL(15,2), at the DS spec's precision."""
    lo = np.ascontiguousarray(cents.astype(np.int64))
    raw = np.empty((len(lo), 2), dtype=np.int64)
    raw[:, 0] = lo
    raw[:, 1] = lo >> 63  # sign extension
    return pa.Array.from_buffers(pa.decimal128(precision, scale), len(lo),
                                 [None, pa.py_buffer(raw.tobytes())])


def _nullable_i64(values: np.ndarray, rs, null_frac: float) -> pa.Array:
    """int64 column with a deterministic sprinkle of NULLs (the fact
    table's dimension FKs are nullable in dsdgen output)."""
    if null_frac <= 0:
        return pa.array(values.astype(np.int64))
    mask = rs.rand(len(values)) < null_frac
    return pa.array(values.astype(np.int64), mask=mask)


def _date_dim() -> pa.Table:
    days = np.arange((D_END - D_START).astype(int), dtype=np.int64)
    abs_days = (D_START - EPOCH).astype(np.int64) + days
    dates = D_START + days
    years = dates.astype("datetime64[Y]").astype(int) + 1970
    months = dates.astype("datetime64[M]").astype(int) % 12 + 1
    month_start = dates.astype("datetime64[M]").astype("datetime64[D]")
    doms = (dates - month_start).astype(int) + 1
    # numpy day-of-week: 1970-01-01 was a Thursday (dow 4 with Sunday=0)
    dows = (abs_days + 4) % 7
    # week_seq increments at each Sunday boundary, starting at 1
    week_seq = (days + ((D_START - EPOCH).astype(np.int64) + 4) % 7) // 7 + 1
    month_seq = (years - years[0]) * 12 + months - 1
    quarters = (months - 1) // 3 + 1
    return pa.table({
        "d_date_sk": pa.array(D_BASE_SK + days),
        "d_date_id": pa.array([f"AAAAAAAA{int(s):08d}"
                               for s in D_BASE_SK + days]),
        "d_date": pa.array(abs_days.astype(np.int32),
                           type=pa.int32()).cast(pa.date32()),
        "d_year": pa.array(years.astype(np.int64)),
        "d_moy": pa.array(months.astype(np.int64)),
        "d_dom": pa.array(doms.astype(np.int64)),
        "d_dow": pa.array(dows.astype(np.int64)),
        "d_qoy": pa.array(quarters.astype(np.int64)),
        "d_week_seq": pa.array(week_seq.astype(np.int64)),
        "d_month_seq": pa.array(month_seq.astype(np.int64)),
        "d_day_name": pa.array(np.array(DAY_NAMES)[dows]),
    })


def _time_dim() -> pa.Table:
    secs = np.arange(86400, dtype=np.int64)
    return pa.table({
        "t_time_sk": pa.array(secs),
        "t_time": pa.array(secs),
        "t_hour": pa.array(secs // 3600),
        "t_minute": pa.array(secs % 3600 // 60),
        "t_second": pa.array(secs % 60),
    })


def generate(sf: float, seed: int = 42) -> Dict[str, pa.Table]:
    """Generate the store-channel tables at scale factor `sf`."""
    rs = np.random.RandomState(seed)
    n_item = max(18, int(18_000 * sf))
    n_cust = max(40, int(100_000 * sf))
    n_addr = max(20, int(50_000 * sf))
    n_store = max(4, int(12 * sf))
    n_promo = max(30, int(300 * sf))
    n_cd = 7200
    n_hd = 7200
    n_ticket = max(64, int(480_000 * sf))

    tables: Dict[str, pa.Table] = {}
    tables["date_dim"] = _date_dim()
    tables["time_dim"] = _time_dim()

    idx = np.arange(n_item, dtype=np.int64)
    cat_id = idx % len(CATEGORIES)
    class_id = idx % CLASSES_PER_CATEGORY
    brand_id = ((cat_id + 1) * 1000 + idx % 50 + 1).astype(np.int64)
    tables["item"] = pa.table({
        "i_item_sk": pa.array(idx + 1),
        "i_item_id": pa.array([f"AAAAAAAA{i + 1:08d}" for i in idx]),
        "i_item_desc": pa.array([f"item description {i % 251}"
                                 for i in idx]),
        "i_current_price": _dec(rs.randint(9, 10000, n_item)),
        "i_wholesale_cost": _dec(rs.randint(5, 8000, n_item)),
        "i_brand_id": pa.array(brand_id),
        "i_brand": pa.array([f"Brand#{b}" for b in brand_id]),
        "i_class_id": pa.array(class_id + 1),
        "i_class": pa.array([f"{CATEGORIES[c]} class {k + 1}"
                             for c, k in zip(cat_id, class_id)]),
        "i_category_id": pa.array(cat_id + 1),
        "i_category": pa.array(np.array(CATEGORIES)[cat_id]),
        "i_manufact_id": pa.array(idx % 100 + 1),
        "i_manufact": pa.array([f"Manufacturer#{i % 100 + 1}"
                                for i in idx]),
        "i_manager_id": pa.array(idx % 100 + 1),
        "i_size": pa.array(np.array(SIZES)[idx % len(SIZES)]),
        "i_color": pa.array(np.array(COLORS)[idx % len(COLORS)]),
        "i_units": pa.array(np.array(UNITS)[idx % len(UNITS)]),
    })

    idx = np.arange(n_addr, dtype=np.int64)
    tables["customer_address"] = pa.table({
        "ca_address_sk": pa.array(idx + 1),
        "ca_address_id": pa.array([f"AAAAAAAA{i + 1:08d}" for i in idx]),
        "ca_street_number": pa.array([str(100 + i % 899) for i in idx]),
        "ca_street_name": pa.array([f"Street {i % 61}" for i in idx]),
        "ca_city": pa.array(np.array(CITIES)[idx % len(CITIES)]),
        "ca_county": pa.array(np.array(COUNTIES)[idx % len(COUNTIES)]),
        "ca_state": pa.array(np.array(STATES)[idx % len(STATES)]),
        "ca_zip": pa.array([f"{10000 + int(i) * 7 % 89999:05d}"
                            for i in idx]),
        "ca_country": pa.array(["United States"] * n_addr),
        "ca_gmt_offset": _dec(
            np.array([-500, -600, -700, -800],
                     dtype=np.int64)[idx % 4], precision=5),
        "ca_location_type": pa.array(
            np.array(LOCATION_TYPES)[idx % len(LOCATION_TYPES)]),
    })

    idx = np.arange(n_cd, dtype=np.int64)
    tables["customer_demographics"] = pa.table({
        "cd_demo_sk": pa.array(idx + 1),
        "cd_gender": pa.array(np.array(["M", "F"])[idx % 2]),
        "cd_marital_status": pa.array(
            np.array(MARITAL)[idx // 2 % len(MARITAL)]),
        "cd_education_status": pa.array(
            np.array(EDUCATION)[idx // 10 % len(EDUCATION)]),
        "cd_purchase_estimate": pa.array(idx % 20 * 500 + 500),
        "cd_credit_rating": pa.array(
            np.array(CREDIT)[idx // 70 % len(CREDIT)]),
        "cd_dep_count": pa.array(idx % 7),
        "cd_dep_employed_count": pa.array(idx // 7 % 7),
        "cd_dep_college_count": pa.array(idx // 49 % 7),
    })

    idx = np.arange(n_hd, dtype=np.int64)
    tables["household_demographics"] = pa.table({
        "hd_demo_sk": pa.array(idx + 1),
        "hd_income_band_sk": pa.array(idx % 20 + 1),
        "hd_buy_potential": pa.array(
            np.array(BUY_POTENTIAL)[idx % len(BUY_POTENTIAL)]),
        "hd_dep_count": pa.array(idx // 6 % 10),
        "hd_vehicle_count": pa.array(idx % 6 - 1),
    })

    idx = np.arange(n_cust, dtype=np.int64)
    c_addr = rs.randint(1, n_addr + 1, n_cust).astype(np.int64)
    tables["customer"] = pa.table({
        "c_customer_sk": pa.array(idx + 1),
        "c_customer_id": pa.array([f"AAAAAAAA{i + 1:08d}" for i in idx]),
        "c_current_cdemo_sk": pa.array(
            rs.randint(1, n_cd + 1, n_cust).astype(np.int64)),
        "c_current_hdemo_sk": pa.array(
            rs.randint(1, n_hd + 1, n_cust).astype(np.int64)),
        "c_current_addr_sk": pa.array(c_addr),
        "c_salutation": pa.array(
            np.array(["Mr.", "Mrs.", "Ms.", "Dr.", "Sir"])[idx % 5]),
        "c_first_name": pa.array([f"First{i % 499}" for i in idx]),
        "c_last_name": pa.array([f"Last{i % 997}" for i in idx]),
        "c_preferred_cust_flag": pa.array(np.array(["Y", "N"])[idx % 2]),
        "c_birth_year": pa.array(idx % 68 + 1925),
        "c_birth_month": pa.array(idx % 12 + 1),
        "c_birth_day": pa.array(idx % 28 + 1),
        "c_email_address": pa.array([f"c{i}@example.com" for i in idx]),
    })

    idx = np.arange(n_store, dtype=np.int64)
    tables["store"] = pa.table({
        "s_store_sk": pa.array(idx + 1),
        "s_store_id": pa.array([f"AAAAAAAA{i + 1:08d}" for i in idx]),
        "s_store_name": pa.array(
            np.array(STORE_NAMES)[idx % len(STORE_NAMES)]),
        "s_number_employees": pa.array(200 + idx * 13 % 100),
        "s_floor_space": pa.array((5_000_000 + idx * 997_000 % 5_000_000)
                                  .astype(np.int64)),
        "s_hours": pa.array(np.array(["8AM-8PM", "8AM-4PM",
                                      "8AM-12AM"])[idx % 3]),
        "s_manager": pa.array([f"Manager {i % 50}" for i in idx]),
        "s_market_id": pa.array(idx % 10 + 1),
        "s_city": pa.array(np.array(CITIES)[idx % len(CITIES)]),
        "s_county": pa.array(np.array(COUNTIES)[idx % len(COUNTIES)]),
        "s_state": pa.array(np.array(STATES)[idx % 8]),
        "s_zip": pa.array([f"{20000 + int(i) * 11 % 79999:05d}"
                           for i in idx]),
        "s_company_id": pa.array(np.ones(n_store, dtype=np.int64)),
        "s_company_name": pa.array(["Unknown"] * n_store),
        "s_gmt_offset": _dec(
            np.array([-500, -600], dtype=np.int64)[idx % 2], precision=5),
        "s_tax_precentage": _dec(idx % 12, precision=5),
    })

    idx = np.arange(n_promo, dtype=np.int64)
    tables["promotion"] = pa.table({
        "p_promo_sk": pa.array(idx + 1),
        "p_promo_id": pa.array([f"AAAAAAAA{i + 1:08d}" for i in idx]),
        "p_promo_name": pa.array(
            np.array(PROMO_NAMES)[idx % len(PROMO_NAMES)]),
        "p_channel_dmail": pa.array(np.array(["Y", "N"])[idx % 2]),
        "p_channel_email": pa.array(
            np.where(idx % 5 == 4, "Y", "N")),
        "p_channel_tv": pa.array(np.where(idx % 3 == 2, "Y", "N")),
        "p_channel_event": pa.array(np.where(idx % 4 == 3, "Y", "N")),
        "p_cost": _dec(rs.randint(50000, 200000, n_promo), precision=15,
                       scale=2),
    })

    idx = np.arange(35, dtype=np.int64)
    tables["reason"] = pa.table({
        "r_reason_sk": pa.array(idx + 1),
        "r_reason_id": pa.array([f"AAAAAAAA{i + 1:08d}" for i in idx]),
        "r_reason_desc": pa.array([f"reason {i + 1}" for i in idx]),
    })

    # -- store_sales: ticket-structured fact -------------------------------
    # sales dates span 1998-01-02 .. 2002-12-31 of the date_dim window
    lo = int((np.datetime64("1998-01-02", "D") - D_START).astype(int))
    hi = int((np.datetime64("2003-01-01", "D") - D_START).astype(int))
    t_date = rs.randint(lo, hi, n_ticket).astype(np.int64)
    t_time = rs.randint(8 * 3600, 22 * 3600, n_ticket).astype(np.int64)
    t_store = rs.randint(1, n_store + 1, n_ticket).astype(np.int64)
    t_cust = rs.randint(1, n_cust + 1, n_ticket).astype(np.int64)
    # half the tickets are bought at the customer's current address,
    # half somewhere else (q68's bought_city <> current city filter)
    t_addr = np.where(rs.rand(n_ticket) < 0.5, c_addr[t_cust - 1],
                      rs.randint(1, n_addr + 1, n_ticket)).astype(np.int64)
    t_hdemo = rs.randint(1, n_hd + 1, n_ticket).astype(np.int64)
    t_cdemo = rs.randint(1, n_cd + 1, n_ticket).astype(np.int64)
    n_lines = rs.randint(1, 12, n_ticket)  # 1..11 lines, avg 6

    ticket = np.repeat(np.arange(1, n_ticket + 1, dtype=np.int64), n_lines)
    n_ss = len(ticket)
    date_sk = D_BASE_SK + np.repeat(t_date, n_lines)
    time_sk = np.repeat(t_time, n_lines)
    store_sk = np.repeat(t_store, n_lines)
    cust_sk = np.repeat(t_cust, n_lines)
    addr_sk = np.repeat(t_addr, n_lines)
    hdemo_sk = np.repeat(t_hdemo, n_lines)
    cdemo_sk = np.repeat(t_cdemo, n_lines)
    item_sk = rs.randint(1, n_item + 1, n_ss).astype(np.int64)
    promo_sk = rs.randint(1, n_promo + 1, n_ss).astype(np.int64)

    qty = rs.randint(1, 101, n_ss).astype(np.int64)
    wholesale = rs.randint(100, 10000, n_ss).astype(np.int64)  # cents
    list_p = (wholesale * rs.randint(110, 160, n_ss) // 100).astype(np.int64)
    sales_p = (list_p * rs.randint(20, 101, n_ss) // 100).astype(np.int64)
    ext_sales = qty * sales_p
    ext_list = qty * list_p
    ext_wholesale = qty * wholesale
    ext_discount = ext_list - ext_sales
    ext_tax = ext_sales * 8 // 100
    coupon = np.where(rs.rand(n_ss) < 0.1,
                      ext_sales * rs.randint(5, 40, n_ss) // 100,
                      0).astype(np.int64)
    net_paid = ext_sales - coupon
    net_paid_tax = net_paid + ext_tax
    net_profit = net_paid - ext_wholesale

    tables["store_sales"] = pa.table({
        "ss_sold_date_sk": pa.array(date_sk),
        "ss_sold_time_sk": pa.array(time_sk),
        "ss_item_sk": pa.array(item_sk),
        "ss_customer_sk": _nullable_i64(cust_sk, rs, 0.02),
        "ss_cdemo_sk": _nullable_i64(cdemo_sk, rs, 0.02),
        "ss_hdemo_sk": _nullable_i64(hdemo_sk, rs, 0.02),
        "ss_addr_sk": _nullable_i64(addr_sk, rs, 0.02),
        "ss_store_sk": pa.array(store_sk),
        "ss_promo_sk": _nullable_i64(promo_sk, rs, 0.35),
        "ss_ticket_number": pa.array(ticket),
        "ss_quantity": pa.array(qty),
        "ss_wholesale_cost": _dec(wholesale),
        "ss_list_price": _dec(list_p),
        "ss_sales_price": _dec(sales_p),
        "ss_ext_discount_amt": _dec(ext_discount),
        "ss_ext_sales_price": _dec(ext_sales),
        "ss_ext_wholesale_cost": _dec(ext_wholesale),
        "ss_ext_list_price": _dec(ext_list),
        "ss_ext_tax": _dec(ext_tax),
        "ss_coupon_amt": _dec(coupon),
        "ss_net_paid": _dec(net_paid),
        "ss_net_paid_inc_tax": _dec(net_paid_tax),
        "ss_net_profit": _dec(net_profit),
    })

    # -- store_returns: ~8% of sale lines come back ------------------------
    ret_mask = rs.rand(n_ss) < 0.08
    ri = np.nonzero(ret_mask)[0]
    n_sr = len(ri)
    ret_delay = rs.randint(1, 91, n_sr).astype(np.int64)
    ret_date = np.minimum(date_sk[ri] - D_BASE_SK + ret_delay,
                          int((D_END - D_START).astype(int)) - 1)
    ret_qty = rs.randint(1, qty[ri] + 1).astype(np.int64)
    ret_amt = ret_qty * sales_p[ri]
    ret_tax = ret_amt * 8 // 100
    fee = rs.randint(50, 10000, n_sr).astype(np.int64)
    net_loss = ret_amt // 2 + fee
    # a tenth of returns come back through a different customer account
    sr_cust = np.where(rs.rand(n_sr) < 0.1,
                       rs.randint(1, n_cust + 1, n_sr),
                       cust_sk[ri]).astype(np.int64)
    tables["store_returns"] = pa.table({
        "sr_returned_date_sk": pa.array(D_BASE_SK + ret_date),
        "sr_item_sk": pa.array(item_sk[ri]),
        "sr_customer_sk": _nullable_i64(sr_cust, rs, 0.02),
        "sr_ticket_number": pa.array(ticket[ri]),
        "sr_store_sk": pa.array(store_sk[ri]),
        "sr_reason_sk": pa.array(
            rs.randint(1, 36, n_sr).astype(np.int64)),
        "sr_return_quantity": pa.array(ret_qty),
        "sr_return_amt": _dec(ret_amt),
        "sr_return_tax": _dec(ret_tax),
        "sr_return_amt_inc_tax": _dec(ret_amt + ret_tax),
        "sr_fee": _dec(fee),
        "sr_net_loss": _dec(net_loss),
    })
    return tables


def write_parquet(path: str, sf: float, seed: int = 42,
                  overwrite: bool = False) -> str:
    """Write all tables under `path/<table>.parquet`; returns `path`.
    Skips generation when the directory is already populated (same
    marker protocol as tpch.datagen.write_parquet)."""
    os.makedirs(path, exist_ok=True)
    marker = os.path.join(path, f".sf_{sf}_{seed}")
    if os.path.exists(marker) and not overwrite:
        return path
    tables = generate(sf, seed)
    for name, table in tables.items():
        pq.write_table(table, os.path.join(path, f"{name}.parquet"))
    with open(marker, "w") as f:
        f.write("ok\n")
    return path

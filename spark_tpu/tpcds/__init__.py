"""TPC-DS harness: data generation, the tranche-1 queries, and pandas
golden references for result-parity checks — the TPC-DS sibling of
`tpch/`, modeled on the reference's committed TPC-DS suites
(`TPCDSQueryTestSuite.scala:54` golden results + plan stability,
`TPCDSQueryBenchmark.scala:54` timed queries over generated data).

The store-channel subset is generated (datagen.py), goldens are an
independent pandas engine (golden.py), queries ship as SQL text
(sql_queries.py, ~21 queries covering CTE nesting, ROLLUP, windows and
3-7-way snowflake joins) plus DataFrame forms for the bench/smoke
subset (queries.py)."""

from .datagen import generate, write_parquet
from .queries import QUERIES, register_tables
from .sql_queries import SQL_QUERIES

__all__ = ["generate", "write_parquet", "QUERIES", "SQL_QUERIES",
           "register_tables"]

"""Independent pandas implementations of the TPC-DS tranche queries.

The trusted-engine role for parity checks, the `tpch/golden.py`
pattern: goldens are computed on THIS generator's data by a separate
pandas implementation, and `compare` (shared with the TPC-H harness)
checks row sets with a small float tolerance. Adaptations mirror
`sql_queries.py`'s documented list exactly — a golden implementing the
un-adapted official text would be checking a different query."""

from __future__ import annotations

import datetime
import os

import numpy as np
import pandas as pd
import pyarrow.parquet as pq

# shared comparison/normalization/caching machinery (one definition —
# the TPC-H and TPC-DS harnesses must not drift on tolerance semantics)
from ..tpch.golden import _cached, compare, normalize_decimals

__all__ = ["GOLDEN", "compare", "normalize_decimals"]


def _read(path: str, name: str) -> pd.DataFrame:
    df = pq.read_table(os.path.join(path, f"{name}.parquet")).to_pandas()
    return normalize_decimals(df)


def _csum(series: pd.Series, cond: pd.Series) -> pd.Series:
    """sum(case when cond then x else null end) input column."""
    return series.where(cond)


def q1(path: str) -> pd.DataFrame:
    sr = _read(path, "store_returns")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    c = _read(path, "customer")
    m = sr.merge(dd[dd["d_year"] == 2000], left_on="sr_returned_date_sk",
                 right_on="d_date_sk")
    ctr = (m.groupby(["sr_customer_sk", "sr_store_sk"], dropna=False,
                     as_index=False)
           .agg(ctr_total_return=("sr_return_amt", "sum")))
    avg = (ctr.groupby("sr_store_sk", as_index=False)
           .agg(avg_return=("ctr_total_return", "mean")))
    avg["avg_return"] *= 1.2
    m = ctr.merge(avg, on="sr_store_sk")
    m = m[m["ctr_total_return"] > m["avg_return"]]
    m = m.merge(st[st["s_state"] == "TN"], left_on="sr_store_sk",
                right_on="s_store_sk")
    m = m.merge(c, left_on="sr_customer_sk", right_on="c_customer_sk")
    out = m[["c_customer_id"]].sort_values("c_customer_id").head(100)
    return out.reset_index(drop=True)


def _brand_month(path: str, manufact=None, manager=None, moy=11,
                 year=None):
    ss = _read(path, "store_sales")
    dd = _read(path, "date_dim")
    it = _read(path, "item")
    dd = dd[dd["d_moy"] == moy]
    if year is not None:
        dd = dd[dd["d_year"] == year]
    if manufact is not None:
        it = it[it["i_manufact_id"] == manufact]
    if manager is not None:
        it = it[it["i_manager_id"] == manager]
    return (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
            .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))


def q3(path: str) -> pd.DataFrame:
    m = _brand_month(path, manufact=28)
    out = (m.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           .agg(sum_agg=("ss_ext_sales_price", "sum"))
           .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
           .sort_values(["d_year", "sum_agg", "brand_id"],
                        ascending=[True, False, True]).head(100))
    return out.reset_index(drop=True)


def q7(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    cd = _read(path, "customer_demographics")
    dd = _read(path, "date_dim")
    it = _read(path, "item")
    pr = _read(path, "promotion")
    cd = cd[(cd["cd_gender"] == "M") & (cd["cd_marital_status"] == "S")
            & (cd["cd_education_status"] == "College")]
    pr = pr[(pr["p_channel_email"] == "N")
            | (pr["p_channel_event"] == "N")]
    m = (ss.merge(dd[dd["d_year"] == 2000], left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
         .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
         .merge(pr, left_on="ss_promo_sk", right_on="p_promo_sk")
         .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
    out = (m.groupby("i_item_id", as_index=False)
           .agg(agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_sales_price", "mean"))
           .sort_values("i_item_id").head(100))
    return out.reset_index(drop=True)


def q19(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    dd = _read(path, "date_dim")
    it = _read(path, "item")
    c = _read(path, "customer")
    ca = _read(path, "customer_address")
    st = _read(path, "store")
    dd = dd[(dd["d_moy"] == 11) & (dd["d_year"] == 1998)]
    it = it[it["i_manager_id"] == 8]
    m = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
         .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
    m = m[m["ca_gmt_offset"] != m["s_gmt_offset"]]
    out = (m.groupby(["i_brand_id", "i_brand", "i_manufact_id",
                      "i_manufact"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", "sum"))
           .rename(columns={"i_brand_id": "brand_id",
                            "i_brand": "brand"})
           .sort_values(["ext_price", "brand_id", "i_manufact_id"],
                        ascending=[False, True, True]).head(100))
    return out[["brand_id", "brand", "i_manufact_id", "i_manufact",
                "ext_price"]].reset_index(drop=True)


def q27(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    cd = _read(path, "customer_demographics")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    it = _read(path, "item")
    cd = cd[(cd["cd_gender"] == "F") & (cd["cd_marital_status"] == "W")
            & (cd["cd_education_status"] == "Primary")]
    m = (ss.merge(dd[dd["d_year"] == 2002], left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
         .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(st[st["s_state"].isin(["TN", "OH"])],
                left_on="ss_store_sk", right_on="s_store_sk")
         .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk"))

    def agg(grouped) -> pd.DataFrame:
        return grouped.agg(agg1=("ss_quantity", "mean"),
                           agg2=("ss_list_price", "mean"),
                           agg3=("ss_coupon_amt", "mean"),
                           agg4=("ss_sales_price", "mean"))

    full = agg(m.groupby(["i_item_id", "s_state"], as_index=False))
    by_item = agg(m.groupby(["i_item_id"], as_index=False))
    by_item["s_state"] = None
    # the () grouping set: a global aggregate — one row even over an
    # empty input (all-NULL), matching the engine's union lowering
    total = pd.DataFrame([{
        "i_item_id": None, "s_state": None,
        "agg1": m["ss_quantity"].mean(),
        "agg2": m["ss_list_price"].mean(),
        "agg3": m["ss_coupon_amt"].mean(),
        "agg4": m["ss_sales_price"].mean()}])
    cols = ["i_item_id", "s_state", "agg1", "agg2", "agg3", "agg4"]
    out = pd.concat([full[cols], by_item[cols], total[cols]],
                    ignore_index=True)
    out = out.sort_values(["i_item_id", "s_state"], na_position="first") \
        .head(100)
    return out.reset_index(drop=True)


def q42(path: str) -> pd.DataFrame:
    m = _brand_month(path, manager=1, year=2000)
    out = (m.groupby(["d_year", "i_category_id", "i_category"],
                     as_index=False)
           .agg(total_sales=("ss_ext_sales_price", "sum"))
           .sort_values(["total_sales", "d_year", "i_category_id"],
                        ascending=[False, True, True]).head(100))
    return out.reset_index(drop=True)


def q43(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    m = (ss.merge(dd[dd["d_year"] == 2000], left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
         .merge(st[st["s_gmt_offset"] == -5.0], left_on="ss_store_sk",
                right_on="s_store_sk"))
    for day, col in (("Sunday", "sun_sales"), ("Monday", "mon_sales"),
                     ("Tuesday", "tue_sales"), ("Wednesday", "wed_sales"),
                     ("Thursday", "thu_sales"), ("Friday", "fri_sales"),
                     ("Saturday", "sat_sales")):
        m[col] = _csum(m["ss_sales_price"], m["d_day_name"] == day)
    out = (m.groupby(["s_store_name", "s_store_id"], as_index=False)
           .agg(**{c: (c, lambda s: s.sum(min_count=1))
                   for c in ("sun_sales", "mon_sales", "tue_sales",
                             "wed_sales", "thu_sales", "fri_sales",
                             "sat_sales")})
           .sort_values(["s_store_name", "s_store_id"]).head(100))
    return out.reset_index(drop=True)


def q48(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    st = _read(path, "store")
    cd = _read(path, "customer_demographics")
    ca = _read(path, "customer_address")
    dd = _read(path, "date_dim")
    m = (ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
         .merge(dd[dd["d_year"] == 2001], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
         .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
         .merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk"))
    demo = (((m["cd_marital_status"] == "M")
             & (m["cd_education_status"] == "4 yr Degree")
             & m["ss_sales_price"].between(100.0, 150.0))
            | ((m["cd_marital_status"] == "D")
               & (m["cd_education_status"] == "2 yr Degree")
               & m["ss_sales_price"].between(50.0, 100.0))
            | ((m["cd_marital_status"] == "S")
               & (m["cd_education_status"] == "College")
               & m["ss_sales_price"].between(150.0, 200.0)))
    geo = (((m["ca_country"] == "United States")
            & m["ca_state"].isin(["CO", "OH", "TX"])
            & m["ss_net_profit"].between(0, 2000))
           | ((m["ca_country"] == "United States")
              & m["ca_state"].isin(["OR", "MN", "KY"])
              & m["ss_net_profit"].between(150, 3000))
           | ((m["ca_country"] == "United States")
              & m["ca_state"].isin(["VA", "CA", "MS"])
              & m["ss_net_profit"].between(50, 25000)))
    sel = m[demo & geo]
    total = sel["ss_quantity"].sum()
    return pd.DataFrame(
        {"quantity_sum": [float(total) if len(sel) else np.nan]})


def q52(path: str) -> pd.DataFrame:
    m = _brand_month(path, manager=1, year=2000)
    out = (m.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", "sum"))
           .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
           .sort_values(["d_year", "ext_price", "brand_id"],
                        ascending=[True, False, True]).head(100))
    return out.reset_index(drop=True)


def q55(path: str) -> pd.DataFrame:
    m = _brand_month(path, manager=28, year=1999)
    out = (m.groupby(["i_brand_id", "i_brand"], as_index=False)
           .agg(ext_price=("ss_ext_sales_price", "sum"))
           .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
           .sort_values(["ext_price", "brand_id"],
                        ascending=[False, True]).head(100))
    return out.reset_index(drop=True)


_DAY_COLS = (("Sunday", "sun_sales"), ("Monday", "mon_sales"),
             ("Tuesday", "tue_sales"), ("Wednesday", "wed_sales"),
             ("Thursday", "thu_sales"), ("Friday", "fri_sales"),
             ("Saturday", "sat_sales"))


def q59(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    m = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    for day, col in _DAY_COLS:
        m[col] = _csum(m["ss_sales_price"], m["d_day_name"] == day)
    wss = (m.groupby(["d_week_seq", "ss_store_sk"], as_index=False)
           .agg(**{c: (c, lambda s: s.sum(min_count=1))
                   for _, c in _DAY_COLS}))
    weeks = dd[dd["d_dow"] == 0][["d_week_seq", "d_month_seq"]].rename(
        columns={"d_week_seq": "w_week_seq", "d_month_seq": "w_month_seq"})

    def half(lo, hi, suffix):
        h = (wss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
             .merge(weeks, left_on="d_week_seq", right_on="w_week_seq"))
        h = h[h["w_month_seq"].between(lo, hi)]
        cols = {"s_store_name": f"s_store_name{suffix}",
                "s_store_id": f"s_store_id{suffix}",
                "d_week_seq": f"d_week_seq{suffix}"}
        cols.update({c: f"{c}{suffix}" for _, c in _DAY_COLS})
        return h[list(cols)].rename(columns=cols)

    y = half(24, 35, "1")
    x = half(36, 47, "2")
    x = x.assign(join_week=x["d_week_seq2"] - 52)
    j = y.merge(x, left_on=["s_store_id1", "d_week_seq1"],
                right_on=["s_store_id2", "join_week"])
    for _, c in _DAY_COLS:
        j[f"r_{c[:3]}"] = j[f"{c}1"] / j[f"{c}2"]
    out = j[["s_store_name1", "s_store_id1", "d_week_seq1",
             "r_sun", "r_mon", "r_tue", "r_wed", "r_thu", "r_fri",
             "r_sat"]]
    out = out.sort_values(["s_store_name1", "s_store_id1",
                           "d_week_seq1"]).head(100)
    return out.reset_index(drop=True)


def q61(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    st = _read(path, "store")
    pr = _read(path, "promotion")
    dd = _read(path, "date_dim")
    c = _read(path, "customer")
    ca = _read(path, "customer_address")
    it = _read(path, "item")
    base = (ss.merge(dd[(dd["d_year"] == 1998) & (dd["d_moy"] == 11)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
            .merge(st[st["s_gmt_offset"] == -5.0], left_on="ss_store_sk",
                   right_on="s_store_sk")
            .merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
            .merge(ca[ca["ca_gmt_offset"] == -5.0],
                   left_on="c_current_addr_sk", right_on="ca_address_sk")
            .merge(it[it["i_category"] == "Jewelry"],
                   left_on="ss_item_sk", right_on="i_item_sk"))
    promo = base.merge(
        pr[(pr["p_channel_dmail"] == "Y") | (pr["p_channel_tv"] == "Y")
           | (pr["p_channel_event"] == "Y")],
        left_on="ss_promo_sk", right_on="p_promo_sk")
    p = promo["ss_ext_sales_price"].sum() if len(promo) else np.nan
    t = base["ss_ext_sales_price"].sum() if len(base) else np.nan
    return pd.DataFrame({"promotions": [p], "total": [t],
                         "ratio": [p / t * 100
                                   if len(base) and t else np.nan]})


def q63(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    it = _read(path, "item")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    band = (((it["i_category"].isin(["Books", "Children", "Electronics"]))
             & (it["i_class"].isin(["Books class 1", "Children class 2",
                                    "Electronics class 3"])))
            | ((it["i_category"].isin(["Women", "Music", "Men"]))
               & (it["i_class"].isin(["Women class 1", "Music class 2",
                                      "Men class 3"]))))
    m = (ss.merge(it[band], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd[dd["d_year"] == 2000], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
    g = (m.groupby(["i_manager_id", "d_moy"], as_index=False)
         .agg(sum_sales=("ss_sales_price", "sum")))
    g["avg_monthly_sales"] = g.groupby("i_manager_id")[
        "sum_sales"].transform("mean")
    g = g[(g["avg_monthly_sales"] > 0)
          & ((g["sum_sales"] - g["avg_monthly_sales"]).abs()
             / g["avg_monthly_sales"] > 0.1)]
    out = g.sort_values(["i_manager_id", "avg_monthly_sales",
                         "sum_sales", "d_moy"]).head(100)
    return out[["i_manager_id", "d_moy", "sum_sales",
                "avg_monthly_sales"]].reset_index(drop=True)


def q65(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    it = _read(path, "item")
    m = ss.merge(dd[dd["d_month_seq"].between(24, 35)],
                 left_on="ss_sold_date_sk", right_on="d_date_sk")
    sc = (m.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)
          .agg(revenue=("ss_sales_price", "sum")))
    sb = (sc.groupby("ss_store_sk", as_index=False)
          .agg(ave=("revenue", "mean")))
    j = sc.merge(sb, on="ss_store_sk")
    j = j[j["revenue"] <= 0.1 * j["ave"]]
    j = (j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
         .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
    out = (j[["s_store_name", "i_item_desc", "revenue",
              "i_current_price", "i_wholesale_cost", "i_brand"]]
           .sort_values(["s_store_name", "i_item_desc", "i_brand",
                         "revenue", "i_current_price"]).head(100))
    return out.reset_index(drop=True)


def q68(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    hd = _read(path, "household_demographics")
    ca = _read(path, "customer_address")
    c = _read(path, "customer")
    hd = hd[(hd["hd_dep_count"] == 4) | (hd["hd_vehicle_count"] == 3)]
    dd = dd[dd["d_dom"].between(1, 2) & dd["d_year"].isin(
        [1999, 2000, 2001])]
    m = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(st[st["s_city"].isin(["Midway", "Fairview"])],
                left_on="ss_store_sk", right_on="s_store_sk")
         .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
         .merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk"))
    dn = (m.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                     "ca_city"], dropna=False, as_index=False)
          .agg(extended_price=("ss_ext_sales_price", "sum"),
               list_price=("ss_ext_list_price", "sum"),
               extended_tax=("ss_ext_tax", "sum"))
          .rename(columns={"ca_city": "bought_city"}))
    j = (dn.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
         .merge(ca, left_on="c_current_addr_sk",
                right_on="ca_address_sk"))
    j = j[j["ca_city"] != j["bought_city"]]
    out = (j[["c_last_name", "c_first_name", "ca_city", "bought_city",
              "ss_ticket_number", "extended_price", "extended_tax",
              "list_price"]]
           .sort_values(["c_last_name", "ss_ticket_number"]).head(100))
    return out.reset_index(drop=True)


def q73(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    hd = _read(path, "household_demographics")
    c = _read(path, "customer")
    hd = hd[hd["hd_buy_potential"].isin([">10000", "Unknown"])
            & (hd["hd_vehicle_count"] > 0)]
    dd = dd[dd["d_dom"].between(1, 2)
            & dd["d_year"].isin([1999, 2000, 2001])]
    m = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(st[st["s_county"].isin(["Williamson County",
                                        "Franklin Parish"])],
                left_on="ss_store_sk", right_on="s_store_sk")
         .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk"))
    dj = (m.groupby(["ss_ticket_number", "ss_customer_sk"], dropna=False,
                    as_index=False)
          .agg(cnt=("ss_ticket_number", "size")))
    dj = dj[dj["cnt"].between(1, 5)]
    j = dj.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
    out = (j[["c_last_name", "c_first_name", "c_salutation",
              "c_preferred_cust_flag", "ss_ticket_number", "cnt"]]
           .sort_values(["cnt", "c_last_name", "ss_ticket_number"],
                        ascending=[False, True, True]).head(100))
    return out.reset_index(drop=True)


def q79(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    hd = _read(path, "household_demographics")
    c = _read(path, "customer")
    hd = hd[(hd["hd_dep_count"] == 6) | (hd["hd_vehicle_count"] > 2)]
    dd = dd[(dd["d_dow"] == 1) & dd["d_year"].isin([1998, 1999, 2000])]
    st = st[st["s_number_employees"].between(200, 295)]
    m = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
         .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk"))
    ms = (m.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                     "s_city"], dropna=False, as_index=False)
          .agg(amt=("ss_coupon_amt", "sum"),
               profit=("ss_net_profit", "sum")))
    j = ms.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
    j = j.assign(city=j["s_city"].str[:30])
    out = (j[["c_last_name", "c_first_name", "city", "ss_ticket_number",
              "amt", "profit"]]
           .sort_values(["c_last_name", "c_first_name", "city", "profit",
                         "ss_ticket_number"]).head(100))
    return out.reset_index(drop=True)


def q89(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    it = _read(path, "item")
    dd = _read(path, "date_dim")
    st = _read(path, "store")
    band = ((it["i_category"].isin(["Books", "Electronics", "Sports"])
             & it["i_class"].isin(["Books class 1", "Electronics class 2",
                                   "Sports class 3"]))
            | (it["i_category"].isin(["Men", "Jewelry", "Women"])
               & it["i_class"].isin(["Men class 4", "Jewelry class 1",
                                     "Women class 2"])))
    m = (ss.merge(it[band], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd[dd["d_year"] == 1999], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
    g = (m.groupby(["i_category", "i_class", "i_brand", "s_store_name",
                    "s_company_name", "d_moy"], as_index=False)
         .agg(sum_sales=("ss_sales_price", "sum")))
    g["avg_monthly_sales"] = g.groupby(
        ["i_category", "i_brand", "s_store_name", "s_company_name"]
    )["sum_sales"].transform("mean")
    g = g[(g["avg_monthly_sales"] != 0)
          & ((g["sum_sales"] - g["avg_monthly_sales"])
             / g["avg_monthly_sales"] < -0.1)]
    g = g.assign(_dev=g["sum_sales"] - g["avg_monthly_sales"])
    out = (g.sort_values(["_dev", "s_store_name", "i_category", "i_class",
                          "i_brand", "d_moy"]).head(100))
    return out[["i_category", "i_class", "i_brand", "s_store_name",
                "s_company_name", "d_moy", "sum_sales",
                "avg_monthly_sales"]].reset_index(drop=True)


def q93(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    sr = _read(path, "store_returns")
    r = _read(path, "reason")
    m = ss.merge(sr, left_on=["ss_item_sk", "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_ticket_number"])
    m = m.merge(r[r["r_reason_desc"] == "reason 19"],
                left_on="sr_reason_sk", right_on="r_reason_sk")
    m = m.assign(act_sales=(m["ss_quantity"] - m["sr_return_quantity"])
                 * m["ss_sales_price"])
    out = (m.groupby("ss_customer_sk", dropna=False, as_index=False)
           .agg(sumsales=("act_sales", "sum")))
    out = out.sort_values(["sumsales", "ss_customer_sk"],
                          na_position="first").head(100)
    return out.reset_index(drop=True)


def q96(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    hd = _read(path, "household_demographics")
    td = _read(path, "time_dim")
    st = _read(path, "store")
    m = (ss.merge(td[(td["t_hour"] == 20) & (td["t_minute"] >= 30)],
                  left_on="ss_sold_time_sk", right_on="t_time_sk")
         .merge(hd[hd["hd_dep_count"] == 7], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
         .merge(st[st["s_store_name"] == "ese"], left_on="ss_store_sk",
                right_on="s_store_sk"))
    return pd.DataFrame({"cnt": [len(m)]})


def q98(path: str) -> pd.DataFrame:
    ss = _read(path, "store_sales")
    it = _read(path, "item")
    dd = _read(path, "date_dim")
    dd = dd[(dd["d_date"] >= datetime.date(1999, 2, 22))
            & (dd["d_date"] <= datetime.date(1999, 3, 24))]
    it = it[it["i_category"].isin(["Sports", "Books", "Home"])]
    m = (ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk"))
    g = (m.groupby(["i_item_id", "i_item_desc", "i_category", "i_class",
                    "i_current_price"], as_index=False)
         .agg(itemrevenue=("ss_ext_sales_price", "sum")))
    g["revenueratio"] = (g["itemrevenue"] * 100.0
                         / g.groupby("i_class")["itemrevenue"]
                         .transform("sum"))
    out = g.sort_values(["i_category", "i_class", "i_item_id",
                         "i_item_desc", "revenueratio"])
    return out[["i_item_id", "i_item_desc", "i_category", "i_class",
                "i_current_price", "itemrevenue",
                "revenueratio"]].reset_index(drop=True)


GOLDEN = {k: _cached(f"tpcds_{k}", v) for k, v in {
    "q1": q1, "q3": q3, "q7": q7, "q19": q19, "q27": q27, "q42": q42,
    "q43": q43, "q48": q48, "q52": q52, "q55": q55, "q59": q59,
    "q61": q61, "q63": q63, "q65": q65, "q68": q68, "q73": q73,
    "q79": q79, "q89": q89, "q93": q93, "q96": q96, "q98": q98,
}.items()}

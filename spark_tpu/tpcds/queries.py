"""TPC-DS tranche queries as DataFrame programs.

DataFrame forms for a representative subset (the bench/preflight trio
plus the kernel-matrix pair); the full tranche lives in
`sql_queries.py`, the `tpch/` split. Join orders follow the frontend
convention — fact on the probe (left) side, dimensions on the build
side — which is exactly the order the cost-based reorder pass
(`plan/join_reorder.py`) revises when `spark_tpu.sql.cbo.joinReorder`
is on."""

from __future__ import annotations

import os

from .. import functions as F
from ..functions import col, lit
from ..io.sources import ParquetSource

TABLES = ("store_sales", "store_returns", "date_dim", "time_dim", "item",
          "customer", "customer_address", "customer_demographics",
          "household_demographics", "store", "promotion", "reason")


def register_tables(session, path: str) -> None:
    """Point the session catalog at the generated Parquet directory."""
    for name in TABLES:
        p = os.path.join(path, f"{name}.parquet")
        if os.path.exists(p):
            session.register_table(name, ParquetSource(p, name))


def q3(session):
    """Brand sales by year for one manufacturer (TPC-DS q3)."""
    ss = (session.table("store_sales")
          .join(session.table("date_dim").filter(col("d_moy") == lit(11)),
                left_on=col("ss_sold_date_sk"), right_on=col("d_date_sk"))
          .join(session.table("item")
                .filter(col("i_manufact_id") == lit(28)),
                left_on=col("ss_item_sk"), right_on=col("i_item_sk")))
    return (ss.group_by(col("d_year"), col("i_brand_id").alias("brand_id"),
                        col("i_brand").alias("brand"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("sum_agg"))
            .sort(col("d_year").asc(), col("sum_agg").desc(),
                  col("brand_id").asc())
            .limit(100))


def q7(session):
    """Promotional item averages for one demographic (TPC-DS q7)."""
    cd = (session.table("customer_demographics")
          .filter((col("cd_gender") == lit("M"))
                  & (col("cd_marital_status") == lit("S"))
                  & (col("cd_education_status") == lit("College"))))
    promo = session.table("promotion").filter(
        (col("p_channel_email") == lit("N"))
        | (col("p_channel_event") == lit("N")))
    ss = (session.table("store_sales")
          .join(session.table("date_dim")
                .filter(col("d_year") == lit(2000)),
                left_on=col("ss_sold_date_sk"), right_on=col("d_date_sk"))
          .join(cd, left_on=col("ss_cdemo_sk"), right_on=col("cd_demo_sk"))
          .join(promo, left_on=col("ss_promo_sk"),
                right_on=col("p_promo_sk"))
          .join(session.table("item"), left_on=col("ss_item_sk"),
                right_on=col("i_item_sk")))
    return (ss.group_by(col("i_item_id"))
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .sort(col("i_item_id").asc())
            .limit(100))


def q42(session):
    """Category sales for one month (TPC-DS q42)."""
    ss = (session.table("store_sales")
          .join(session.table("date_dim")
                .filter((col("d_moy") == lit(11))
                        & (col("d_year") == lit(2000))),
                left_on=col("ss_sold_date_sk"), right_on=col("d_date_sk"))
          .join(session.table("item")
                .filter(col("i_manager_id") == lit(1)),
                left_on=col("ss_item_sk"), right_on=col("i_item_sk")))
    return (ss.group_by(col("d_year"), col("i_category_id"),
                        col("i_category"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("total_sales"))
            .sort(col("total_sales").desc(), col("d_year").asc(),
                  col("i_category_id").asc())
            .limit(100))


def q52(session):
    """Brand sales for one month (TPC-DS q52)."""
    ss = (session.table("store_sales")
          .join(session.table("date_dim")
                .filter((col("d_moy") == lit(11))
                        & (col("d_year") == lit(2000))),
                left_on=col("ss_sold_date_sk"), right_on=col("d_date_sk"))
          .join(session.table("item")
                .filter(col("i_manager_id") == lit(1)),
                left_on=col("ss_item_sk"), right_on=col("i_item_sk")))
    return (ss.group_by(col("d_year"),
                        col("i_brand_id").alias("brand_id"),
                        col("i_brand").alias("brand"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .sort(col("d_year").asc(), col("ext_price").desc(),
                  col("brand_id").asc())
            .limit(100))


def q55(session):
    """Brand sales for one manager-month (TPC-DS q55)."""
    ss = (session.table("store_sales")
          .join(session.table("date_dim")
                .filter((col("d_moy") == lit(11))
                        & (col("d_year") == lit(1999))),
                left_on=col("ss_sold_date_sk"), right_on=col("d_date_sk"))
          .join(session.table("item")
                .filter(col("i_manager_id") == lit(28)),
                left_on=col("ss_item_sk"), right_on=col("i_item_sk")))
    return (ss.group_by(col("i_brand_id").alias("brand_id"),
                        col("i_brand").alias("brand"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .sort(col("ext_price").desc(), col("brand_id").asc())
            .limit(100))


def q96(session):
    """Half-hour store traffic count (TPC-DS q96)."""
    td = (session.table("time_dim")
          .filter((col("t_hour") == lit(20))
                  & (col("t_minute") >= lit(30))))
    hd = session.table("household_demographics").filter(
        col("hd_dep_count") == lit(7))
    st = session.table("store").filter(
        col("s_store_name") == lit("ese"))
    ss = (session.table("store_sales")
          .join(td, left_on=col("ss_sold_time_sk"),
                right_on=col("t_time_sk"))
          .join(hd, left_on=col("ss_hdemo_sk"),
                right_on=col("hd_demo_sk"))
          .join(st, left_on=col("ss_store_sk"),
                right_on=col("s_store_sk")))
    return ss.agg(F.count().alias("cnt"))


QUERIES = {"q3": q3, "q7": q7, "q42": q42, "q52": q52, "q55": q55,
           "q96": q96}

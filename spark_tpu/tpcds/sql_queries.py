"""TPC-DS tranche-1 queries as SQL text.

Adapted from the official templates to the store channel subset this
harness generates and to the syntax the frontend supports, the same
discipline as `tpch/sql_queries.py` ("the official query forms,
restricted to the syntax the frontend supports"). Documented
adaptations, applied consistently to SQL, DataFrame and golden forms:

- window-over-aggregate queries (q63/q89/q98) nest the aggregate in a
  FROM-subquery and apply the window above it (the frontend does not
  combine GROUP BY and OVER in one SELECT);
- equi-join conjuncts that the official text repeats inside OR branches
  (q48) are hoisted out of the OR, leaving only attribute bands inside
  — same relational semantics, no cross-join blowup;
- q1's correlated average is expressed as its standard decorrelated
  form (a second CTE grouping the first — still two references to the
  shared CTE);
- q59's per-week calendar join uses date_dim's Sunday rows (d_dow = 0,
  one row per week) instead of all seven days, so week rows are not
  duplicated;
- q19's shops-away-from-home predicate compares time zones
  (ca_gmt_offset <> s_gmt_offset) instead of 5-digit zip prefixes: the
  columnar string tier only compares strings against literals or a
  shared dictionary, and the numeric form keeps the same intent;
- q63/q89's monthly-deviation ratio divides by
  cast(avg_monthly_sales as double): the engine's decimal division
  NULLs rows past its f64-exactness bound at divisor scale 6 (the
  documented scaled-int64 deviation), and the double form matches what
  the reference computes for the ratio anyway;
- ORDER BY lists carry enough trailing keys to make every ordering
  total (golden parity cannot tolerate tie-dependent row order).
"""

Q1 = """
with customer_total_return as (
    select
        sr_customer_sk as ctr_customer_sk,
        sr_store_sk as ctr_store_sk,
        sum(sr_return_amt) as ctr_total_return
    from
        store_returns,
        date_dim
    where
        sr_returned_date_sk = d_date_sk
        and d_year = 2000
    group by
        sr_customer_sk,
        sr_store_sk
),
store_avg_return as (
    select
        ctr_store_sk as avg_store_sk,
        avg(ctr_total_return) * 1.2 as avg_return
    from
        customer_total_return
    group by
        ctr_store_sk
)
select
    c_customer_id
from
    customer_total_return,
    store_avg_return,
    store,
    customer
where
    ctr_store_sk = avg_store_sk
    and ctr_total_return > avg_return
    and s_store_sk = ctr_store_sk
    and s_state = 'TN'
    and ctr_customer_sk = c_customer_sk
order by
    c_customer_id
limit 100
"""

Q3 = """
select
    d_year,
    i_brand_id as brand_id,
    i_brand as brand,
    sum(ss_ext_sales_price) as sum_agg
from
    date_dim,
    store_sales,
    item
where
    d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manufact_id = 28
    and d_moy = 11
group by
    d_year,
    i_brand_id,
    i_brand
order by
    d_year,
    sum_agg desc,
    brand_id
limit 100
"""

Q7 = """
select
    i_item_id,
    avg(ss_quantity) as agg1,
    avg(ss_list_price) as agg2,
    avg(ss_coupon_amt) as agg3,
    avg(ss_sales_price) as agg4
from
    store_sales,
    customer_demographics,
    date_dim,
    item,
    promotion
where
    ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and ss_cdemo_sk = cd_demo_sk
    and ss_promo_sk = p_promo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and (p_channel_email = 'N' or p_channel_event = 'N')
    and d_year = 2000
group by
    i_item_id
order by
    i_item_id
limit 100
"""

Q19 = """
select
    i_brand_id as brand_id,
    i_brand as brand,
    i_manufact_id,
    i_manufact,
    sum(ss_ext_sales_price) as ext_price
from
    date_dim,
    store_sales,
    item,
    customer,
    customer_address,
    store
where
    d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 8
    and d_moy = 11
    and d_year = 1998
    and ss_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
    and ca_gmt_offset <> s_gmt_offset
    and ss_store_sk = s_store_sk
group by
    i_brand_id,
    i_brand,
    i_manufact_id,
    i_manufact
order by
    ext_price desc,
    brand_id,
    i_manufact_id
limit 100
"""

Q27 = """
select
    i_item_id,
    s_state,
    avg(ss_quantity) as agg1,
    avg(ss_list_price) as agg2,
    avg(ss_coupon_amt) as agg3,
    avg(ss_sales_price) as agg4
from
    store_sales,
    customer_demographics,
    date_dim,
    store,
    item
where
    ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and ss_cdemo_sk = cd_demo_sk
    and cd_gender = 'F'
    and cd_marital_status = 'W'
    and cd_education_status = 'Primary'
    and d_year = 2002
    and s_state in ('TN', 'OH')
group by
    rollup(i_item_id, s_state)
order by
    i_item_id,
    s_state
limit 100
"""

Q42 = """
select
    d_year,
    i_category_id,
    i_category,
    sum(ss_ext_sales_price) as total_sales
from
    date_dim,
    store_sales,
    item
where
    d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 1
    and d_moy = 11
    and d_year = 2000
group by
    d_year,
    i_category_id,
    i_category
order by
    total_sales desc,
    d_year,
    i_category_id
limit 100
"""

Q43 = """
select
    s_store_name,
    s_store_id,
    sum(case when d_day_name = 'Sunday' then ss_sales_price
        else null end) as sun_sales,
    sum(case when d_day_name = 'Monday' then ss_sales_price
        else null end) as mon_sales,
    sum(case when d_day_name = 'Tuesday' then ss_sales_price
        else null end) as tue_sales,
    sum(case when d_day_name = 'Wednesday' then ss_sales_price
        else null end) as wed_sales,
    sum(case when d_day_name = 'Thursday' then ss_sales_price
        else null end) as thu_sales,
    sum(case when d_day_name = 'Friday' then ss_sales_price
        else null end) as fri_sales,
    sum(case when d_day_name = 'Saturday' then ss_sales_price
        else null end) as sat_sales
from
    date_dim,
    store_sales,
    store
where
    d_date_sk = ss_sold_date_sk
    and ss_store_sk = s_store_sk
    and s_gmt_offset = -5.00
    and d_year = 2000
group by
    s_store_name,
    s_store_id
order by
    s_store_name,
    s_store_id
limit 100
"""

Q48 = """
select
    sum(ss_quantity) as quantity_sum
from
    store_sales,
    store,
    customer_demographics,
    customer_address,
    date_dim
where
    s_store_sk = ss_store_sk
    and ss_sold_date_sk = d_date_sk
    and ss_cdemo_sk = cd_demo_sk
    and ss_addr_sk = ca_address_sk
    and d_year = 2001
    and (
        (cd_marital_status = 'M'
         and cd_education_status = '4 yr Degree'
         and ss_sales_price between 100.00 and 150.00)
        or (cd_marital_status = 'D'
            and cd_education_status = '2 yr Degree'
            and ss_sales_price between 50.00 and 100.00)
        or (cd_marital_status = 'S'
            and cd_education_status = 'College'
            and ss_sales_price between 150.00 and 200.00)
    )
    and (
        (ca_country = 'United States'
         and ca_state in ('CO', 'OH', 'TX')
         and ss_net_profit between 0 and 2000)
        or (ca_country = 'United States'
            and ca_state in ('OR', 'MN', 'KY')
            and ss_net_profit between 150 and 3000)
        or (ca_country = 'United States'
            and ca_state in ('VA', 'CA', 'MS')
            and ss_net_profit between 50 and 25000)
    )
"""

Q52 = """
select
    d_year,
    i_brand_id as brand_id,
    i_brand as brand,
    sum(ss_ext_sales_price) as ext_price
from
    date_dim,
    store_sales,
    item
where
    d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 1
    and d_moy = 11
    and d_year = 2000
group by
    d_year,
    i_brand_id,
    i_brand
order by
    d_year,
    ext_price desc,
    brand_id
limit 100
"""

Q55 = """
select
    i_brand_id as brand_id,
    i_brand as brand,
    sum(ss_ext_sales_price) as ext_price
from
    date_dim,
    store_sales,
    item
where
    d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 28
    and d_moy = 11
    and d_year = 1999
group by
    i_brand_id,
    i_brand
order by
    ext_price desc,
    brand_id
limit 100
"""

Q59 = """
with wss as (
    select
        d_week_seq,
        ss_store_sk,
        sum(case when d_day_name = 'Sunday' then ss_sales_price
            else null end) as sun_sales,
        sum(case when d_day_name = 'Monday' then ss_sales_price
            else null end) as mon_sales,
        sum(case when d_day_name = 'Tuesday' then ss_sales_price
            else null end) as tue_sales,
        sum(case when d_day_name = 'Wednesday' then ss_sales_price
            else null end) as wed_sales,
        sum(case when d_day_name = 'Thursday' then ss_sales_price
            else null end) as thu_sales,
        sum(case when d_day_name = 'Friday' then ss_sales_price
            else null end) as fri_sales,
        sum(case when d_day_name = 'Saturday' then ss_sales_price
            else null end) as sat_sales
    from
        store_sales,
        date_dim
    where
        d_date_sk = ss_sold_date_sk
    group by
        d_week_seq,
        ss_store_sk
)
select
    s_store_name1,
    s_store_id1,
    d_week_seq1,
    sun_sales1 / sun_sales2 as r_sun,
    mon_sales1 / mon_sales2 as r_mon,
    tue_sales1 / tue_sales2 as r_tue,
    wed_sales1 / wed_sales2 as r_wed,
    thu_sales1 / thu_sales2 as r_thu,
    fri_sales1 / fri_sales2 as r_fri,
    sat_sales1 / sat_sales2 as r_sat
from
    (select
         s_store_name as s_store_name1,
         wss.d_week_seq as d_week_seq1,
         s_store_id as s_store_id1,
         sun_sales as sun_sales1,
         mon_sales as mon_sales1,
         tue_sales as tue_sales1,
         wed_sales as wed_sales1,
         thu_sales as thu_sales1,
         fri_sales as fri_sales1,
         sat_sales as sat_sales1
     from
         wss,
         store,
         (select d_week_seq as w_week_seq, d_month_seq as w_month_seq
          from date_dim where d_dow = 0) w1
     where
         w_week_seq = wss.d_week_seq
         and ss_store_sk = s_store_sk
         and w_month_seq between 24 and 35) y,
    (select
         s_store_name as s_store_name2,
         wss.d_week_seq as d_week_seq2,
         s_store_id as s_store_id2,
         sun_sales as sun_sales2,
         mon_sales as mon_sales2,
         tue_sales as tue_sales2,
         wed_sales as wed_sales2,
         thu_sales as thu_sales2,
         fri_sales as fri_sales2,
         sat_sales as sat_sales2
     from
         wss,
         store,
         (select d_week_seq as w_week_seq, d_month_seq as w_month_seq
          from date_dim where d_dow = 0) w2
     where
         w_week_seq = wss.d_week_seq
         and ss_store_sk = s_store_sk
         and w_month_seq between 36 and 47) x
where
    s_store_id1 = s_store_id2
    and d_week_seq1 = d_week_seq2 - 52
order by
    s_store_name1,
    s_store_id1,
    d_week_seq1
limit 100
"""

Q61 = """
select
    promotions,
    total,
    promotions / total * 100 as ratio
from
    (select sum(ss_ext_sales_price) as promotions
     from
         store_sales,
         store,
         promotion,
         date_dim,
         customer,
         customer_address,
         item
     where
         ss_sold_date_sk = d_date_sk
         and ss_store_sk = s_store_sk
         and ss_promo_sk = p_promo_sk
         and ss_customer_sk = c_customer_sk
         and ca_address_sk = c_current_addr_sk
         and ss_item_sk = i_item_sk
         and ca_gmt_offset = -5
         and i_category = 'Jewelry'
         and (p_channel_dmail = 'Y' or p_channel_tv = 'Y'
              or p_channel_event = 'Y')
         and s_gmt_offset = -5
         and d_year = 1998
         and d_moy = 11) promotional_sales,
    (select sum(ss_ext_sales_price) as total
     from
         store_sales,
         store,
         date_dim,
         customer,
         customer_address,
         item
     where
         ss_sold_date_sk = d_date_sk
         and ss_store_sk = s_store_sk
         and ss_customer_sk = c_customer_sk
         and ca_address_sk = c_current_addr_sk
         and ss_item_sk = i_item_sk
         and ca_gmt_offset = -5
         and i_category = 'Jewelry'
         and s_gmt_offset = -5
         and d_year = 1998
         and d_moy = 11) all_sales
"""

Q63 = """
select
    i_manager_id,
    d_moy,
    sum_sales,
    avg_monthly_sales
from
    (select
         i_manager_id,
         d_moy,
         sum_sales,
         avg(sum_sales) over (partition by i_manager_id)
             as avg_monthly_sales
     from
         (select
              i_manager_id,
              d_moy,
              sum(ss_sales_price) as sum_sales
          from
              item,
              store_sales,
              date_dim,
              store
          where
              ss_item_sk = i_item_sk
              and ss_sold_date_sk = d_date_sk
              and ss_store_sk = s_store_sk
              and d_year = 2000
              and ((i_category in ('Books', 'Children', 'Electronics')
                    and i_class in ('Books class 1', 'Children class 2',
                                    'Electronics class 3'))
                   or (i_category in ('Women', 'Music', 'Men')
                       and i_class in ('Women class 1', 'Music class 2',
                                       'Men class 3')))
          group by
              i_manager_id,
              d_moy) tmp1) tmp2
where
    avg_monthly_sales > 0
    and abs(sum_sales - avg_monthly_sales)
        / cast(avg_monthly_sales as double) > 0.1
order by
    i_manager_id,
    avg_monthly_sales,
    sum_sales,
    d_moy
limit 100
"""

Q65 = """
with sc as (
    select
        ss_store_sk,
        ss_item_sk,
        sum(ss_sales_price) as revenue
    from
        store_sales,
        date_dim
    where
        ss_sold_date_sk = d_date_sk
        and d_month_seq between 24 and 35
    group by
        ss_store_sk,
        ss_item_sk
),
sb as (
    select
        ss_store_sk as store_sk,
        avg(revenue) as ave
    from
        sc
    group by
        ss_store_sk
)
select
    s_store_name,
    i_item_desc,
    revenue,
    i_current_price,
    i_wholesale_cost,
    i_brand
from
    store,
    item,
    sb,
    sc
where
    store_sk = sc.ss_store_sk
    and revenue <= 0.1 * ave
    and s_store_sk = sc.ss_store_sk
    and i_item_sk = sc.ss_item_sk
order by
    s_store_name,
    i_item_desc,
    i_brand,
    revenue,
    i_current_price
limit 100
"""

Q68 = """
select
    c_last_name,
    c_first_name,
    ca_city,
    bought_city,
    ss_ticket_number,
    extended_price,
    extended_tax,
    list_price
from
    (select
         ss_ticket_number,
         ss_customer_sk,
         ca_city as bought_city,
         sum(ss_ext_sales_price) as extended_price,
         sum(ss_ext_list_price) as list_price,
         sum(ss_ext_tax) as extended_tax
     from
         store_sales,
         date_dim,
         store,
         household_demographics,
         customer_address
     where
         ss_sold_date_sk = d_date_sk
         and ss_store_sk = s_store_sk
         and ss_hdemo_sk = hd_demo_sk
         and ss_addr_sk = ca_address_sk
         and d_dom between 1 and 2
         and (hd_dep_count = 4 or hd_vehicle_count = 3)
         and d_year in (1999, 2000, 2001)
         and s_city in ('Midway', 'Fairview')
     group by
         ss_ticket_number,
         ss_customer_sk,
         ss_addr_sk,
         ca_city) dn,
    customer,
    customer_address current_addr
where
    ss_customer_sk = c_customer_sk
    and customer.c_current_addr_sk = current_addr.ca_address_sk
    and current_addr.ca_city <> bought_city
order by
    c_last_name,
    ss_ticket_number
limit 100
"""

Q73 = """
select
    c_last_name,
    c_first_name,
    c_salutation,
    c_preferred_cust_flag,
    ss_ticket_number,
    cnt
from
    (select
         ss_ticket_number,
         ss_customer_sk,
         count(*) as cnt
     from
         store_sales,
         date_dim,
         store,
         household_demographics
     where
         ss_sold_date_sk = d_date_sk
         and ss_store_sk = s_store_sk
         and ss_hdemo_sk = hd_demo_sk
         and d_dom between 1 and 2
         and (hd_buy_potential = '>10000'
              or hd_buy_potential = 'Unknown')
         and hd_vehicle_count > 0
         and d_year in (1999, 2000, 2001)
         and s_county in ('Williamson County', 'Franklin Parish')
     group by
         ss_ticket_number,
         ss_customer_sk) dj,
    customer
where
    ss_customer_sk = c_customer_sk
    and cnt between 1 and 5
order by
    cnt desc,
    c_last_name asc,
    ss_ticket_number
limit 100
"""

Q79 = """
select
    c_last_name,
    c_first_name,
    substring(s_city, 1, 30) as city,
    ss_ticket_number,
    amt,
    profit
from
    (select
         ss_ticket_number,
         ss_customer_sk,
         s_city,
         sum(ss_coupon_amt) as amt,
         sum(ss_net_profit) as profit
     from
         store_sales,
         date_dim,
         store,
         household_demographics
     where
         ss_sold_date_sk = d_date_sk
         and ss_store_sk = s_store_sk
         and ss_hdemo_sk = hd_demo_sk
         and (hd_dep_count = 6 or hd_vehicle_count > 2)
         and d_dow = 1
         and d_year in (1998, 1999, 2000)
         and s_number_employees between 200 and 295
     group by
         ss_ticket_number,
         ss_customer_sk,
         ss_addr_sk,
         s_city) ms,
    customer
where
    ss_customer_sk = c_customer_sk
order by
    c_last_name,
    c_first_name,
    city,
    profit,
    ss_ticket_number
limit 100
"""

Q89 = """
select
    i_category,
    i_class,
    i_brand,
    s_store_name,
    s_company_name,
    d_moy,
    sum_sales,
    avg_monthly_sales
from
    (select
         i_category,
         i_class,
         i_brand,
         s_store_name,
         s_company_name,
         d_moy,
         sum_sales,
         avg(sum_sales) over (partition by i_category, i_brand,
                              s_store_name, s_company_name)
             as avg_monthly_sales
     from
         (select
              i_category,
              i_class,
              i_brand,
              s_store_name,
              s_company_name,
              d_moy,
              sum(ss_sales_price) as sum_sales
          from
              item,
              store_sales,
              date_dim,
              store
          where
              ss_item_sk = i_item_sk
              and ss_sold_date_sk = d_date_sk
              and ss_store_sk = s_store_sk
              and d_year = 1999
              and ((i_category in ('Books', 'Electronics', 'Sports')
                    and i_class in ('Books class 1',
                                    'Electronics class 2',
                                    'Sports class 3'))
                   or (i_category in ('Men', 'Jewelry', 'Women')
                       and i_class in ('Men class 4', 'Jewelry class 1',
                                       'Women class 2')))
          group by
              i_category,
              i_class,
              i_brand,
              s_store_name,
              s_company_name,
              d_moy) t1) t2
where
    avg_monthly_sales <> 0
    and (sum_sales - avg_monthly_sales)
        / cast(avg_monthly_sales as double) < -0.1
order by
    sum_sales - avg_monthly_sales,
    s_store_name,
    i_category,
    i_class,
    i_brand,
    d_moy
limit 100
"""

Q93 = """
select
    ss_customer_sk,
    sum(act_sales) as sumsales
from
    (select
         ss_customer_sk,
         (ss_quantity - sr_return_quantity) * ss_sales_price as act_sales
     from
         store_sales,
         store_returns,
         reason
     where
         sr_item_sk = ss_item_sk
         and sr_ticket_number = ss_ticket_number
         and sr_reason_sk = r_reason_sk
         and r_reason_desc = 'reason 19') t
group by
    ss_customer_sk
order by
    sumsales,
    ss_customer_sk
limit 100
"""

Q96 = """
select
    count(*) as cnt
from
    store_sales,
    household_demographics,
    time_dim,
    store
where
    ss_sold_time_sk = t_time_sk
    and ss_hdemo_sk = hd_demo_sk
    and ss_store_sk = s_store_sk
    and t_hour = 20
    and t_minute >= 30
    and hd_dep_count = 7
    and s_store_name = 'ese'
"""

Q98 = """
select
    i_item_id,
    i_item_desc,
    i_category,
    i_class,
    i_current_price,
    itemrevenue,
    revenueratio
from
    (select
         i_item_id,
         i_item_desc,
         i_category,
         i_class,
         i_current_price,
         itemrevenue,
         itemrevenue * 100.0000 / sum(itemrevenue)
             over (partition by i_class) as revenueratio
     from
         (select
              i_item_id,
              i_item_desc,
              i_category,
              i_class,
              i_current_price,
              sum(ss_ext_sales_price) as itemrevenue
          from
              store_sales,
              item,
              date_dim
          where
              ss_item_sk = i_item_sk
              and i_category in ('Sports', 'Books', 'Home')
              and ss_sold_date_sk = d_date_sk
              and d_date between date '1999-02-22' and date '1999-03-24'
          group by
              i_item_id,
              i_item_desc,
              i_category,
              i_class,
              i_current_price) t1) t2
order by
    i_category,
    i_class,
    i_item_id,
    i_item_desc,
    revenueratio
"""

SQL_QUERIES = {
    "q1": Q1, "q3": Q3, "q7": Q7, "q19": Q19, "q27": Q27, "q42": Q42,
    "q43": Q43, "q48": Q48, "q52": Q52, "q55": Q55, "q59": Q59,
    "q61": Q61, "q63": Q63, "q65": Q65, "q68": Q68, "q73": Q73,
    "q79": Q79, "q89": Q89, "q93": Q93, "q96": Q96, "q98": Q98,
}

"""The long-lived SQL service: HTTP JSON endpoint over a session pool.

The `HiveThriftServer2.scala:44` analog, sized to this engine: a
threading stdlib HTTP server (no new dependencies) in front of the
session pool, admission controller and resource arbiter.

Endpoints:

- ``POST /sql``: submit a query. JSON body
  ``{"sql": "...", "session": "name", "conf": {...}, "mode":
  "sync"|"async", "format": "json"|"arrow"}`` (all but ``sql``
  optional). Sync returns the result (JSON columns/rows, or an Arrow
  IPC stream with ``format=arrow``) plus the service query id; async
  returns 202 with the id immediately. Admission rejections are HTTP
  429 and queue timeouts 503, both with structured JSON bodies.
- ``GET /queries``: paginated listing of the query registry (newest
  first; ``?offset=&limit=&status=&session=``) — the live history UI
  seat, no JSONL scraping required.
- ``GET /queries/<id>``: the query's status record, fed by the
  listener bus (engine query id, phase times, fault events, status).
- ``GET /queries/<id>/timeline``: post-execution detail from the
  bounded QueryHistoryStore — per-phase spans, per-stage XLA
  flops/bytes/peak-HBM, per-shard flight-recorder records.
- ``GET /queries/<id>/plan``: the submitted SQL plus the describe()
  fingerprint and the runtime-annotated physical tree.
- ``DELETE /queries/<id>``: cancel a submitted/running query
  (execution/lifecycle.py). A running query stops at its next
  cooperative boundary (chunk, stage attempt, backoff, queue/lease
  wait) with a structured ``QUERY_CANCELLED`` error; a queued async
  request leaves the admission queue without ever executing. 200 with
  ``cancel_requested``; 404 (structured) for an unknown id; 409 for a
  query that already finished. Idempotent: a second DELETE of a
  still-stopping query is another 200.
- ``GET /metrics``: the shared metrics registry in Prometheus text
  exposition (queries, admission, arbiter, compile/result caches,
  latency histograms with native ``_bucket``/``_sum``/``_count``).
- ``GET /healthz``: combined health + pool/admission/arbiter/quota
  stats (now with ``ready``/``draining``). ``GET /healthz/live`` and
  ``GET /healthz/ready`` split liveness from readiness: a worker
  replaying its warm-start manifest is live-but-not-ready (ready is
  503 NOT_READY until the replay finishes), so a fleet router
  withholds traffic instead of racing the replay.
- ``GET /status``: the status store's live health snapshot — queries
  in flight and per-phase outcomes per session, admission queue
  depth, arbiter lease occupancy, cache hit rates, p50/p95/p99 query
  latency per phase and query class, SLO burn rate.
- ``GET /status/timeseries``: the heartbeat-sampled ring time-series
  behind the snapshot (``?series=a,b&limit=N`` to filter/trim).
- ``GET /debug/bundle``: dump an on-demand flight-recorder diagnostic
  bundle per pooled session; returns the bundle directory paths.

Per-request deadline: ``POST /sql`` honors
``spark_tpu.execution.queryDeadlineMs`` from the request's ``conf``
map (or the service conf), armed at SUBMIT entry so admission-queue
and session waits count against the end-to-end budget; a blown
deadline surfaces as a structured ``QUERY_DEADLINE_EXCEEDED`` error.

Per-session quotas: ``spark_tpu.service.session.maxConcurrent`` bounds
one session name's in-flight submissions (SESSION_QUOTA_EXCEEDED, 429)
and ``spark_tpu.service.session.hbmShare`` caps one session's arbiter
leases — a greedy session degrades to out-of-core paths instead of
starving the pool.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..config import Conf
from ..execution import lifecycle
from ..expr import AnalysisError
from ..observability import ListenerBus, MetricsRegistry, QueryListener
from ..observability.flight_recorder import FlightRecorder
from ..observability.listener import ServiceEvent
from ..observability.sinks import json_default
from ..observability.status_store import StatusStore
from ..sql.lexer import ParseError
from ..udf_worker import UdfError
from .admission import (SESSION_MAX_CONCURRENT_KEY, AdmissionController,
                        AdmissionError, AdmissionRejected,
                        AdmissionTimeout, ServiceDraining, SessionQuota)
from .arbiter import (DeviceResourceArbiter, get_arbiter, install_arbiter)
from .pool import PoolExhausted, SessionPool
from .query_history import (HISTORY_SIZE_KEY, QueryHistoryStore,
                            detail_from_event)

MAX_CONCURRENT_KEY = "spark_tpu.service.maxConcurrent"
QUEUE_DEPTH_KEY = "spark_tpu.service.queueDepth"
QUEUE_TIMEOUT_KEY = "spark_tpu.service.queueTimeoutMs"
HOST_KEY = "spark_tpu.service.host"
PORT_KEY = "spark_tpu.service.port"
HBM_BUDGET_KEY = "spark_tpu.service.hbmBudget"
RESULT_CACHE_KEY = "spark_tpu.service.resultCacheBytes"
QUERY_LOG_KEY = "spark_tpu.service.queryLogSize"
ID_PREFIX_KEY = "spark_tpu.service.idPrefix"
DRAIN_TIMEOUT_KEY = "spark_tpu.service.fleet.drainTimeoutMs"


class _StatusListener(QueryListener):
    """Pooled-session subscriber feeding `GET /queries/<id>`: engine
    lifecycle events resolve against the service record currently
    leased onto that session (sessions execute one query at a time).
    At query end the full detail record (spans, stage costs, per-shard
    records, runtime plan tree) lands in the service's
    QueryHistoryStore for `GET /queries/<id>/{timeline,plan}`."""

    def __init__(self, entry, history: Optional[QueryHistoryStore] = None):
        self._entry = entry
        self._history = history

    def _record(self):
        return self._entry.current_record

    def on_query_start(self, event) -> None:
        r = self._record()
        # first start only: a cached-subtree materialization (WITH
        # clause) spawns a NESTED QueryExecution whose start event must
        # not overwrite the outer query's engine id
        if r is not None and "engine_query_id" not in r:
            r["engine_query_id"] = event.query_id

    def on_fault(self, event) -> None:
        r = self._record()
        if r is not None and len(r.setdefault("fault_events", [])) < 16:
            r["fault_events"].append(
                {"action": event.action, "error": event.error[:160]})

    def on_query_end(self, event) -> None:
        r = self._record()
        if r is None:
            return
        ev = event.event or {}
        # OUTER execution only: nested subquery/CTE executions post
        # their own end events, which must not overwrite the detail of
        # the query the client submitted
        if event.query_id == r.get("engine_query_id"):
            r["phase_times_s"] = ev.get("phase_times_s")
            if ev.get("fault_summary"):
                r["fault_summary"] = {
                    k: v for k, v in ev["fault_summary"].items()
                    if isinstance(v, (int, float))}
            if self._history is not None:
                self._history.put(r["id"], detail_from_event(event))


class SqlService:
    """Session pool + admission + arbiter + HTTP front end. Usable
    embedded (`submit()`) or served (`start()`/`stop()`)."""

    def __init__(self, conf: Optional[Conf] = None,
                 init_session=None):
        self.conf = conf or Conf()
        self.metrics = MetricsRegistry()
        #: service event stream (ServiceEvent per admission/lifecycle
        #: transition) — tests and user hooks subscribe here
        self.bus = ListenerBus()
        self.arbiter = DeviceResourceArbiter(
            int(self.conf.get(HBM_BUDGET_KEY)), metrics=self.metrics,
            result_cache_bytes=int(self.conf.get(RESULT_CACHE_KEY)))
        self._installed_arbiter = False
        #: per-query detail store behind GET /queries/<id>/{timeline,
        #: plan}, fed by the pooled sessions' status listener
        self.history = QueryHistoryStore(
            int(self.conf.get(HISTORY_SIZE_KEY)))
        self.pool = SessionPool(
            self.conf, self.metrics, self.arbiter,
            init_session=init_session,
            make_listener=self._make_listener)
        self.admission = AdmissionController(
            int(self.conf.get(MAX_CONCURRENT_KEY)),
            int(self.conf.get(QUEUE_DEPTH_KEY)),
            float(self.conf.get(QUEUE_TIMEOUT_KEY)),
            metrics=self.metrics, on_event=self._post)
        #: per-session in-flight quota (session.maxConcurrent): one
        #: greedy session cannot consume every admission slot
        self.session_quota = SessionQuota(
            int(self.conf.get(SESSION_MAX_CONCURRENT_KEY)),
            metrics=self.metrics)
        #: heartbeat-sampled engine-health store behind GET /status —
        #: providers run OUTSIDE its lock (each takes its own), so the
        #: status seat never extends any provider's critical section
        self.status_store = StatusStore(self.conf, self.metrics, {
            "admission": self.admission.stats,
            "quota": self.session_quota.stats,
            "arbiter": self.arbiter.stats,
            "pool": lambda: {"sessions": len(self.pool)},
            "udf": self._udf_stats,
        })
        self._records: "OrderedDict[str, Dict]" = OrderedDict()
        self._records_lock = threading.Lock()
        #: cancel tokens of submitted/running queries, by service query
        #: id (DELETE /queries/<id> reaches them cross-thread); entries
        #: are dropped when their query finishes
        self._tokens: Dict[str, "lifecycle.CancelToken"] = {}
        #: in-flight async submissions (each is a worker thread):
        #: bounded at maxConcurrent + queueDepth so an async burst
        #: sheds at the front door like sync traffic does, instead of
        #: accumulating one blocked thread per request
        self._async_inflight = 0
        self._async_lock = threading.Lock()
        #: serializes lazy arbiter installation: two first-submits
        #: racing _ensure_arbiter could both observe "not installed"
        #: and one would leak _installed_arbiter=True over the other's
        #: install (stop() would then uninstall an arbiter a second
        #: service had installed meanwhile)
        self._install_lock = threading.Lock()
        self._record_bound = int(self.conf.get(QUERY_LOG_KEY))
        self._seq = 0
        self._id_prefix = str(self.conf.get(ID_PREFIX_KEY) or "")
        self._started_ts = time.time()
        #: readiness gate behind GET /healthz/ready: set once the
        #: warm-start manifest replay finished (immediately when warm
        #: start is off) — a fleet router withholds traffic until then
        self._ready = threading.Event()
        #: serializes stop() (idempotent, signal-safe: a SIGTERM's
        #: drain thread and an explicit stop() must not both tear the
        #: httpd down) and guards the _stopped/_draining flags
        self._stop_lock = threading.Lock()
        self._stopped = False
        #: draining: new submissions shed with SERVICE_DRAINING (503)
        #: while in-flight queries finish under the drain budget
        self._draining = False
        #: set by stop() AFTER teardown completes (never by the signal
        #: handler directly): worker mains park on wait_for_shutdown()
        #: and must not wake until the drain has run
        self._shutdown_event = threading.Event()
        # lifecycle attrs (guarded-by waiver): written only by the
        # owning control thread in start()/stop(), not on the request
        # path
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        #: background compile-cache warm-start replay (start() spawns
        #: it AFTER the socket binds; stop() joins it bounded)
        self._warm_thread: Optional[threading.Thread] = None

    def _make_listener(self, entry) -> QueryListener:
        """Per-pooled-session listener wiring (runs in pool._create):
        bind the status store's per-session feed, then hand back the
        /queries status listener the pool registers."""
        self.status_store.bind(entry.session, entry.name)
        return _StatusListener(entry, self.history)

    def _udf_stats(self) -> Dict:
        """Status-store provider: live UDF workers across the pool
        (GIL-atomic reads of each pool's `_live`; 0 when no session
        has spawned a worker pool)."""
        live = 0
        for s in self.pool.sessions().values():
            pool = getattr(s, "_udf_pool", None)
            if pool is not None:
                live += int(pool._live)
        return {"workers_live": live}

    # -- service event stream ----------------------------------------------

    def _post(self, action: str, query_id: str, detail: str = "",
              session: str = "") -> None:
        rec = self.get_query(query_id)
        if rec is not None and len(rec.setdefault("events", [])) < 32:
            rec["events"].append({"ts": time.time(), "action": action})
        self.bus.post("on_service", ServiceEvent(
            query_id=query_id, ts=time.time(), action=action,
            session=session, detail=detail))

    # -- query registry -----------------------------------------------------

    def _new_record(self, sql: str, session: str,
                    conf: Optional[Dict] = None) -> Dict:
        """Create the status record AND its cancel token in ONE
        critical section: the moment a record is visible to
        DELETE /queries/<id>, its token is reachable too — no window
        where a submitted query reads as 'already finished'. The
        deadline arms HERE (submit entry, per-request conf override
        falling back to the service conf): queryDeadlineMs is
        end-to-end, so admission-queue and busy-session waits count
        against it."""
        v = (conf or {}).get(lifecycle.DEADLINE_KEY)
        if v is None:
            v = self.conf.get(lifecycle.DEADLINE_KEY)
        ms = float(v or 0)
        tok = lifecycle.CancelToken(deadline_ms=ms if ms > 0 else None)
        with self._records_lock:
            self._seq += 1
            rid = f"q-{self._id_prefix}{self._seq}"
            record = {"id": rid, "sql": sql[:500], "session": session,
                      "status": "submitted", "submitted_ts": time.time()}
            self._records[rid] = record
            self._tokens[rid] = tok
            # bound the registry by evicting oldest FINISHED records
            # only: a running/async record is a client's only handle to
            # its query — dropping it would 404 the status poll and
            # orphan later lifecycle transitions. Unfinished records
            # are themselves bounded by admission (maxConcurrent +
            # queueDepth), so the registry stays near the bound.
            if len(self._records) > self._record_bound:
                for old_id in list(self._records):
                    if len(self._records) <= self._record_bound:
                        break
                    if self._records[old_id]["status"] not in (
                            "submitted", "running"):
                        del self._records[old_id]
        return record

    def get_query(self, query_id: str) -> Optional[Dict]:
        with self._records_lock:
            return self._records.get(query_id)

    def query_snapshot(self, query_id: str) -> Optional[Dict]:
        """Serialization-safe copy of a record: GET /queries/<id> must
        not json-iterate the live dict a worker thread is mutating
        (dict-changed-size mid-dump)."""
        rec = self.get_query(query_id)
        if rec is None:
            return None
        snap = dict(rec)  # C-level copy: atomic under the GIL
        for k in ("events", "fault_events"):
            if k in snap:
                snap[k] = list(snap[k])
        return snap

    # -- submission ---------------------------------------------------------

    def _check_draining(self) -> None:
        """Front-door shed while draining: a new submission gets a
        structured SERVICE_DRAINING 503 before it creates a record or
        touches a quota slot (a router retries on another worker).
        GIL-atomic flag read; writes are serialized under _stop_lock."""
        if self._draining:
            self.metrics.counter("service_drain_rejected").inc()
            raise ServiceDraining(
                "service is draining; not admitting new queries")

    def _ensure_arbiter(self) -> None:
        """Install the shared arbiter (when service.hbmBudget > 0) on
        first use — submit() must arbitrate HBM whether the service is
        embedded or start()ed; stop() uninstalls what we installed.
        Lock-guarded: concurrent first submissions must resolve to
        exactly one install (and one owner for stop() to undo)."""
        with self._install_lock:
            if (not self._installed_arbiter and self.arbiter.total > 0
                    and get_arbiter() is None):
                install_arbiter(self.arbiter)
                self._installed_arbiter = True

    def _lock_session(self, entry, session: str, query_id: str) -> None:
        """Lease the named session (its execution is serialized),
        bounded by the queueTimeoutMs discipline so a request stuck
        behind a long-running query sheds with a structured 503
        instead of waiting forever. Cancellable: the wait runs in
        token-capped slices (execution/lifecycle.py), so a DELETE or
        a blown queryDeadlineMs releases the waiter promptly."""
        timeout_ms = self.admission.queue_timeout_ms
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms > 0 else None)
        while True:
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            s = lifecycle.wait_slice(remaining)
            if entry.lock.acquire(timeout=s if s is not None else -1):
                return
            lifecycle.checkpoint("session_wait")
        self.metrics.counter("service_queue_timeout").inc()
        self._post("queue_timeout", query_id,
                   detail=f"session={session} busy", session=session)
        raise AdmissionTimeout(
            f"session '{session}' still busy after {timeout_ms:g}ms",
            session=session, queue_timeout_ms=timeout_ms)

    def _get_token(self, rid: str) -> Optional["lifecycle.CancelToken"]:
        with self._records_lock:
            return self._tokens.get(rid)

    def _drop_token(self, rid: str) -> None:
        with self._records_lock:
            self._tokens.pop(rid, None)

    def _finish_lifecycle(self, record: Dict, e: Exception,
                          session: str) -> None:
        """Record a cancelled/deadlined outcome: structured error body,
        terminal status, lifecycle counter (only when the query never
        reached the engine — executions that started already counted
        in the executor), and the service event."""
        cancelled = isinstance(e, lifecycle.QueryCancelledError)
        status = "cancelled" if cancelled else "deadline_exceeded"
        record["status"] = status
        record["error"] = {
            "error": ("QUERY_CANCELLED" if cancelled
                      else "QUERY_DEADLINE_EXCEEDED"),
            "message": f"{type(e).__name__}: {e}"[:400],
            "query_id": record["id"]}
        record["finished_ts"] = time.time()
        if "started_ts" not in record:
            self.metrics.counter(
                "query_cancelled" if cancelled
                else "query_deadline_exceeded").inc()
        self._post(status, record["id"], session=session)

    def submit(self, sql: str, session: str = "default",
               conf: Optional[Dict] = None):
        """Run `sql` on the named pooled session under admission
        control. Returns (record, Arrow table). Raises AdmissionError /
        PoolExhausted / the structured lifecycle errors, or whatever
        the engine raised; the record reflects the outcome either
        way."""
        self._check_draining()
        record = self._new_record(sql, session, conf)
        rid = record["id"]
        self._ensure_arbiter()
        self.metrics.counter("service_queries_submitted").inc()
        self._post("submitted", rid, session=session)
        ctx_token = lifecycle.install(self._get_token(rid))
        try:
            # per-session quota FIRST: a greedy session sheds at its
            # own bound before consuming a pool-wide queue slot
            self.session_quota.acquire(session)
            try:
                # session serialization next, admission slot second: a
                # request blocked behind a busy session must not hold
                # one of the maxConcurrent execution slots while doing
                # no work (it would starve other sessions' requests
                # into 429/503)
                entry = self.pool.get_or_create(session)
                self._lock_session(entry, session, rid)
                try:
                    # overrides land inside the same lock window the
                    # query executes in: sticky per-session SET
                    # semantics, and a concurrent request can neither
                    # clobber them before this query runs nor land its
                    # own mid-query
                    if conf:
                        for k, v in conf.items():
                            entry.session.conf.set(k, v)
                    with self.admission.slot(rid):
                        entry.current_record = record
                        record["status"] = "running"
                        record["started_ts"] = time.time()
                        try:
                            with entry.session.as_active():
                                qe = entry.session.sql(sql)._qe()
                                table = qe.collect()
                        finally:
                            entry.current_record = None
                finally:
                    entry.lock.release()
            finally:
                self.session_quota.release(session)
            # success bookkeeping INSIDE the try: the record must read
            # terminal before the finally drops the token, so a racing
            # DELETE never sees (running, no token) mid-transition
            record["status"] = "ok"
            record["row_count"] = int(table.num_rows)
            record["finished_ts"] = time.time()
            record["elapsed_ms"] = round(
                (record["finished_ts"] - record["started_ts"]) * 1e3, 1)
            self.metrics.counter("service_completed").inc()
            self._post("finished", rid, session=session)
        except AdmissionError as e:
            record["status"] = ("queue_timeout"
                                if e.code == "ADMISSION_TIMEOUT"
                                else "rejected")
            e.detail.setdefault("query_id", rid)
            record["error"] = e.to_dict()
            record["finished_ts"] = time.time()
            if e.code == "SESSION_QUOTA_EXCEEDED":
                # the AdmissionController counts its own rejections;
                # quota rejections get the same service-level
                # bookkeeping here (submit_async's quota catch does)
                self.metrics.counter("service_rejected").inc()
                self._post("rejected", rid, detail="sessionQuota",
                           session=session)
            raise
        except PoolExhausted as e:
            # capacity rejection, not an engine failure: must not count
            # into service_failed or read as EXECUTION_ERROR in the
            # record (the HTTP layer returns 429 for it)
            record["status"] = "rejected"
            record["error"] = e.to_dict()
            record["finished_ts"] = time.time()
            self.metrics.counter("service_rejected").inc()
            self._post("rejected", rid, detail="maxSessions",
                       session=session)
            raise
        except (lifecycle.QueryCancelledError,
                lifecycle.QueryDeadlineError) as e:
            self._finish_lifecycle(record, e, session)
            raise
        except Exception as e:  # noqa: BLE001 — recorded, then surfaced
            record["status"] = "error"
            code = ("INVALID_SQL"
                    if isinstance(e, (ParseError, AnalysisError))
                    else "UDF_ERROR" if isinstance(e, UdfError)
                    else "EXECUTION_ERROR")
            record["error"] = {"error": code,
                               "message": f"{type(e).__name__}: {e}"[:400]}
            if isinstance(e, UdfError):
                # the USER traceback captured inside the worker child —
                # the client debugs their lambda, not our pool framing
                record["error"]["traceback"] = e.worker_traceback
            record["finished_ts"] = time.time()
            self.metrics.counter("service_failed").inc()
            self._post("failed", rid, detail=type(e).__name__,
                       session=session)
            raise
        finally:
            lifecycle.uninstall(ctx_token)
            self._drop_token(rid)
        return record, table

    def submit_async(self, sql: str, session: str = "default",
                     conf: Optional[Dict] = None) -> Dict:
        """Fire-and-poll submission: returns the record immediately;
        progress lands on it (GET /queries/<id>). The worker thread
        holds no result — async is for effects/status, sync for data.
        Raises AdmissionRejected (structured, HTTP 429) when
        maxConcurrent + queueDepth async submissions are already in
        flight, or SessionQuotaExceeded at the per-session bound.

        The cancel token is created WITH the record, before the worker
        spawns: a DELETE arriving while the request is still queued
        cancels it out of the admission queue without it ever
        executing."""
        self._check_draining()
        record = self._new_record(sql, session, conf)
        try:
            self.session_quota.acquire(session)
        except AdmissionError as err:
            record["status"] = "rejected"
            err.detail.setdefault("query_id", record["id"])
            record["error"] = err.to_dict()
            record["finished_ts"] = time.time()
            self._drop_token(record["id"])
            self.metrics.counter("service_rejected").inc()
            self._post("rejected", record["id"],
                       detail="sessionQuota", session=session)
            raise
        bound = (self.admission.max_concurrent
                 + self.admission.queue_depth)
        # the bound check-and-increment is the only atomic part; the
        # rejection bookkeeping runs OUTSIDE the lock — _post takes
        # _records_lock, and holding _async_lock across it inverted
        # the registry's lock-order ranking (lock-order lint LO202)
        with self._async_lock:
            in_flight = self._async_inflight
            rejected = in_flight >= bound
            if not rejected:
                self._async_inflight += 1
        if rejected:
            self.session_quota.release(session)
            err = AdmissionRejected(
                f"async submissions in flight at bound "
                f"({in_flight}/{bound})",
                in_flight=in_flight, bound=bound,
                query_id=record["id"])
            record["status"] = "rejected"
            record["error"] = err.to_dict()
            record["finished_ts"] = time.time()
            self._drop_token(record["id"])
            self.metrics.counter("service_rejected").inc()
            self._post("rejected", record["id"],
                       detail="asyncInFlight", session=session)
            raise err

        tok = self._get_token(record["id"])

        def run():
            # re-drive through submit's machinery minus re-registration
            # (same ordering as submit: session lease, then slot). The
            # token installs on THIS worker thread: a cancel delivered
            # while queued raises out of the admission/session waits
            # and the request never executes (slot math intact).
            ctx_token = lifecycle.install(tok)
            try:
                entry = self.pool.get_or_create(session)
                self._lock_session(entry, session, record["id"])
                try:
                    if conf:
                        for k, v in conf.items():
                            entry.session.conf.set(k, v)
                    with self.admission.slot(record["id"]):
                        entry.current_record = record
                        record["status"] = "running"
                        record["started_ts"] = time.time()
                        try:
                            with entry.session.as_active():
                                t = entry.session.sql(sql)._qe().collect()
                            record["row_count"] = int(t.num_rows)
                            record["status"] = "ok"
                            self.metrics.counter(
                                "service_completed").inc()
                            self._post("finished", record["id"],
                                       session=session)
                        finally:
                            entry.current_record = None
                finally:
                    entry.lock.release()
            except AdmissionError as e:
                record["status"] = ("queue_timeout"
                                    if e.code == "ADMISSION_TIMEOUT"
                                    else "rejected")
                record["error"] = e.to_dict()
            except PoolExhausted as e:
                record["status"] = "rejected"
                record["error"] = e.to_dict()
                self.metrics.counter("service_rejected").inc()
                self._post("rejected", record["id"],
                           detail="maxSessions", session=session)
            except (lifecycle.QueryCancelledError,
                    lifecycle.QueryDeadlineError) as e:
                self._finish_lifecycle(record, e, session)
            except Exception as e:  # noqa: BLE001 — poll-visible
                record["status"] = "error"
                code = ("INVALID_SQL"
                        if isinstance(e, (ParseError, AnalysisError))
                        else "UDF_ERROR" if isinstance(e, UdfError)
                        else "EXECUTION_ERROR")
                record["error"] = {
                    "error": code,
                    "message": f"{type(e).__name__}: {e}"[:400]}
                if isinstance(e, UdfError):
                    record["error"]["traceback"] = e.worker_traceback
                self.metrics.counter("service_failed").inc()
                self._post("failed", record["id"], session=session)
            finally:
                lifecycle.uninstall(ctx_token)
                self._drop_token(record["id"])
                self.session_quota.release(session)
                with self._async_lock:
                    self._async_inflight -= 1
            record["finished_ts"] = time.time()

        try:
            self._ensure_arbiter()
            self.metrics.counter("service_queries_submitted").inc()
            self._post("submitted", record["id"], session=session)
            threading.Thread(target=run, daemon=True,
                             name=f"sql-{record['id']}").start()
        except BaseException as e:
            # Thread.start() can fail under thread exhaustion — the
            # exact overload quotas exist for. run()'s finally (the
            # only release path) never executes, so undo its
            # bookkeeping here or the session permanently loses a
            # quota slot (and the record reads 'submitted' forever,
            # unevictable)
            self.session_quota.release(session)
            with self._async_lock:
                self._async_inflight -= 1
            self._drop_token(record["id"])
            record["status"] = "error"
            record["error"] = {"error": "EXECUTION_ERROR",
                               "message": f"{type(e).__name__}: "
                                          f"{e}"[:400]}
            record["finished_ts"] = time.time()
            raise
        return record

    # -- endpoints' data ----------------------------------------------------

    #: status-record fields exposed in the GET /queries listing (the
    #: full record stays behind GET /queries/<id>)
    _LIST_FIELDS = ("id", "sql", "session", "status", "submitted_ts",
                    "started_ts", "finished_ts", "elapsed_ms",
                    "row_count", "engine_query_id")

    def query_listing(self, offset: int = 0, limit: int = 50,
                      status: Optional[str] = None,
                      session: Optional[str] = None) -> Dict:
        """Paginated query listing, newest first, optionally filtered
        by status / session name. Bounded by the same queryLogSize
        registry GET /queries/<id> reads from. Live streaming trigger
        loops (streaming.live_queries) ride along under `streams` —
        unpaginated; there are at most a handful per process."""
        from ..streaming import live_queries
        offset = max(0, int(offset))
        limit = max(1, min(int(limit), 500))
        with self._records_lock:
            # C-level copies under the lock: worker threads mutate the
            # live record dicts mid-listing
            records = [dict(r) for r in self._records.values()]
        records.reverse()  # insertion order == submission order
        if status is not None:
            records = [r for r in records if r.get("status") == status]
        if session is not None:
            records = [r for r in records if r.get("session") == session]
        page = records[offset:offset + limit]
        out = {"queries": [{k: r.get(k) for k in self._LIST_FIELDS
                            if k in r} for r in page],
               "total": len(records), "offset": offset, "limit": limit,
               # outside _records_lock by construction (this line runs
               # after the with block): live_queries takes its own
               # registry + per-query status locks
               "streams": live_queries()}
        if offset + limit < len(records):
            out["next_offset"] = offset + limit
        return out

    def query_timeline(self, query_id: str) -> Optional[Dict]:
        """Per-query flight-recorder view: phase spans + per-stage XLA
        flops/bytes/peak-HBM + per-shard records, from the history
        store (None when the id is unknown; a known-but-still-running
        query serves its status record with empty detail)."""
        rec = self.query_snapshot(query_id)
        if rec is None:
            return None
        detail = self.history.get(query_id) or {}
        return {"query_id": query_id,
                "status": rec.get("status"),
                "session": rec.get("session"),
                "engine_query_id": (rec.get("engine_query_id")
                                    or detail.get("engine_query_id")),
                "elapsed_ms": rec.get("elapsed_ms"),
                "phase_times_s": detail.get("phase_times_s")
                or rec.get("phase_times_s"),
                "spans": detail.get("spans") or [],
                "stages": detail.get("stages") or [],
                "shards": detail.get("shards") or [],
                "metrics": detail.get("metrics") or {},
                "predictions": detail.get("predictions") or [],
                "fault_summary": (detail.get("fault_summary")
                                  or rec.get("fault_summary"))}

    def query_plan(self, query_id: str) -> Optional[Dict]:
        """Explain view: the submitted SQL, the describe() fingerprint
        and the runtime-annotated physical tree."""
        rec = self.query_snapshot(query_id)
        if rec is None:
            return None
        detail = self.history.get(query_id) or {}
        reorder = detail.get("reorder") or {}
        return {"query_id": query_id,
                "status": rec.get("status"),
                "sql": rec.get("sql"),
                "plan": detail.get("plan"),
                "physical": detail.get("plan_tree"),
                # cost-based join-reorder verdict: yes/no + per-region
                # chosen order with per-join estimated rows, so a wrong
                # reorder is debuggable straight from the history API
                "reorder": ("yes" if reorder.get("changed") else "no")
                if reorder else None,
                "reorder_regions": reorder.get("regions") or [],
                "analysis_findings": detail.get("analysis_findings")
                or [],
                # per-rule optimizer application trace (schema v7):
                # which rules fired, how often, and (under
                # planChangeLog) the first effective tree diff
                "rule_trace": detail.get("rule_trace") or []}

    def cancel_query(self, query_id: str):
        """Request cooperative cancellation of a submitted/running
        query (the DELETE /queries/<id> seat). Returns (http_status,
        json_body) — 200 cancel_requested, 404 unknown id (structured,
        same error shape as 429/503), 409 already finished.
        Idempotent: a second DELETE of a still-stopping query returns
        another 200; cancel-after-finish is the 409.

        `stream-<n>` ids are live streaming trigger loops
        (streaming.live_queries): DELETE stops the loop — cancel the
        lifecycle token, join the thread bounded — leaving zero orphan
        threads and the checkpoint at its last committed batch."""
        if query_id.startswith("stream-"):
            from ..streaming import get_live
            q = get_live(query_id)
            if q is None:
                return 404, {"error": "NOT_FOUND",
                             "message": f"no live streaming query "
                                        f"{query_id!r}",
                             "query_id": query_id}
            q.stop()
            return 200, {"query_id": query_id, "status": "stopped",
                         "query_status": q.status}
        rec = self.get_query(query_id)
        if rec is None:
            return 404, {"error": "NOT_FOUND",
                         "message": f"unknown query id {query_id!r}",
                         "query_id": query_id}
        with self._records_lock:
            tok = self._tokens.get(query_id)
        status = rec.get("status")
        if tok is None or status not in ("submitted", "running"):
            return 409, {"error": "QUERY_FINISHED",
                         "message": f"query {query_id} already "
                                    f"finished (status={status})",
                         "query_id": query_id, "status": status}
        tok.cancel()
        self._post("cancel_requested", query_id,
                   session=rec.get("session", ""))
        return 200, {"query_id": query_id, "status": "cancel_requested"}

    def metrics_text(self) -> str:
        from ..observability.metrics import prometheus_text
        return prometheus_text(self.metrics.snapshot())

    @property
    def ready(self) -> bool:
        """Readiness: the warm-start manifest replay (when enabled)
        has completed — live-but-not-ready during the replay, so a
        fleet router withholds traffic instead of racing it."""
        return self._ready.is_set()

    def health(self) -> Dict:
        return {"status": "ok",
                "ready": self.ready,
                "draining": self._draining,
                "uptime_s": round(time.time() - self._started_ts, 1),
                "sessions": len(self.pool),
                "admission": self.admission.stats(),
                "session_quota": self.session_quota.stats(),
                "arbiter": self.arbiter.stats()
                if self._installed_arbiter else None}

    def debug_bundles(self) -> Dict:
        """On-demand flight-recorder dump, one bundle per pooled
        session (the GET /debug/bundle seat)."""
        bundles = []
        for name, session in self.pool.sessions().items():
            rec = FlightRecorder.of(session)
            if rec is None:
                continue
            path = rec.dump("on_demand", extra={"session": name})
            if path is not None:
                bundles.append({"session": name, "path": path})
        return {"bundles": bundles}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "SqlService":
        """Install the arbiter (when hbmBudget > 0), serve HTTP on
        service.{host,port} from a daemon thread, then warm-start the
        sessions-shared stage cache from the persistent compile cache
        (compileCache.{enabled,warmStart}) on a BACKGROUND thread — a
        restarted serving process opens hot (deserialization instead
        of XLA compiles) without delaying the socket bind: a full
        manifest replay must never hold /healthz at
        connection-refused. Queries racing the replay just compile as
        usual (the stage cache fills under them either way)."""
        self._ensure_arbiter()
        self.status_store.start()
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer(
            (str(self.conf.get(HOST_KEY)), int(self.conf.get(PORT_KEY))),
            handler)
        self._httpd.daemon_threads = True
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="sql-service-http")
        self._serve_thread.start()
        from ..execution import compile_cache as CC
        if bool(self.conf.get(CC.WARM_START_KEY)) \
                and CC.get_cache(self.conf) is not None:
            def warm():
                # live-but-not-ready while the manifest replays:
                # readiness flips in the finally so a replay failure
                # degrades to cold compiles, never a stuck NOT_READY
                try:
                    n = CC.warm_start(self.arbiter.stage_cache,
                                      self.conf, self.metrics)
                    if n:
                        self.metrics.gauge("service_warm_stages").set(n)
                finally:
                    self._ready.set()

            self._warm_thread = threading.Thread(
                target=warm, daemon=True, name="sql-service-warmstart")
            self._warm_thread.start()
        else:
            self._ready.set()
        return self

    @property
    def port(self) -> Optional[int]:
        return None if self._httpd is None \
            else self._httpd.server_address[1]

    def drain(self, timeout_ms: Optional[float] = None) -> bool:
        """Stop admitting (new submissions shed with a structured
        SERVICE_DRAINING 503) and wait — bounded by `timeout_ms`,
        default fleet.drainTimeoutMs — for in-flight work (running +
        queued + async threads) to finish. In-flight queries keep
        their own queryDeadlineMs budgets, so the wait is doubly
        bounded. Returns True when the service drained dry within the
        budget. Idempotent; safe before start()."""
        with self._stop_lock:
            self._draining = True
        if timeout_ms is None:
            timeout_ms = float(self.conf.get(DRAIN_TIMEOUT_KEY))
        deadline = time.monotonic() + float(timeout_ms) / 1e3
        while True:
            stats = self.admission.stats()
            with self._async_lock:
                n_async = self._async_inflight
            if (not stats.get("running") and not stats.get("queued")
                    and n_async == 0):
                self.metrics.counter("service_drains").inc()
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def stop(self) -> None:
        """Clean shutdown: stop accepting, close the socket, join the
        status-store heartbeat, uninstall the arbiter if this service
        installed it. Idempotent and signal-safe: _stop_lock
        serializes concurrent stops (a SIGTERM shutdown thread racing
        an explicit stop(), or a double-stop) — the second caller
        blocks on the bounded joins, then returns having torn nothing
        down twice. Safe during warm start: the replay thread is
        joined bounded (it only fills the waived stage_cache dict and
        never takes _stop_lock, so no deadlock)."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
            self._draining = True
            self.status_store.stop()
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()
                self._httpd = None
            if self._serve_thread is not None:
                self._serve_thread.join(timeout=10)
                self._serve_thread = None
            if self._warm_thread is not None:
                self._warm_thread.join(timeout=30)
                self._warm_thread = None
            with self._install_lock:
                if self._installed_arbiter:
                    install_arbiter(None)
                    self._installed_arbiter = False
        self._shutdown_event.set()

    def shutdown(self) -> None:
        """The drain path: shed new work, bounded-wait in-flight, then
        stop. What the SIGTERM/SIGINT handlers run (on a normal
        thread) and what a fleet worker does when its supervisor
        terminates it."""
        self.drain()
        self.stop()

    def install_signal_handlers(self) -> None:
        """Wire SIGTERM/SIGINT to the drain path. Handler-safe by
        construction: the handler only spawns a normal thread for
        shutdown() — stop() joins threads and takes locks, neither
        legal inside a signal frame. The handler deliberately does NOT
        set _shutdown_event: stop() sets it after teardown, so a
        worker main parked on wait_for_shutdown() stays parked until
        the drain has actually run (waking it early let the worker
        exit with in-flight queries — async ones especially — still
        running, silently skipping the bounded-drain guarantee).
        Double delivery (or a signal racing an explicit stop())
        serializes on _stop_lock and is a no-op the second time. Call
        from the main thread (CPython restricts signal.signal to
        it)."""
        import signal

        def _handler(signum, frame):
            threading.Thread(target=self.shutdown, daemon=True,
                             name="sql-service-shutdown").start()

        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _handler)

    def wait_for_shutdown(self,
                          timeout: Optional[float] = None) -> bool:
        """Park until stop() has completed — including the
        signal-driven drain path, which only sets the event once the
        drain ran and the service tore down (worker mains block here).
        Returns whether the event fired."""
        return self._shutdown_event.wait(timeout)


# ---------------------------------------------------------------------------
# HTTP plumbing
# ---------------------------------------------------------------------------


def _table_rows(table) -> list:
    """Arrow table -> JSON-friendly row dicts: decimals to float, dates
    and timestamps to ISO strings (repr-degrading them through the
    event-log encoder would leak Python syntax to HTTP clients)."""
    import datetime
    import decimal
    rows = table.to_pylist()
    for row in rows:
        for k, v in row.items():
            if isinstance(v, decimal.Decimal):
                row[k] = float(v)
            elif isinstance(v, (datetime.date, datetime.datetime)):
                row[k] = v.isoformat()
    return rows


def _make_handler(service: SqlService):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet: metrics cover it
            pass

        def _send_json(self, status: int, payload: Dict) -> None:
            body = json.dumps(payload, default=json_default).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, status: int, text: str,
                       content_type: str) -> None:
            body = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            from urllib.parse import parse_qs
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                self._send_json(200, service.health())
            elif path == "/healthz/live":
                # liveness: the socket answers — distinct from ready
                # (a worker replaying its warm-start manifest is live
                # but must not take routed traffic yet)
                self._send_json(200, {"live": True,
                                      "ready": service.ready})
            elif path == "/healthz/ready":
                if service.ready:
                    self._send_json(200, {"ready": True})
                else:
                    self._send_json(503, {
                        "error": "NOT_READY",
                        "message": "warm-start replay in progress",
                        "ready": False})
            elif path == "/status":
                self._send_json(200, service.status_store.snapshot())
            elif path == "/status/timeseries":
                qs = parse_qs(query)
                names = None
                if qs.get("series"):
                    names = [s for s in qs["series"][0].split(",") if s]
                try:
                    limit = (int(qs["limit"][0])
                             if qs.get("limit") else None)
                except (TypeError, ValueError) as e:
                    self._send_json(400, {"error": "BAD_REQUEST",
                                          "message": str(e)[:200]})
                    return
                self._send_json(200, service.status_store.timeseries(
                    names=names, limit=limit))
            elif path == "/debug/bundle":
                self._send_json(200, service.debug_bundles())
            elif path == "/metrics":
                self._send_text(
                    200, service.metrics_text(),
                    "text/plain; version=0.0.4; charset=utf-8")
            elif path in ("/queries", "/queries/"):
                qs = parse_qs(query)

                def arg(name, default=None):
                    v = qs.get(name)
                    return v[0] if v else default

                try:
                    listing = service.query_listing(
                        offset=int(arg("offset", 0)),
                        limit=int(arg("limit", 50)),
                        status=arg("status"), session=arg("session"))
                except (TypeError, ValueError) as e:
                    self._send_json(400, {"error": "BAD_REQUEST",
                                          "message": str(e)[:200]})
                    return
                self._send_json(200, listing)
            elif path.startswith("/queries/"):
                rest = path[len("/queries/"):]
                qid = rest
                if rest.endswith("/timeline"):
                    qid = rest[:-len("/timeline")]
                    payload = service.query_timeline(qid)
                elif rest.endswith("/plan"):
                    qid = rest[:-len("/plan")]
                    payload = service.query_plan(qid)
                else:
                    payload = service.query_snapshot(rest)
                if payload is None:
                    # structured 404: same error shape as the 429/503
                    # admission bodies (error + message + detail)
                    self._send_json(404, {
                        "error": "NOT_FOUND",
                        "message": f"unknown query id {qid!r}",
                        "query_id": qid})
                else:
                    self._send_json(200, payload)
            else:
                self._send_json(404, {"error": "NOT_FOUND",
                                      "message": path})

        def do_DELETE(self):  # noqa: N802 — BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0]
            if path.startswith("/queries/"):
                qid = path[len("/queries/"):]
                status, payload = service.cancel_query(qid)
                self._send_json(status, payload)
            else:
                self._send_json(404, {"error": "NOT_FOUND",
                                      "message": path})

        def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0]
            if path != "/sql":
                # drain the body first: on an HTTP/1.1 keep-alive
                # connection unread body bytes would be parsed as the
                # start of the NEXT request (stream desync)
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                self._send_json(404, {"error": "NOT_FOUND",
                                      "message": path})
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(n) or b"{}")
                sql = req.get("sql")
                if not sql or not isinstance(sql, str):
                    self._send_json(400, {
                        "error": "BAD_REQUEST",
                        "message": "body must be JSON with a 'sql' "
                                   "string"})
                    return
            except (ValueError, TypeError) as e:
                self._send_json(400, {"error": "BAD_REQUEST",
                                      "message": str(e)[:200]})
                return
            session = str(req.get("session") or "default")
            conf = req.get("conf") or None
            if req.get("mode") == "async":
                try:
                    record = service.submit_async(sql, session, conf)
                except AdmissionError as e:
                    self._send_json(e.http_status, e.to_dict())
                    return
                self._send_json(202, {"query_id": record["id"],
                                      "status": record["status"]})
                return
            try:
                record, table = service.submit(sql, session, conf)
            except AdmissionError as e:
                self._send_json(e.http_status, e.to_dict())
                return
            except PoolExhausted as e:
                self._send_json(429, e.to_dict())
                return
            except (ParseError, AnalysisError) as e:
                self._send_json(400, {
                    "error": "INVALID_SQL",
                    "message": f"{type(e).__name__}: {e}"[:400]})
                return
            except lifecycle.QueryCancelledError as e:
                # the sync request's query was DELETEd mid-flight:
                # structured body, 409 (the request conflicts with an
                # explicit cancel of its own resource)
                self._send_json(409, {
                    "error": "QUERY_CANCELLED",
                    "message": f"{type(e).__name__}: {e}"[:400]})
                return
            except lifecycle.QueryDeadlineError as e:
                self._send_json(504, {
                    "error": "QUERY_DEADLINE_EXCEEDED",
                    "message": f"{type(e).__name__}: {e}"[:400]})
                return
            except UdfError as e:
                # user code raised inside a UDF worker: the query is at
                # fault, not the engine — 400-class, with the worker-
                # captured USER traceback in the structured body
                self._send_json(400, {
                    "error": "UDF_ERROR",
                    "message": f"{type(e).__name__}: {e}"[:400],
                    "traceback": e.worker_traceback})
                return
            except Exception as e:  # noqa: BLE001 — structured surface
                self._send_json(500, {
                    "error": "EXECUTION_ERROR",
                    "message": f"{type(e).__name__}: {e}"[:400]})
                return
            if req.get("format") == "arrow":
                import io
                import pyarrow as pa
                buf = io.BytesIO()
                with pa.ipc.new_stream(buf, table.schema) as w:
                    w.write_table(table)
                body = buf.getvalue()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/vnd.apache.arrow.stream")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("X-Query-Id", record["id"])
                self.end_headers()
                self.wfile.write(body)
                return
            self._send_json(200, {
                "query_id": record["id"], "status": record["status"],
                "columns": table.column_names,
                "rows": _table_rows(table),
                "row_count": record.get("row_count"),
                "elapsed_ms": record.get("elapsed_ms")})

    return Handler

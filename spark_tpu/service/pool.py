"""Session pool: named long-lived sessions sharing process resources.

The `SparkSQLSessionManager` seat of the thriftserver: one pooled
`SparkTpuSession` per distinct session name, each with its OWN conf
overlay (a child `Conf` over the service base conf — the per-session
SQLConf clone) and its own catalog/UDF registry, but SHARING the
process resources the arbiter owns:

- one compiled-stage cache (`arbiter.stage_cache`) — the second
  session's identical query is a `compile_cache_hits` hit;
- one plan-fingerprint result cache (`arbiter.result_cache`);
- one metrics registry, so `GET /metrics` aggregates the fleet.

Execution per session is SERIALIZED (a per-session lock): the engine's
per-session state (query sequence, AQE cap store, exec depth) is
single-caller by design, so concurrency comes from running DIFFERENT
sessions' queries in parallel — exactly the thriftserver model of one
session per connection. Leasing a busy session blocks until it frees.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..config import Conf

MAX_SESSIONS_KEY = "spark_tpu.service.maxSessions"


class PoolExhausted(RuntimeError):
    """Structured error: a NEW session name past service.maxSessions."""

    def to_dict(self) -> Dict:
        return {"error": "POOL_EXHAUSTED", "message": str(self)}


class _Entry:
    __slots__ = ("name", "session", "lock", "current_record", "ready",
                 "init_error")

    def __init__(self, session, name: str = "default"):
        #: pool name: status-store attribution label for this session
        self.name = name
        self.session = session
        self.lock = threading.Lock()
        #: the service query record currently executing on this
        #: session (the status listener resolves events against it)
        self.current_record = None
        #: set once the (possibly slow) init_session hook has run —
        #: concurrent first requests for the same name wait on it
        #: instead of stalling the whole pool
        self.ready = threading.Event()
        self.init_error = None


class SessionPool:
    def __init__(self, base_conf: Conf, metrics, arbiter,
                 init_session: Optional[Callable] = None,
                 make_listener: Optional[Callable] = None):
        self._base_conf = base_conf
        self._metrics = metrics
        self._arbiter = arbiter
        self._init_session = init_session
        self._make_listener = make_listener
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self.max_sessions = int(base_conf.get(MAX_SESSIONS_KEY))

    def _create(self, name: str) -> _Entry:
        from ..session import SparkTpuSession
        conf = Conf(parent=self._base_conf)
        # register_active=False: a pooled session must not become the
        # process-global active session (worker threads pin it per
        # query with session.as_active())
        s = SparkTpuSession(conf, register_active=False)
        # swap in the shared process resources (see module docstring)
        s.metrics = self._metrics
        s._stage_cache = self._arbiter.stage_cache
        s._data_cache = self._arbiter.result_cache
        entry = _Entry(s, name)
        if self._make_listener is not None:
            s.add_listener(self._make_listener(entry))
        return entry

    def get_or_create(self, name: str = "default") -> _Entry:
        """Fetch the named session, creating it (bounded by
        service.maxSessions) on first use. Conf overrides are the
        CALLER's job, applied while holding `entry.lock` (the server
        does) so a request's overrides and its execution are atomic —
        a concurrent request naming the same session can neither
        clobber them pre-execution nor land them mid-query."""
        with self._lock:
            entry = self._entries.get(name)
            creating = entry is None
            if creating:
                if len(self._entries) >= self.max_sessions:
                    raise PoolExhausted(
                        f"session pool full "
                        f"({len(self._entries)}/{self.max_sessions}); "
                        f"reuse an existing session name")
                entry = self._entries[name] = self._create(name)
                self._metrics.gauge("service_sessions").set(
                    len(self._entries))
        if not creating:
            # the creator may still be inside init_session: wait for
            # it rather than handing out a half-initialized session
            entry.ready.wait()
            if entry.init_error is not None:
                raise RuntimeError(
                    f"session '{name}' failed to initialize: "
                    f"{entry.init_error}") from entry.init_error
            return entry
        # run the user init hook OUTSIDE the pool lock: registering
        # tables reads Parquet schemas (easily seconds) and lookups of
        # every OTHER session must not stall behind it
        try:
            if self._init_session is not None:
                self._init_session(entry.session)
        except BaseException as e:
            entry.init_error = e
            with self._lock:
                self._entries.pop(name, None)
                self._metrics.gauge("service_sessions").set(
                    len(self._entries))
            entry.ready.set()
            raise
        entry.ready.set()
        return entry

    def sessions(self) -> Dict[str, object]:
        with self._lock:
            return {n: e.session for n, e in self._entries.items()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

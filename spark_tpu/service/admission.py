"""Admission control for the SQL service: bounded concurrency + queue.

The thriftserver seat of a bounded execution pool: at most
`spark_tpu.service.maxConcurrent` queries execute at once; up to
`spark_tpu.service.queueDepth` more wait; anything past that is
rejected IMMEDIATELY with a structured error (HTTP 429 at the server),
and a queued query that waits longer than
`spark_tpu.service.queueTimeoutMs` fails with a structured timeout —
load sheds at the front door instead of growing an unbounded backlog
(the reference rejects at the pool the same way).

Queue waits are CANCELLABLE (execution/lifecycle.py): with a cancel
token installed — the service installs one per request — the cv wait
runs in short slices capped by the remaining queryDeadlineMs budget,
and a cancelled/deadlined waiter leaves the queue with its slot math
intact, never having executed.

`SessionQuota` adds the per-session half
(`spark_tpu.service.session.maxConcurrent`): one session name's
in-flight submissions are bounded separately, so a single greedy
session cannot consume every queue slot and starve the pool —
exceeding it rejects with SESSION_QUOTA_EXCEEDED (HTTP 429) and
counts `session_quota_rejections`.

Every transition posts a typed `ServiceEvent` on the service bus and
counts into the shared metrics registry, so `GET /metrics` shows
admitted/queued/rejected/timeout totals live.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

SESSION_MAX_CONCURRENT_KEY = "spark_tpu.service.session.maxConcurrent"


class AdmissionError(RuntimeError):
    """Base for structured admission failures: `to_dict()` is the HTTP
    error body (and the shape tests assert on)."""

    code = "ADMISSION_ERROR"
    http_status = 500

    def __init__(self, message: str, **detail):
        super().__init__(message)
        self.detail = detail

    def to_dict(self) -> Dict:
        return {"error": self.code, "message": str(self), **self.detail}


class AdmissionRejected(AdmissionError):
    """Queue full: the submission was never queued."""

    code = "ADMISSION_REJECTED"
    http_status = 429


class AdmissionTimeout(AdmissionError):
    """Queued, but no slot freed within queueTimeoutMs."""

    code = "ADMISSION_TIMEOUT"
    http_status = 503


class ServiceDraining(AdmissionError):
    """The service is draining (SIGTERM / explicit drain()): new
    submissions shed at the front door while in-flight queries finish
    under the bounded drain budget. 503: the condition is transient —
    a router retries elsewhere."""

    code = "SERVICE_DRAINING"
    http_status = 503


class SessionQuotaExceeded(AdmissionError):
    """The session's per-session in-flight quota
    (spark_tpu.service.session.maxConcurrent) is full."""

    code = "SESSION_QUOTA_EXCEEDED"
    http_status = 429


class SessionQuota:
    """Per-session in-flight submission counter. `acquire` is
    check-and-increment under the quota lock; rejection bookkeeping
    (counter + structured raise) runs outside it. 0 = unlimited."""

    def __init__(self, max_per_session: int, metrics=None):
        self.max_per_session = int(max_per_session)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}

    def acquire(self, session: str) -> None:
        """Count one in-flight submission for `session`; raises
        SessionQuotaExceeded (structured, HTTP 429) at the bound."""
        if self.max_per_session <= 0:
            return
        with self._lock:
            n = self._inflight.get(session, 0)
            over = n >= self.max_per_session
            if not over:
                self._inflight[session] = n + 1
        if over:
            if self.metrics is not None:
                self.metrics.counter("session_quota_rejections").inc()
            raise SessionQuotaExceeded(
                f"session '{session}' at its in-flight quota "
                f"({n}/{self.max_per_session})",
                session=session, in_flight=n,
                session_max_concurrent=self.max_per_session)

    def release(self, session: str) -> None:
        if self.max_per_session <= 0:
            return
        with self._lock:
            n = self._inflight.get(session, 0) - 1
            if n <= 0:
                self._inflight.pop(session, None)
            else:
                self._inflight[session] = n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"max_per_session": self.max_per_session,
                    "sessions_in_flight": dict(self._inflight)}


class AdmissionController:
    """Condition-variable slot gate. `slot(...)` is a context manager:
    entering acquires (or queues for) an execution slot, exiting
    releases it and wakes the queue head."""

    def __init__(self, max_concurrent: int, queue_depth: int,
                 queue_timeout_ms: float, metrics=None, on_event=None):
        self.max_concurrent = int(max_concurrent)
        self.queue_depth = int(queue_depth)
        self.queue_timeout_ms = float(queue_timeout_ms)
        self.metrics = metrics
        #: callable(action, query_id, detail) -> None; the service
        #: routes these onto its listener bus as ServiceEvents
        self.on_event = on_event or (lambda *a, **k: None)
        self._cv = threading.Condition()
        self.running = 0
        self.queued = 0

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc()

    def _gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("service_running").set(self.running)
            self.metrics.gauge("service_queued").set(self.queued)

    def acquire(self, query_id: str = "") -> None:
        """Take an execution slot, queueing within bounds. Raises
        AdmissionRejected / AdmissionTimeout (structured), or the
        structured lifecycle error when the request's cancel token was
        cancelled / its deadline blew while queued (the waiter leaves
        the queue without ever executing; slot math intact)."""
        from ..execution import lifecycle
        # cooperative boundary before taking (or queueing for) a slot
        lifecycle.checkpoint("admission")
        deadline = None
        if self.queue_timeout_ms > 0:
            deadline = time.monotonic() + self.queue_timeout_ms / 1e3
        with self._cv:
            # fast path only when nobody is queued: a fresh arrival
            # must not steal a freed slot ahead of waiters (barging
            # would starve queued requests into 503s under a steady
            # arrival stream)
            if self.running < self.max_concurrent and self.queued == 0:
                self.running += 1
                self._count("service_admitted")
                self._gauges()
                self.on_event("admitted", query_id)
                return
            if self.queued >= self.queue_depth:
                self._count("service_rejected")
                self.on_event("rejected", query_id,
                              f"queueDepth={self.queue_depth}")
                raise AdmissionRejected(
                    f"admission queue full "
                    f"(running={self.running}, "
                    f"queued={self.queued}/{self.queue_depth})",
                    running=self.running, queued=self.queued,
                    queue_depth=self.queue_depth,
                    max_concurrent=self.max_concurrent)
            self.queued += 1
            self._count("service_queued_total")
            self._gauges()
            self.on_event("queued", query_id)
            try:
                while self.running >= self.max_concurrent:
                    remaining = None if deadline is None \
                        else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        self._count("service_queue_timeout")
                        self.on_event(
                            "queue_timeout", query_id,
                            f"queueTimeoutMs={self.queue_timeout_ms:g}")
                        raise AdmissionTimeout(
                            f"no execution slot within "
                            f"{self.queue_timeout_ms:g}ms "
                            f"(running={self.running}, "
                            f"queued={self.queued})",
                            running=self.running, queued=self.queued,
                            queue_timeout_ms=self.queue_timeout_ms)
                    # token-capped wait: with a cancel token installed
                    # the wait runs in short slices (bounded by the
                    # remaining deadline budget) and re-checks the
                    # token each wakeup, so DELETE /queries/<id> or a
                    # blown queryDeadlineMs lands within ~one slice
                    # instead of after queueTimeoutMs
                    self._cv.wait(lifecycle.wait_slice(remaining))
                    lifecycle.checkpoint("queue_wait")
            finally:
                self.queued -= 1
                self._gauges()
            self.running += 1
            self._count("service_admitted")
            self._gauges()
            self.on_event("admitted", query_id)

    def release(self) -> None:
        with self._cv:
            self.running -= 1
            self._gauges()
            self._cv.notify()

    class _Slot:
        def __init__(self, ctl: "AdmissionController", query_id: str):
            self._ctl = ctl
            self._query_id = query_id

        def __enter__(self):
            self._ctl.acquire(self._query_id)
            return self

        def __exit__(self, *exc):
            self._ctl.release()
            return False

    def slot(self, query_id: str = "") -> "_Slot":
        return self._Slot(self, query_id)

    def stats(self) -> Dict[str, Optional[int]]:
        with self._cv:
            return {"running": self.running, "queued": self.queued,
                    "max_concurrent": self.max_concurrent,
                    "queue_depth": self.queue_depth}

"""Admission control for the SQL service: bounded concurrency + queue.

The thriftserver seat of a bounded execution pool: at most
`spark_tpu.service.maxConcurrent` queries execute at once; up to
`spark_tpu.service.queueDepth` more wait; anything past that is
rejected IMMEDIATELY with a structured error (HTTP 429 at the server),
and a queued query that waits longer than
`spark_tpu.service.queueTimeoutMs` fails with a structured timeout —
load sheds at the front door instead of growing an unbounded backlog
(the reference rejects at the pool the same way).

Every transition posts a typed `ServiceEvent` on the service bus and
counts into the shared metrics registry, so `GET /metrics` shows
admitted/queued/rejected/timeout totals live.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class AdmissionError(RuntimeError):
    """Base for structured admission failures: `to_dict()` is the HTTP
    error body (and the shape tests assert on)."""

    code = "ADMISSION_ERROR"
    http_status = 500

    def __init__(self, message: str, **detail):
        super().__init__(message)
        self.detail = detail

    def to_dict(self) -> Dict:
        return {"error": self.code, "message": str(self), **self.detail}


class AdmissionRejected(AdmissionError):
    """Queue full: the submission was never queued."""

    code = "ADMISSION_REJECTED"
    http_status = 429


class AdmissionTimeout(AdmissionError):
    """Queued, but no slot freed within queueTimeoutMs."""

    code = "ADMISSION_TIMEOUT"
    http_status = 503


class AdmissionController:
    """Condition-variable slot gate. `slot(...)` is a context manager:
    entering acquires (or queues for) an execution slot, exiting
    releases it and wakes the queue head."""

    def __init__(self, max_concurrent: int, queue_depth: int,
                 queue_timeout_ms: float, metrics=None, on_event=None):
        self.max_concurrent = int(max_concurrent)
        self.queue_depth = int(queue_depth)
        self.queue_timeout_ms = float(queue_timeout_ms)
        self.metrics = metrics
        #: callable(action, query_id, detail) -> None; the service
        #: routes these onto its listener bus as ServiceEvents
        self.on_event = on_event or (lambda *a, **k: None)
        self._cv = threading.Condition()
        self.running = 0
        self.queued = 0

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc()

    def _gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("service_running").set(self.running)
            self.metrics.gauge("service_queued").set(self.queued)

    def acquire(self, query_id: str = "") -> None:
        """Take an execution slot, queueing within bounds. Raises
        AdmissionRejected / AdmissionTimeout (structured)."""
        deadline = None
        if self.queue_timeout_ms > 0:
            deadline = time.monotonic() + self.queue_timeout_ms / 1e3
        with self._cv:
            # fast path only when nobody is queued: a fresh arrival
            # must not steal a freed slot ahead of waiters (barging
            # would starve queued requests into 503s under a steady
            # arrival stream)
            if self.running < self.max_concurrent and self.queued == 0:
                self.running += 1
                self._count("service_admitted")
                self._gauges()
                self.on_event("admitted", query_id)
                return
            if self.queued >= self.queue_depth:
                self._count("service_rejected")
                self.on_event("rejected", query_id,
                              f"queueDepth={self.queue_depth}")
                raise AdmissionRejected(
                    f"admission queue full "
                    f"(running={self.running}, "
                    f"queued={self.queued}/{self.queue_depth})",
                    running=self.running, queued=self.queued,
                    queue_depth=self.queue_depth,
                    max_concurrent=self.max_concurrent)
            self.queued += 1
            self._count("service_queued_total")
            self._gauges()
            self.on_event("queued", query_id)
            try:
                while self.running >= self.max_concurrent:
                    remaining = None if deadline is None \
                        else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        self._count("service_queue_timeout")
                        self.on_event(
                            "queue_timeout", query_id,
                            f"queueTimeoutMs={self.queue_timeout_ms:g}")
                        raise AdmissionTimeout(
                            f"no execution slot within "
                            f"{self.queue_timeout_ms:g}ms "
                            f"(running={self.running}, "
                            f"queued={self.queued})",
                            running=self.running, queued=self.queued,
                            queue_timeout_ms=self.queue_timeout_ms)
                    self._cv.wait(remaining)
            finally:
                self.queued -= 1
                self._gauges()
            self.running += 1
            self._count("service_admitted")
            self._gauges()
            self.on_event("admitted", query_id)

    def release(self) -> None:
        with self._cv:
            self.running -= 1
            self._gauges()
            self._cv.notify()

    class _Slot:
        def __init__(self, ctl: "AdmissionController", query_id: str):
            self._ctl = ctl
            self._query_id = query_id

        def __enter__(self):
            self._ctl.acquire(self._query_id)
            return self

        def __exit__(self, *exc):
            self._ctl.release()
            return False

    def slot(self, query_id: str = "") -> "_Slot":
        return self._Slot(self, query_id)

    def stats(self) -> Dict[str, Optional[int]]:
        with self._cv:
            return {"running": self.running, "queued": self.queued,
                    "max_concurrent": self.max_concurrent,
                    "queue_depth": self.queue_depth}

"""Crash-only serving fleet: supervisor + router over SqlService workers.

One host, N `SqlService` worker SUBPROCESSES, one public port. The
supervisor owns the socket; workers bind ephemeral loopback ports and
share the persistent compile cache directory
(spark_tpu.sql.compileCache.dir), so a respawned worker opens
hot — warm-start manifest replay instead of XLA recompiles. The
design is crash-only (Candea & Fox): workers hold NO durable state
(query records are in-memory; results are re-derivable because the
engine is deterministic and the compile cache is shared), so the
recovery path from kill -9 IS the start path, and the supervisor
exercises it routinely instead of treating it as an exception.

Routing — session affinity by consistent hash:
    Each worker owns `_VNODES` points on an md5 hash ring; a session
    name hashes to a preference-ordered worker list (walk the ring).
    Queries from one session land on one worker (its session-scoped
    catalog state — CREATE TABLE AS SELECT tables, conf overrides —
    lives there), and when that worker dies the session re-homes to
    the NEXT ring position deterministically, without reshuffling any
    other session's placement.

Failover — reads retry once, everything else surfaces loss:
    A worker that dies mid-request is detected by the broken proxy
    connection. Idempotent reads (SELECT/WITH/VALUES/EXPLAIN/SHOW/
    DESCRIBE, conf fleet.failoverReads) transparently retry ONCE on
    the re-homed worker — byte parity holds because the engine is
    deterministic and the compile cache is shared. Writes and
    unclassifiable statements get a structured 503 WORKER_LOST with
    the fleet request id: re-running them is the CLIENT's decision.
    Query ids embed worker index + generation (`q-w0g2-5` via
    spark_tpu.service.idPrefix), so GET/DELETE /queries/<id> routes
    without a lookup table and a stale generation answers 503
    WORKER_LOST — in-memory records died with the worker, and the
    router says so instead of 404-ing.

Supervision — RetryPolicy ladder with a flap breaker:
    The health thread (fleet-health) polls child processes, probes
    /healthz/ready (live-but-not-ready workers — warm-start replay in
    progress — take no traffic), and respawns crashes under the
    shared `RetryPolicy` exponential-backoff ladder
    (fleet.restartBackoffMs). K crashes inside fleet.restartWindowMs
    (fleet.restartMaxPerWindow) trips the breaker: the worker is
    QUARANTINED (no respawn storm against a deterministic boot
    failure) and its traffic sheds with the same structured 503
    machinery admission control uses. Every death dumps a flight
    bundle (MANIFEST.json + stderr tail) under fleet.dir.

Drain — SIGTERM is a first-class exit:
    `shutdown()` (wired to SIGTERM/SIGINT by the CLI) stops admitting
    (503 FLEET_DRAINING), waits bounded (fleet.drainTimeoutMs) for
    in-flight proxied queries, then SIGTERMs workers — each runs its
    own SqlService drain path — and reaps them. kill -9 the
    supervisor and the workers die with it (they are direct children
    watched by pipes; the chaos matrix asserts zero orphans).

Worker protocol (stdlib-only, no IPC framework):
    supervisor spawns  python -m spark_tpu.service.fleet --worker
    with SPARK_TPU_FLEET_CONF (JSON conf overrides: port=0, loopback
    host, idPrefix=w<idx>g<gen>-) and SPARK_TPU_FLEET_IDX; the worker
    starts, prints ONE stdout JSON handshake line
    {"spark_tpu_fleet_worker": idx, "port": p, "pid": pid}, installs
    SIGTERM/SIGINT drain handlers, and parks on wait_for_shutdown().
"""

from __future__ import annotations

import bisect
import collections
import hashlib
import http.client
import itertools
import json
import os
import random
import re
import subprocess
import sys
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..config import Conf
from ..observability.metrics import MetricsRegistry, prometheus_text
from ..observability.status_store import StatusStore
from .admission import AdmissionError

WORKERS_KEY = "spark_tpu.service.fleet.workers"
RESTART_MAX_KEY = "spark_tpu.service.fleet.restartMaxPerWindow"
RESTART_WINDOW_KEY = "spark_tpu.service.fleet.restartWindowMs"
RESTART_BACKOFF_KEY = "spark_tpu.service.fleet.restartBackoffMs"
DRAIN_TIMEOUT_KEY = "spark_tpu.service.fleet.drainTimeoutMs"
FAILOVER_READS_KEY = "spark_tpu.service.fleet.failoverReads"
HEALTH_INTERVAL_KEY = "spark_tpu.service.fleet.healthIntervalMs"
SPAWN_TIMEOUT_KEY = "spark_tpu.service.fleet.spawnTimeoutMs"
PROXY_TIMEOUT_KEY = "spark_tpu.service.fleet.proxyTimeoutMs"
FLEET_DIR_KEY = "spark_tpu.service.fleet.dir"
INIT_KEY = "spark_tpu.service.fleet.init"
HOST_KEY = "spark_tpu.service.host"
PORT_KEY = "spark_tpu.service.port"
ID_PREFIX_KEY = "spark_tpu.service.idPrefix"

ENV_CONF = "SPARK_TPU_FLEET_CONF"
ENV_IDX = "SPARK_TPU_FLEET_IDX"

#: virtual nodes per worker on the hash ring — enough that removing
#: one worker re-homes its sessions roughly evenly across survivors
_VNODES = 64

#: monotonically numbers supervisors in one process so their thread
#: names never collide (see FleetSupervisor.thread_prefix)
_SUP_IDS = itertools.count(1)

#: consecutive liveness-ping failures before a ready worker is
#: declared wedged and recycled through the crash ladder
_PING_FAILURE_LIMIT = 3

#: worker query ids are `q-w<idx>g<generation>-<seq>`; the router
#: parses ownership out of the id instead of keeping a lookup table
_QID_RE = re.compile(r"^q-w(\d+)g(\d+)-")

_READ_KEYWORDS = ("SELECT", "WITH", "VALUES", "EXPLAIN", "SHOW",
                  "DESCRIBE")


def _is_read(sql: str) -> bool:
    """True when the statement is an idempotent read — safe to retry
    once on a re-homed worker after the original died mid-query.
    Unknown/unparseable statements classify as NOT reads (failover
    must never replay a write)."""
    s = sql or ""
    while True:
        s = s.lstrip()
        if s.startswith("--"):
            nl = s.find("\n")
            if nl < 0:
                return False
            s = s[nl + 1:]
        else:
            break
    m = re.match(r"[A-Za-z]+", s)
    return bool(m) and m.group(0).upper() in _READ_KEYWORDS


class FleetDraining(AdmissionError):
    """The fleet is draining (SIGTERM / explicit drain()): the router
    sheds new submissions while in-flight proxied queries finish."""

    code = "FLEET_DRAINING"
    http_status = 503


class FleetUnavailable(AdmissionError):
    """No ready worker to route to — every worker is crashed, still
    warm-starting, or quarantined by the flap breaker. Structured 503
    like the admission shed path: transient, a client retries."""

    code = "FLEET_UNAVAILABLE"
    http_status = 503


class _WorkerLost(Exception):
    """Internal: the proxy connection to a worker broke mid-request
    (the worker died, or its socket did — crash-only treats both as
    death)."""


class _Worker:
    """Supervisor-side record of one worker slot. All mutable fields
    are guarded by the per-instance `_lock` (concurrency registry:
    service.fleet_worker, rank 13) and mutated ONLY through methods
    here; the supervisor reads via `snapshot()`/`info()`.

    States: stopped -> starting -> live -> ready
                         \\-> crashed -> backoff -> starting ...
                                     \\-> quarantined
    """

    def __init__(self, idx: int):
        self.idx = idx
        self._lock = threading.Lock()
        self.state = "stopped"
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.pid: Optional[int] = None
        #: bumped per spawn; baked into the worker's query-id prefix
        #: (q-w<idx>g<gen>-...) so stale ids route to 503 WORKER_LOST
        self.generation = 0
        #: RetryPolicy ladder for the current crash burst (None until
        #: the first crash; reset when the budget is consumed)
        self.policy = None
        self.next_spawn_ts = 0.0
        self.spawn_deadline_ts = 0.0
        self.ping_failures = 0
        # append-only ring buffers: deque ops are GIL-atomic and these
        # are never rebound after __init__
        self.crash_times: collections.deque = collections.deque(
            maxlen=32)
        self.stderr_tail: collections.deque = collections.deque(
            maxlen=200)

    # -- spawn-side transitions (health thread only) ---------------------

    def begin_spawn(self, deadline_ts: float) -> int:
        with self._lock:
            self.generation += 1
            self.state = "starting"
            self.spawn_deadline_ts = deadline_ts
            self.ping_failures = 0
            self.port = None
            self.pid = None
            return self.generation

    def attach_proc(self, proc: subprocess.Popen) -> None:
        with self._lock:
            self.proc = proc
            self.pid = proc.pid

    def note_handshake(self, gen: int, port: int, pid: int) -> None:
        """Stdout-reader thread: the worker printed its handshake.
        Generation-checked — a stale reader from a previous spawn
        must not resurrect a respawned slot."""
        with self._lock:
            if gen != self.generation or self.state != "starting":
                return
            self.port = port
            self.pid = pid
            self.state = "live"

    def mark_ready(self) -> None:
        with self._lock:
            if self.state == "live":
                self.state = "ready"
                self.ping_failures = 0

    # -- failure-side transitions ----------------------------------------

    def mark_lost(self) -> bool:
        """Router-observed death (broken proxy connection): flip to
        crashed so routing skips the slot immediately; the health
        thread reaps and schedules the respawn."""
        with self._lock:
            if self.state in ("ready", "live"):
                self.state = "crashed"
                return True
            return False

    def note_ping_failure(self) -> int:
        with self._lock:
            self.ping_failures += 1
            return self.ping_failures

    def reset_ping_failures(self) -> None:
        with self._lock:
            if self.ping_failures:
                self.ping_failures = 0

    def take_proc(self) -> Optional[Dict]:
        """Claim the dead/dying process for reaping (health thread).
        Returns None when the slot was already handled — the guard
        that makes a router-marked death and the health tick's own
        poll detection converge on ONE crash accounting."""
        with self._lock:
            if self.state not in ("starting", "live", "ready",
                                  "crashed"):
                return None
            out = {"proc": self.proc, "port": self.port,
                   "pid": self.pid, "generation": self.generation}
            self.proc = None
            self.port = None
            self.state = "crashed"
            return out

    def record_crash(self, now: float, window_s: float,
                     max_per_window: int,
                     backoff_ms: float) -> Optional[float]:
        """Account one crash: flap breaker first (>= max_per_window
        crashes inside window_s -> quarantined, returns None), else
        schedule the respawn under the RetryPolicy exponential-backoff
        ladder and return the delay in ms."""
        from ..execution.failures import RetryPolicy
        with self._lock:
            self.crash_times.append(now)
            recent = sum(1 for t in self.crash_times
                         if now - t <= window_s)
            if recent >= max_per_window:
                self.state = "quarantined"
                self.policy = None
                return None
            if self.policy is None or self.policy.remaining <= 0:
                # no-op sleep: attempt_retry() returns the jittered
                # delay without blocking the health thread; seeded rng
                # keeps chaos tests deterministic
                self.policy = RetryPolicy(
                    max_per_window, backoff_ms,
                    sleep=lambda s: None,
                    rng=random.Random(self.idx * 7919
                                      + self.generation))
            policy = self.policy
        # attempt_retry runs a lifecycle checkpoint (chaos seam) —
        # outside _lock so the fault/lifecycle machinery never nests
        # under the worker lock
        delay_ms = policy.attempt_retry()
        if delay_ms is None:
            delay_ms = float(backoff_ms)
        with self._lock:
            self.state = "backoff"
            self.next_spawn_ts = now + delay_ms / 1e3
        return delay_ms

    def mark_stopped(self) -> Optional[subprocess.Popen]:
        with self._lock:
            proc = self.proc
            self.proc = None
            self.port = None
            self.state = "stopped"
            return proc

    # -- reads ------------------------------------------------------------

    def snapshot(self) -> Dict:
        with self._lock:
            return {"state": self.state, "proc": self.proc,
                    "port": self.port, "pid": self.pid,
                    "generation": self.generation,
                    "next_spawn_ts": self.next_spawn_ts,
                    "spawn_deadline_ts": self.spawn_deadline_ts,
                    "ping_failures": self.ping_failures}

    def info(self) -> Dict:
        """JSON-safe view for /fleet, /healthz and the status store."""
        with self._lock:
            return {"worker": self.idx, "state": self.state,
                    "port": self.port, "pid": self.pid,
                    "generation": self.generation,
                    "restarts": max(0, self.generation - 1),
                    "crashes": len(self.crash_times)}


class FleetSupervisor:
    """Owns the public port, the worker slots, and the health loop.
    `start()` spawns the workers and serves; `shutdown()` is the
    SIGTERM drain path; `stop()` is the fast teardown (tests'
    finally blocks)."""

    def __init__(self, conf: Optional[Conf] = None):
        self.conf = conf or Conf()
        self.metrics = MetricsRegistry()
        #: per-instance thread-name prefix: lockwatch leak checks (and
        #: humans reading thread dumps) must be able to tell THIS
        #: fleet's threads from another supervisor's in the same
        #: process (tests run several)
        self.thread_prefix = f"fleet{next(_SUP_IDS)}-"
        n = int(self.conf.get(WORKERS_KEY))
        self._workers = [_Worker(i) for i in range(n)]
        #: guards _inflight/_draining/_stopped/_seq (concurrency
        #: registry: service.fleet_inflight, rank 12 — below the
        #: per-worker lock, so cv -> worker._lock nests ascending)
        self._cv = threading.Condition()
        self._inflight = 0
        self._draining = False
        self._stopped = False
        self._seq = 0
        ring: List[Tuple[int, int]] = []
        for idx in range(n):
            for v in range(_VNODES):
                point = int(hashlib.md5(
                    f"w{idx}#{v}".encode()).hexdigest()[:8], 16)
                ring.append((point, idx))
        self._ring = sorted(ring)
        self._ring_points = [p for p, _ in self._ring]
        self._window_s = float(self.conf.get(RESTART_WINDOW_KEY)) / 1e3
        self._max_per_window = int(self.conf.get(RESTART_MAX_KEY))
        self._backoff_ms = float(self.conf.get(RESTART_BACKOFF_KEY))
        self._drain_timeout_ms = float(
            self.conf.get(DRAIN_TIMEOUT_KEY))
        self._failover_reads = bool(self.conf.get(FAILOVER_READS_KEY))
        self._interval_s = float(
            self.conf.get(HEALTH_INTERVAL_KEY)) / 1e3
        self._spawn_timeout_s = float(
            self.conf.get(SPAWN_TIMEOUT_KEY)) / 1e3
        self._proxy_timeout_s = float(
            self.conf.get(PROXY_TIMEOUT_KEY)) / 1e3
        d = str(self.conf.get(FLEET_DIR_KEY) or "")
        self._fleet_dir = d or os.path.join(
            tempfile.gettempdir(), f"spark-tpu-fleet-{os.getpid()}")
        self._bundle_seq = itertools.count()
        self._started_ts = time.time()
        self._health_stop = threading.Event()
        self._shutdown_event = threading.Event()
        self.status_store = StatusStore(self.conf, self.metrics, {
            "fleet": self.stats,
        })
        # lifecycle attrs (guarded-by waiver): written only by the
        # owning control thread in start()/teardown
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        os.makedirs(self._fleet_dir, exist_ok=True)
        self.status_store.start()
        self._httpd = ThreadingHTTPServer(
            (str(self.conf.get(HOST_KEY)),
             int(self.conf.get(PORT_KEY))),
            _make_router(self))
        self._httpd.daemon_threads = True
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=self.thread_prefix + "http")
        self._serve_thread.start()
        for w in self._workers:
            self._spawn(w)
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name=self.thread_prefix + "health")
        self._health_thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return None if self._httpd is None \
            else self._httpd.server_address[1]

    def ready_count(self) -> int:
        return sum(1 for w in self._workers
                   if w.snapshot()["state"] == "ready")

    def wait_ready(self, timeout_s: float = 120.0,
                   n: Optional[int] = None) -> bool:
        """Block until `n` (default: all) workers are ready — the
        test/CLI helper mirroring a k8s readiness gate."""
        want = len(self._workers) if n is None else int(n)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ready_count() >= want:
                return True
            time.sleep(0.05)
        return self.ready_count() >= want

    def drain(self, timeout_ms: Optional[float] = None) -> bool:
        """Stop admitting (router sheds with 503 FLEET_DRAINING) and
        bounded-wait for in-flight proxied queries — each of which is
        already bounded by its own queryDeadlineMs budget. True when
        the router drained dry inside the budget."""
        with self._cv:
            self._draining = True
        if timeout_ms is None:
            timeout_ms = self._drain_timeout_ms
        deadline = time.monotonic() + float(timeout_ms) / 1e3
        with self._cv:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                # short slices: notify_all in end_request wakes us;
                # the slice only bounds a lost-wakeup worst case
                self._cv.wait(min(0.1, left))
            ok = self._inflight == 0
        if ok:
            self.metrics.counter("fleet_drains").inc()
        return ok

    def shutdown(self) -> bool:
        """The SIGTERM path: drain the router, SIGTERM the workers
        (each runs its own SqlService drain), reap, tear down. True
        when everything exited cleanly inside the budgets."""
        ok = self.drain()
        with self._cv:
            already = self._stopped
            self._stopped = True
        if already:
            return ok
        clean = self._stop_workers(graceful=True)
        self._teardown_http()
        return ok and clean

    def stop(self) -> None:
        """Fast idempotent teardown (tests' finally blocks): no drain
        courtesy — SIGTERM, bounded wait, SIGKILL leftovers, reap."""
        with self._cv:
            already = self._stopped
            self._stopped = True
            self._draining = True
        if already:
            return
        self._stop_workers(graceful=False)
        self._teardown_http()

    def wait_for_shutdown(self,
                          timeout: Optional[float] = None) -> bool:
        return self._shutdown_event.wait(timeout)

    def _stop_workers(self, graceful: bool) -> bool:
        # health loop first: it must not respawn what we kill
        self._health_stop.set()
        t = self._health_thread
        if t is not None:
            t.join(timeout=10)
        procs: List[subprocess.Popen] = []
        for w in self._workers:
            p = w.mark_stopped()
            if p is None:
                continue
            if p.poll() is None:
                procs.append(p)
            else:
                p.wait()  # reap an already-dead child
        clean = True
        for p in procs:
            try:
                p.terminate()  # SIGTERM -> worker's drain path
            except OSError:
                pass
        budget = self._drain_timeout_ms / 1e3 + 5.0 if graceful else 5.0
        deadline = time.monotonic() + budget
        for p in procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                clean = False
                try:
                    p.kill()
                except OSError:
                    pass
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            else:
                clean = clean and p.returncode == 0
        return clean

    def _teardown_http(self) -> None:
        self.status_store.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10)
            self._serve_thread = None
        self._shutdown_event.set()

    # -- spawning ----------------------------------------------------------

    def _worker_conf(self, idx: int, gen: int) -> Dict:
        """Conf overrides shipped to the worker: every JSON-safe
        explicit setting from the supervisor chain (compile-cache dir,
        deadlines, admission bounds...), plus the forced worker seat:
        loopback ephemeral bind and the routing id prefix."""
        out: Dict = {}
        layers = []
        c: Optional[Conf] = self.conf
        while c is not None:
            layers.append(getattr(c, "_settings", {}))
            c = getattr(c, "_parent", None)
        for layer in reversed(layers):
            out.update(layer)
        safe = {}
        for k, v in out.items():
            try:
                json.dumps(v)
            except (TypeError, ValueError):
                continue
            safe[k] = v
        safe[HOST_KEY] = "127.0.0.1"
        safe[PORT_KEY] = 0
        safe[ID_PREFIX_KEY] = f"w{idx}g{gen}-"
        return safe

    def _spawn(self, w: _Worker) -> None:
        now = time.monotonic()
        gen = w.begin_spawn(now + self._spawn_timeout_s)
        from ..testing import faults
        try:
            # chaos seam: a rule here makes the spawn itself fail,
            # exercising the ladder and the flap breaker
            faults.fire("fleet_worker")
            env = dict(os.environ)
            env[ENV_CONF] = json.dumps(self._worker_conf(w.idx, gen))
            env[ENV_IDX] = str(w.idx)
            root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            env["PYTHONPATH"] = root + os.pathsep \
                + env.get("PYTHONPATH", "")
            proc = subprocess.Popen(
                [sys.executable, "-m", "spark_tpu.service.fleet",
                 "--worker"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
                encoding="utf-8", errors="replace")
        except Exception as e:  # noqa: BLE001 — rides the crash ladder
            w.stderr_tail.append(f"spawn failed: {type(e).__name__}: "
                                 f"{e}")
            self._account_crash(w, None, "spawn_failed",
                                {"proc": None, "port": None,
                                 "pid": None, "generation": gen})
            return
        w.attach_proc(proc)
        self.metrics.counter("fleet_spawns").inc()
        if gen > 1:
            self.metrics.counter("fleet_restarts").inc()
        threading.Thread(
            target=self._read_stdout, args=(w, proc, gen),
            daemon=True,
            name=f"{self.thread_prefix}out-w{w.idx}").start()
        threading.Thread(
            target=self._read_stderr, args=(w, proc),
            daemon=True,
            name=f"{self.thread_prefix}err-w{w.idx}").start()

    def _read_stdout(self, w: _Worker, proc: subprocess.Popen,
                     gen: int) -> None:
        """Pipe watcher: parse the one-line JSON handshake, then keep
        draining so the child never blocks on a full pipe. EOF means
        the child exited; the health loop reaps."""
        try:
            for line in proc.stdout:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                if msg.get("spark_tpu_fleet_worker") is not None:
                    w.note_handshake(gen, int(msg["port"]),
                                     int(msg["pid"]))
        except (OSError, ValueError):
            pass

    def _read_stderr(self, w: _Worker,
                     proc: subprocess.Popen) -> None:
        try:
            for line in proc.stderr:
                w.stderr_tail.append(line.rstrip("\n"))
        except (OSError, ValueError):
            pass

    # -- health loop -------------------------------------------------------

    def _health_loop(self) -> None:
        while not self._health_stop.wait(self._interval_s):
            with self._cv:
                frozen = self._draining or self._stopped
            now = time.monotonic()
            ready = 0
            for w in self._workers:
                try:
                    self._tick_worker(w, now, frozen)
                except Exception:  # noqa: BLE001 — loop must survive
                    pass
                if w.snapshot()["state"] == "ready":
                    ready += 1
            self.metrics.gauge("fleet_workers_ready").set(ready)

    def _tick_worker(self, w: _Worker, now: float,
                     frozen: bool) -> None:
        st = w.snapshot()
        state, proc = st["state"], st["proc"]
        if state in ("quarantined", "stopped"):
            return
        if state == "crashed":
            # router saw the broken connection first
            self._on_worker_death(w, None, "proxy_error")
            return
        if proc is not None and proc.poll() is not None:
            self._on_worker_death(w, proc.returncode, "exit")
            return
        if state == "starting":
            if now >= st["spawn_deadline_ts"]:
                self._on_worker_death(w, None, "spawn_timeout")
            return
        if state == "live":
            # readiness probe: warm-start replay done?
            if self._probe(st["port"], "/healthz/ready") == 200:
                w.mark_ready()
            return
        if state == "ready":
            if self._probe(st["port"], "/healthz/live") == 200:
                w.reset_ping_failures()
            elif w.note_ping_failure() >= _PING_FAILURE_LIMIT:
                self._on_worker_death(w, None, "ping_timeout")
            return
        if state == "backoff" and not frozen \
                and now >= st["next_spawn_ts"]:
            self._spawn(w)

    def _probe(self, port: Optional[int],
               path: str) -> Optional[int]:
        if not port:
            return None
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=2.0)
        try:
            conn.request("GET", path)
            r = conn.getresponse()
            r.read()
            return r.status
        except (OSError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    def _on_worker_death(self, w: _Worker, rc: Optional[int],
                         reason: str) -> None:
        info = w.take_proc()
        if info is None:
            return  # another path already accounted this death
        proc = info["proc"]
        if proc is not None:
            if rc is None:
                try:
                    proc.kill()
                except OSError:
                    pass
            try:
                proc.wait(timeout=10)
                rc = proc.returncode
            except subprocess.TimeoutExpired:
                pass
        self._account_crash(w, rc, reason, info)

    def _account_crash(self, w: _Worker, rc: Optional[int],
                       reason: str, info: Dict) -> None:
        self.metrics.counter("fleet_worker_lost").inc()
        self._dump_bundle(w, info, rc, reason)
        delay_ms = w.record_crash(time.monotonic(), self._window_s,
                                  self._max_per_window,
                                  self._backoff_ms)
        if delay_ms is None:
            # flap breaker: crash storm inside the window — quarantine
            # instead of a respawn loop against a deterministic failure
            self.metrics.counter("fleet_quarantined").inc()

    def _dump_bundle(self, w: _Worker, info: Dict, rc: Optional[int],
                     reason: str) -> None:
        """Flight bundle per death: what the worker said on stderr
        and where it was in its lifecycle — the post-mortem record a
        crash-only design owes the operator."""
        try:
            d = os.path.join(
                self._fleet_dir, "bundles",
                f"w{w.idx}-g{info['generation']}-"
                f"{next(self._bundle_seq)}-{reason}")
            os.makedirs(d, exist_ok=True)
            manifest = {"ts": time.time(), "worker": w.idx,
                        "generation": info["generation"],
                        "reason": reason, "returncode": rc,
                        "pid": info["pid"], "port": info["port"],
                        "info": w.info()}
            with open(os.path.join(d, "MANIFEST.json"), "w") as f:
                json.dump(manifest, f, indent=2, default=str)
            with open(os.path.join(d, "stderr.txt"), "w") as f:
                f.write("\n".join(w.stderr_tail))
            self.metrics.counter("fleet_bundles").inc()
        except OSError:
            pass

    # -- routing -----------------------------------------------------------

    def _route(self, session: str) -> List[int]:
        h = int(hashlib.md5(
            str(session).encode()).hexdigest()[:8], 16)
        i = bisect.bisect_left(self._ring_points, h)
        seen, order = set(), []
        for k in range(len(self._ring)):
            _, idx = self._ring[(i + k) % len(self._ring)]
            if idx not in seen:
                seen.add(idx)
                order.append(idx)
        return order

    def _pick(self, session: str) -> Tuple[Optional[_Worker],
                                           Optional[int]]:
        """First READY worker on the session's ring walk — affinity
        with deterministic re-homing when the home worker is down."""
        for idx in self._route(session):
            w = self._workers[idx]
            st = w.snapshot()
            if st["state"] == "ready" and st["port"]:
                return w, st["port"]
        return None, None

    def note_worker_lost(self, w: _Worker) -> None:
        w.mark_lost()

    # -- request accounting ------------------------------------------------

    def begin_request(self) -> str:
        with self._cv:
            if self._draining or self._stopped:
                raise FleetDraining(
                    "fleet is draining; not admitting new queries")
            self._seq += 1
            self._inflight += 1
            return f"fleet-{self._seq}"

    def end_request(self) -> None:
        with self._cv:
            self._inflight -= 1
            self._cv.notify_all()

    def proxy(self, port: int, method: str, path: str,
              body: Optional[bytes] = None,
              headers: Optional[Dict] = None) -> Tuple[int, list,
                                                       bytes]:
        conn = http.client.HTTPConnection(
            "127.0.0.1", port, timeout=self._proxy_timeout_s)
        try:
            conn.request(method, path, body=body,
                         headers=headers or {})
            r = conn.getresponse()
            data = r.read()
            return r.status, r.getheaders(), data
        except (OSError, http.client.HTTPException) as e:
            raise _WorkerLost(f"{type(e).__name__}: {e}") from e
        finally:
            conn.close()

    # -- introspection -----------------------------------------------------

    def fleet_health(self) -> Dict:
        infos = [w.info() for w in self._workers]
        ready = sum(1 for i in infos if i["state"] == "ready")
        with self._cv:
            draining, inflight = self._draining, self._inflight
        return {"status": "ok" if ready else "degraded",
                "role": "fleet", "ready": ready > 0,
                "draining": draining,
                "workers_ready": ready, "workers": infos,
                "inflight": inflight,
                "uptime_s": round(time.time() - self._started_ts, 1)}

    def stats(self) -> Dict:
        """Status-store provider (GET /status, series fleet.*)."""
        infos = [w.info() for w in self._workers]
        with self._cv:
            inflight, draining = self._inflight, self._draining
        return {"workers": len(infos),
                "ready": sum(i["state"] == "ready" for i in infos),
                "quarantined": sum(i["state"] == "quarantined"
                                   for i in infos),
                "restarts": sum(i["restarts"] for i in infos),
                "inflight": inflight, "draining": int(draining)}

    def metrics_text(self) -> str:
        """GET /metrics body: the supervisor's own fleet_* registry
        merged with every live worker's /metrics, each worker's
        samples tagged with a worker="<idx>" label so identically
        named series from N workers stay distinguishable. A worker
        dying mid-scrape is noted and skipped — a scrape must degrade,
        never fail."""
        texts: List[Tuple[Optional[str], str]] = [
            (None, prometheus_text(self.metrics.snapshot()))]
        for w in self._workers:
            st = w.snapshot()
            if st["state"] not in ("ready", "live") or not st["port"]:
                continue
            try:
                status, _, data = self.proxy(
                    st["port"], "GET", "/metrics")
            except _WorkerLost:
                self.note_worker_lost(w)
                continue
            if status == 200:
                texts.append((str(w.idx),
                              data.decode("utf-8", "replace")))
        return _merge_prometheus(texts)

    def worker_pids(self) -> List[int]:
        return [w.snapshot()["pid"] for w in self._workers
                if w.snapshot()["pid"] is not None]


# ---------------------------------------------------------------------------
# Prometheus exposition merge (supervisor + workers on one scrape)
# ---------------------------------------------------------------------------


def _label_sample(line: str, worker: str) -> str:
    """Tag one exposition sample line with worker="<idx>" (inserted
    first in an existing label set, e.g. histogram `_bucket{le=...}`
    lines)."""
    name, _, rest = line.partition(" ")
    if "{" in name:
        head, _, tail = name.partition("{")
        name = f'{head}{{worker="{worker}",{tail}'
    else:
        name = f'{name}{{worker="{worker}"}}'
    return f"{name} {rest}"


def _merge_prometheus(texts: List[Tuple[Optional[str], str]]) -> str:
    """Merge several text expositions into one valid 0.0.4 document:
    each (worker_label, text) source's samples get a worker label
    (None = emit unlabeled, the supervisor's own series), families
    sharing a name coalesce under a single # TYPE line, and every
    family's samples stay contiguous — both format requirements when
    N workers export the same metric names."""
    families: Dict[str, Dict] = {}
    order: List[str] = []
    for worker, text in texts:
        fam = None
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                _, _, name, typ = line.split(None, 3)
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = {"type": typ,
                                            "samples": []}
                    order.append(name)
            elif not line or line.startswith("#"):
                continue
            elif fam is not None:
                fam["samples"].append(
                    line if worker is None
                    else _label_sample(line, worker))
    out: List[str] = []
    for name in order:
        fam = families[name]
        out.append(f"# TYPE {name} {fam['type']}")
        out.extend(fam["samples"])
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Router HTTP front end
# ---------------------------------------------------------------------------


def _make_router(sup: FleetSupervisor):
    class Router(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet: metrics cover it
            pass

        def _send_json(self, status: int, payload: Dict) -> None:
            body = json.dumps(payload, default=str).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _relay(self, status: int, hdrs: list, data: bytes,
                   extra: Dict) -> None:
            """Forward a worker response, keeping only end-to-end
            headers (length is recomputed; hop-by-hop dropped)."""
            self.send_response(status)
            keep = {"content-type", "x-query-id"}
            for k, v in hdrs:
                if k.lower() in keep:
                    self.send_header(k, v)
            for k, v in extra.items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        # -- query-id routing ---------------------------------------------

        def _route_query_path(self, method: str) -> None:
            path = self.path.split("?", 1)[0]
            rest = path[len("/queries/"):]
            qid = rest.split("/", 1)[0]
            m = _QID_RE.match(qid)
            if not m:
                self._send_json(404, {
                    "error": "NOT_FOUND",
                    "message": f"unknown query id {qid!r} (fleet ids "
                               f"embed their worker: q-w<i>g<n>-...)",
                    "query_id": qid})
                return
            idx, gen = int(m.group(1)), int(m.group(2))
            if idx >= len(sup._workers):
                self._send_json(404, {
                    "error": "NOT_FOUND",
                    "message": f"no worker {idx} in this fleet",
                    "query_id": qid})
                return
            w = sup._workers[idx]
            st = w.snapshot()
            if (gen != st["generation"]
                    or st["state"] not in ("ready", "live")
                    or not st["port"]):
                # crash-only: in-memory records died with the worker —
                # say so structurally instead of 404-ing
                self._send_json(503, {
                    "error": "WORKER_LOST",
                    "message": f"query {qid} belonged to worker {idx} "
                               f"generation {gen}, which is gone "
                               f"(records are in-memory and die with "
                               f"their worker)",
                    "query_id": qid, "worker": idx})
                return
            try:
                status, hdrs, data = sup.proxy(
                    st["port"], method, self.path)
            except _WorkerLost:
                sup.note_worker_lost(w)
                self._send_json(503, {
                    "error": "WORKER_LOST",
                    "message": f"worker {idx} died answering for "
                               f"{qid}",
                    "query_id": qid, "worker": idx})
                return
            self._relay(status, hdrs, data,
                        {"X-Fleet-Worker": str(idx)})

        # -- verbs ---------------------------------------------------------

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            from urllib.parse import parse_qs
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                h = sup.fleet_health()
                self._send_json(200 if h["ready"] else 503, h)
            elif path == "/healthz/live":
                self._send_json(200, {"live": True,
                                      "ready": sup.ready_count() > 0})
            elif path == "/healthz/ready":
                if sup.ready_count() > 0:
                    self._send_json(200, {"ready": True})
                else:
                    self._send_json(503, {
                        "error": "NOT_READY",
                        "message": "no ready worker",
                        "ready": False})
            elif path == "/fleet":
                self._send_json(200, sup.fleet_health())
            elif path == "/metrics":
                body = sup.metrics_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/status":
                self._send_json(200, sup.status_store.snapshot())
            elif path == "/status/timeseries":
                qs = parse_qs(query)
                names = None
                if qs.get("series"):
                    names = [s for s in qs["series"][0].split(",")
                             if s]
                try:
                    limit = (int(qs["limit"][0])
                             if qs.get("limit") else None)
                except (TypeError, ValueError) as e:
                    self._send_json(400, {"error": "BAD_REQUEST",
                                          "message": str(e)[:200]})
                    return
                self._send_json(200, sup.status_store.timeseries(
                    names=names, limit=limit))
            elif path in ("/queries", "/queries/"):
                # fan-out merge across ready workers
                out: Dict = {"queries": [], "streams": [],
                             "total": 0, "workers": {}}
                for w in sup._workers:
                    st = w.snapshot()
                    if st["state"] != "ready" or not st["port"]:
                        continue
                    try:
                        status, _, data = sup.proxy(
                            st["port"], "GET", self.path)
                    except _WorkerLost:
                        sup.note_worker_lost(w)
                        continue
                    if status != 200:
                        continue
                    try:
                        d = json.loads(data)
                    except ValueError:
                        continue
                    out["queries"].extend(d.get("queries") or [])
                    out["streams"].extend(d.get("streams") or [])
                    out["total"] += int(d.get("total") or 0)
                    out["workers"][str(w.idx)] = int(
                        d.get("total") or 0)
                self._send_json(200, out)
            elif path.startswith("/queries/"):
                self._route_query_path("GET")
            else:
                self._send_json(404, {"error": "NOT_FOUND",
                                      "message": path})

        def do_DELETE(self):  # noqa: N802
            path = self.path.split("?", 1)[0]
            if path.startswith("/queries/"):
                self._route_query_path("DELETE")
            else:
                self._send_json(404, {"error": "NOT_FOUND",
                                      "message": path})

        def do_POST(self):  # noqa: N802
            path = self.path.split("?", 1)[0]
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n) if n else b"{}"
            if path != "/sql":
                self._send_json(404, {"error": "NOT_FOUND",
                                      "message": path})
                return
            try:
                req = json.loads(raw or b"{}")
                if not isinstance(req, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, TypeError) as e:
                self._send_json(400, {"error": "BAD_REQUEST",
                                      "message": str(e)[:200]})
                return
            session = str(req.get("session") or "default")
            sql = req.get("sql") or ""
            try:
                fid = sup.begin_request()
            except AdmissionError as e:
                sup.metrics.counter("fleet_requests_shed").inc()
                self._send_json(e.http_status, e.to_dict())
                return
            try:
                self._post_sql(session, sql, raw, fid)
            finally:
                sup.end_request()

        def _post_sql(self, session: str, sql: str, raw: bytes,
                      fid: str) -> None:
            headers = {"Content-Type":
                       self.headers.get("Content-Type")
                       or "application/json"}
            attempt = 0
            w, port = sup._pick(session)
            while True:
                if w is None:
                    sup.metrics.counter("fleet_requests_shed").inc()
                    self._send_json(503, FleetUnavailable(
                        "no ready worker (crashed, warm-starting or "
                        "quarantined)",
                        workers_ready=sup.ready_count()).to_dict())
                    return
                try:
                    status, hdrs, data = sup.proxy(
                        port, "POST", self.path, raw, headers)
                except _WorkerLost:
                    sup.note_worker_lost(w)
                    lost_idx = w.idx
                    if (attempt == 0 and sup._failover_reads
                            and _is_read(sql)):
                        # idempotent read: retry ONCE on the re-homed
                        # worker (shared compile cache +
                        # deterministic engine => byte parity)
                        attempt = 1
                        sup.metrics.counter("fleet_failovers").inc()
                        w, port = sup._pick(session)
                        continue
                    self._send_json(503, {
                        "error": "WORKER_LOST",
                        "message": f"worker {lost_idx} died "
                                   f"mid-request; statement is not a "
                                   f"retryable read"
                        if attempt == 0 else
                        f"worker {lost_idx} died during failover "
                        f"retry",
                        "query_id": fid, "worker": lost_idx})
                    return
                sup.metrics.counter("fleet_requests_proxied").inc()
                extra = {"X-Fleet-Worker": str(w.idx),
                         "X-Fleet-Request": fid}
                if attempt:
                    extra["X-Fleet-Failover"] = "1"
                self._relay(status, hdrs, data, extra)
                return

    return Router


# ---------------------------------------------------------------------------
# Entry points: worker child and supervisor CLI
# ---------------------------------------------------------------------------


def _resolve_init(spec: str):
    """'module:function' -> callable, the init_session hook a worker
    applies to every pooled session (register tables, UDFs...)."""
    import importlib
    mod, _, fn = spec.partition(":")
    m = importlib.import_module(mod)
    return getattr(m, fn) if fn else None


def _worker_main() -> int:
    """Child entry (`python -m spark_tpu.service.fleet --worker`):
    build the conf from SPARK_TPU_FLEET_CONF, serve on an ephemeral
    loopback port, print the one-line JSON handshake, park until
    SIGTERM drains us."""
    idx = int(os.environ.get(ENV_IDX, "0"))
    conf = Conf()
    for k, v in json.loads(os.environ.get(ENV_CONF, "{}")).items():
        conf.set(k, v)
    init = None
    spec = str(conf.get(INIT_KEY) or "")
    if spec:
        # resolve BEFORE the heavy engine import: a bad init spec is a
        # deterministic boot failure and should crash cheaply (the
        # supervisor's flap breaker quarantines it after K attempts)
        init = _resolve_init(spec)
    from .server import SqlService
    svc = SqlService(conf, init_session=init)
    svc.install_signal_handlers()  # SIGTERM/SIGINT -> drain -> stop
    svc.start()
    print(json.dumps({"spark_tpu_fleet_worker": idx,
                      "port": svc.port, "pid": os.getpid()}),
          flush=True)
    # wait_for_shutdown only unblocks after a signal-driven
    # drain+stop has fully completed, so stop() here is a true
    # idempotent no-op (it also covers a direct stop() call)
    svc.wait_for_shutdown()
    svc.stop()
    return 0


def _supervisor_main(argv: Optional[List[str]] = None) -> int:
    """CLI entry (scripts/fleet.py): parse flags, serve, park until
    SIGTERM/SIGINT drains the fleet."""
    import argparse
    import signal
    p = argparse.ArgumentParser(
        prog="spark-tpu-fleet",
        description="Crash-only SqlService fleet: supervisor + "
                    "router over N worker subprocesses.")
    p.add_argument("--workers", type=int, default=None,
                   help="worker subprocess count "
                        f"(default: conf {WORKERS_KEY})")
    p.add_argument("--host", default=None,
                   help="router bind host")
    p.add_argument("--port", type=int, default=None,
                   help="router bind port (0 = ephemeral)")
    p.add_argument("--conf", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="conf override, repeatable (values parse as "
                        "JSON when possible)")
    p.add_argument("--init", default=None, metavar="MODULE:FUNC",
                   help="session initializer run in every worker")
    args = p.parse_args(argv)
    conf = Conf()
    for kv in args.conf:
        k, _, v = kv.partition("=")
        try:
            parsed = json.loads(v)
        except ValueError:
            parsed = v
        conf.set(k, parsed)
    if args.workers is not None:
        conf.set(WORKERS_KEY, args.workers)
    if args.host is not None:
        conf.set(HOST_KEY, args.host)
    if args.port is not None:
        conf.set(PORT_KEY, args.port)
    if args.init:
        conf.set(INIT_KEY, args.init)
    sup = FleetSupervisor(conf).start()

    def _handler(signum, frame):
        threading.Thread(target=sup.shutdown, daemon=True,
                         name="fleet-shutdown").start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _handler)
    print(json.dumps({"spark_tpu_fleet": {
        "port": sup.port, "pid": os.getpid(),
        "workers": len(sup._workers)}}), flush=True)
    sup.wait_for_shutdown()
    return 0


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        sys.exit(_worker_main())
    sys.exit(_supervisor_main(
        [a for a in sys.argv[1:] if a != "--worker"]))

"""Long-lived concurrent SQL service (the hive-thriftserver analog).

The engine below this package is single-query: one session, one
driver thread, per-query budgets. This package is the serving layer
that turns it into a long-lived multi-session server
(`HiveThriftServer2.scala:44` seat):

- ``arbiter``: the cross-query device resource arbiter — ONE shared
  HBM lease pool (replacing per-query `deviceBudget` reads), the
  sessions-shared compiled-stage cache, and the size-bounded
  plan-fingerprint result cache (`UnifiedMemoryManager.scala:49` +
  `CacheManager.scala` seats);
- ``admission``: bounded-queue admission control
  (`service.{maxConcurrent,queueDepth,queueTimeoutMs}`) with
  structured rejection/timeout errors;
- ``pool``: the session pool — per-session conf overlays on the
  config registry, one shared metrics registry, serialized per-session
  execution;
- ``server``: the HTTP JSON endpoint (stdlib http.server):
  `POST /sql`, `GET /queries/<id>`, `GET /metrics` (Prometheus text),
  `GET /healthz`.

`arbiter` is imported eagerly (the session constructor uses its
ResultCache); the HTTP machinery loads lazily.
"""

from . import arbiter  # noqa: F401

__all__ = ["arbiter", "SqlService", "SessionPool", "AdmissionController",
           "AdmissionRejected", "AdmissionTimeout"]


def __getattr__(name):
    if name == "SqlService":
        from .server import SqlService
        return SqlService
    if name == "SessionPool":
        from .pool import SessionPool
        return SessionPool
    if name in ("AdmissionController", "AdmissionRejected",
                "AdmissionTimeout"):
        from . import admission
        return getattr(admission, name)
    raise AttributeError(name)

"""Bounded in-memory per-query detail store behind the history API.

The SQLAppStatusStore seat of the reference's UI/HistoryServer stack:
the service's status registry (`SqlService._records`) holds the light
lifecycle record every client polls, while THIS store holds the heavy
post-execution detail the timeline/plan endpoints serve — phase spans,
per-stage XLA cost/HBM accounting, per-shard flight-recorder records,
the runtime-annotated plan tree — fed by the pooled sessions' status
listener at `on_query_end` (the same bus event the event-log writer
consumes, so a running service is debuggable over HTTP without
scraping JSONL files).

Entries are JSON-ready dicts keyed by the SERVICE query id; the store
is bounded (`spark_tpu.service.historySize`) and evicts oldest-first —
detail records are much heavier than status records, hence the
separate, smaller bound.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional

HISTORY_SIZE_KEY = "spark_tpu.service.historySize"


class QueryHistoryStore:
    def __init__(self, max_entries: int = 128):
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Dict]" = OrderedDict()

    def put(self, query_id: str, detail: Dict) -> None:
        with self._lock:
            self._entries[query_id] = detail
            self._entries.move_to_end(query_id)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def get(self, query_id: str) -> Optional[Dict]:
        with self._lock:
            return self._entries.get(query_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def detail_from_event(event) -> Dict:
    """Shape one QueryEndEvent into the stored detail dict (everything
    the timeline/plan endpoints serve, already JSON-serializable — the
    event record is the same dict the event-log line is written from)."""
    ev = event.event or {}
    return {
        "engine_query_id": event.query_id,
        "status": event.status,
        "ts": ev.get("ts"),
        "plan": ev.get("plan"),
        "plan_tree": ev.get("plan_tree"),
        "phase_times_s": ev.get("phase_times_s"),
        "spans": ev.get("spans") or [],
        "stages": ev.get("stages") or [],
        "shards": ev.get("shards") or [],
        "metrics": ev.get("metrics") or {},
        "predictions": ev.get("predictions") or [],
        "reorder": ev.get("reorder"),
        "analysis_findings": ev.get("analysis_findings") or [],
        "fault_summary": ev.get("fault_summary"),
        "error": ev.get("error"),
    }

"""Cross-query device resource arbiter + shared caches.

The `UnifiedMemoryManager.scala:49` analog for a process serving many
concurrent queries: ONE device (HBM) byte pool that every query leases
scan residency from, instead of each query consulting its own private
`spark_tpu.sql.memory.deviceBudget`. The pool is unified with the
device table cache (io/device_cache.py) the way the reference unifies
execution and storage memory: lease pressure first evicts cached
tables (storage), then denies the lease — and a denied lease routes
the query down the out-of-core spill/streaming paths it already has
(execution/external.py, streaming_agg partial spill), never a crash.
The PR-2 OOM ladder composes unchanged: its rung-2 overlay pins an
explicit 1-byte deviceBudget, which takes precedence over the arbiter
(a forced re-route must stay forced).

Also arbiter-owned, because they are process resources the way HBM is:

- the compiled-stage cache shared across every pooled session (stage
  keys are plan-describe + compile-relevant conf, bucket-aligned since
  PR 4, so cross-session hit rates are high — the Janino-cache seat);
- the plan-fingerprint result cache (`ResultCache`), promoting the
  per-session `_data_cache` dict behind `QueryExecution._apply_cache`
  to a size-bounded, thread-safe LRU (the CacheManager /
  InMemoryRelation seat).

Installation is process-level (`install_arbiter` / `get_arbiter`),
matching device_cache.CACHE: HBM is a process resource. The SQL
service installs one at startup from `spark_tpu.service.hbmBudget`;
without one, every legacy single-session code path is byte-identical.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from contextvars import ContextVar
from typing import Dict, Optional

DEVICE_BUDGET_KEY = "spark_tpu.sql.memory.deviceBudget"
HBM_BUDGET_KEY = "spark_tpu.service.hbmBudget"
RESULT_CACHE_BYTES_KEY = "spark_tpu.service.resultCacheBytes"
SESSION_HBM_SHARE_KEY = "spark_tpu.service.session.hbmShare"


class _Owner:
    """Identity of one query execution's leases (created per
    execute_batch / external collect via `enter_query`). `group` is
    the session identity (the app_id prefix of the executor's
    "app:qN" label) — the unit the per-session hbmShare quota
    aggregates leases over."""

    __slots__ = ("label", "group")

    def __init__(self, label: str = ""):
        self.label = label
        self.group = label.rsplit(":q", 1)[0] if ":q" in label else label


#: the owner of the query execution running in the current context;
#: set by the executor, read by the deep streaming/external gates
_OWNER: ContextVar[Optional[_Owner]] = ContextVar(
    "spark_tpu_arbiter_owner", default=None)


class DeviceResourceArbiter:
    """One shared HBM byte pool, leased per (query, scan).

    `try_acquire` is idempotent per (owner, key): the same scan is
    gate-checked from several sites along one execution (external
    collect, streaming splice, resident-preference), and they must all
    see one stable verdict. Denials are memoized per owner for the
    same reason — a lease freed mid-execution must not flip a query
    that already committed to the spill path back to resident.
    """

    def __init__(self, total_bytes: int, metrics=None,
                 result_cache_bytes: int = 0):
        self.total = int(total_bytes)
        self.metrics = metrics
        self._cv = threading.Condition()
        self._leases: Dict[_Owner, Dict[object, int]] = {}
        self._denied: Dict[_Owner, set] = {}
        #: device-cache keys each owner was admitted against as
        #: STORAGE: pinned in the cache so lease-pressure eviction
        #: can't reclaim bytes a running query still references
        self._pins: Dict[_Owner, set] = {}
        #: sessions-shared compiled-stage cache (the Janino-cache seat;
        #: pooled sessions all point their _stage_cache here).
        #: Deliberately unlocked (guarded-by waiver): dict get/set are
        #: GIL-atomic and keys are deterministic content hashes, so
        #: the worst concurrent-fill race is a duplicate compile whose
        #: last write wins with an equivalent value.
        self.stage_cache: Dict[str, object] = {}
        #: arbiter-owned plan-fingerprint result cache (pooled sessions
        #: all point their _data_cache here)
        self.result_cache = ResultCache(max_bytes=result_cache_bytes,
                                        metrics=metrics)

    # -- accounting ---------------------------------------------------------

    @property
    def leased_bytes(self) -> int:
        with self._cv:
            return self._leased_locked()

    def _leased_locked(self) -> int:
        return sum(sum(d.values()) for d in self._leases.values())

    def _storage_bytes(self) -> int:
        from ..io.device_cache import CACHE
        return CACHE.nbytes

    def headroom(self) -> int:
        with self._cv:
            return self.total - self._leased_locked() - self._storage_bytes()

    # -- leasing ------------------------------------------------------------

    def try_acquire(self, owner: Optional[_Owner], key, nbytes: int,
                    wait_ms: float = 0.0, share: float = 0.0) -> bool:
        """Lease `nbytes` of residency for (owner, key). Storage (the
        device table cache) is evicted LRU-first under pressure — the
        UnifiedMemoryManager storage-eviction move — then the request
        waits up to `wait_ms` for other queries to release, then is
        denied (the caller takes the out-of-core path).

        `share` (spark_tpu.service.session.hbmShare) caps ONE owner
        group's (= session's) total leases at share * pool: a lease
        that would push the session past its share is denied
        immediately (`session_quota_rejections`) — waiting could only
        succeed by the session releasing its own leases, which happens
        at query end, after this query already committed to a path.

        Lease waits are cancellable: with a lifecycle token installed
        the cv wait runs in deadline-capped slices and a
        cancelled/deadlined waiter raises the structured error out of
        the gate (the query is stopping — there is no path to route)."""
        from ..execution import lifecycle
        from ..io.device_cache import CACHE
        if owner is None:
            # no query scope (direct engine use with an arbiter
            # installed): grant against headroom without tracking —
            # there is no release point to hold a lease open for
            return nbytes <= self.headroom()
        deadline = time.monotonic() + wait_ms / 1e3
        group_cap = int(share * self.total) if share > 0 else 0
        with self._cv:
            held = self._leases.get(owner, {})
            if key in held:
                return True
            if key in self._denied.get(owner, ()):
                return False
            while True:
                if group_cap > 0:
                    group_leased = sum(
                        sum(d.values())
                        for o, d in self._leases.items()
                        if o.group == owner.group)
                    if group_leased + nbytes > group_cap:
                        self._denied.setdefault(owner, set()).add(key)
                        self._count("arbiter_lease_denied")
                        self._count("session_quota_rejections")
                        return False
                free = (self.total - self._leased_locked()
                        - self._storage_bytes())
                if nbytes <= free:
                    self._leases.setdefault(owner, {})[key] = int(nbytes)
                    self._count("arbiter_lease_granted")
                    self._gauges()
                    return True
                # queued eviction: shrink the storage pool before
                # denying execution memory
                freed = CACHE.evict_bytes(nbytes - free)
                if freed > 0:
                    self._count("arbiter_storage_evicted_bytes", freed)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._denied.setdefault(owner, set()).add(key)
                    self._count("arbiter_lease_denied")
                    return False
                self._cv.wait(lifecycle.wait_slice(remaining))
                lifecycle.checkpoint("lease_wait")

    def pin_storage(self, owner: Optional[_Owner], key) -> None:
        """Record that `owner` is executing against the CACHED copy of
        `key`: pin it so another query's lease pressure can't evict
        bytes this query still references (evicting them frees
        nothing — the live reference keeps the HBM held — while the
        accounting would credit them as free)."""
        from ..io.device_cache import CACHE
        if owner is None or key is None:
            return
        with self._cv:
            pins = self._pins.setdefault(owner, set())
            if key in pins:
                return
            if CACHE.pin(key):
                pins.add(key)

    def convert_lease_to_pin(self, owner: Optional[_Owner], key) -> None:
        """The owner's leased scan just landed in the device cache:
        its bytes now count as storage (headroom subtracts
        CACHE.nbytes), so keeping the lease would double-count — drop
        it and pin the cache entry for the rest of the execution."""
        from ..io.device_cache import CACHE
        if owner is None:
            return
        with self._cv:
            held = self._leases.get(owner)
            if not held or key not in held:
                return
            pins = self._pins.setdefault(owner, set())
            if key not in pins and not CACHE.pin(key):
                # the put was rejected (entry never landed in storage):
                # the batch is still live on device but NOT in
                # CACHE.nbytes, so the lease stays — dropping it would
                # credit phantom headroom
                return
            pins.add(key)
            del held[key]
            self._gauges()
            self._cv.notify_all()

    def release(self, owner: Optional[_Owner]) -> None:
        """Drop every lease, pin and denial memo the owner holds —
        called when its query execution ends or the OOM ladder
        re-plans."""
        from ..io.device_cache import CACHE
        if owner is None:
            return
        with self._cv:
            self._leases.pop(owner, None)
            self._denied.pop(owner, None)
            for key in self._pins.pop(owner, ()):
                CACHE.unpin(key)
            self._gauges()
            self._cv.notify_all()

    # -- observability ------------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc(n)

    def _gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("arbiter_leased_bytes").set(
                self._leased_locked())
            self.metrics.gauge("arbiter_total_bytes").set(self.total)

    def stats(self) -> Dict[str, int]:
        with self._cv:
            return {"total_bytes": self.total,
                    "leased_bytes": self._leased_locked(),
                    "owners": len(self._leases),
                    "headroom_bytes": (self.total - self._leased_locked()
                                       - self._storage_bytes())}


# ---------------------------------------------------------------------------
# Process-level installation (device_cache.CACHE discipline: HBM is a
# process resource)
# ---------------------------------------------------------------------------

_ARBITER: Optional[DeviceResourceArbiter] = None


def install_arbiter(arbiter: Optional[DeviceResourceArbiter]) -> None:
    global _ARBITER
    _ARBITER = arbiter


def get_arbiter() -> Optional[DeviceResourceArbiter]:
    return _ARBITER


# ---------------------------------------------------------------------------
# Query-scope plumbing (executor-facing)
# ---------------------------------------------------------------------------


#: token for a scope opened inside an enclosing scope: the outer owner
#: keeps the leases, so nested exit is a no-op. Without this, the
#: external-collect gate's exit would release the residency lease it
#: just granted BEFORE the resident execution it authorized runs —
#: and concurrent queries would each see full headroom.
_NESTED = ("nested-arbiter-scope",)


def enter_query(label: str = "") -> Optional[tuple]:
    """Open a lease scope for the query execution starting in this
    context. Returns an opaque token for `exit_query`, or None when no
    arbiter is installed (zero overhead on the legacy path). Re-entrant:
    a scope opened under an existing scope shares the outer owner, so
    leases live until the OUTERMOST exit (collect() opens that scope —
    residency granted at the external-collect gate must stay accounted
    while the resident execution runs)."""
    if _ARBITER is None:
        return None
    if _OWNER.get() is not None:
        return _NESTED
    owner = _Owner(label)
    return owner, _OWNER.set(owner)


def exit_query(token: Optional[tuple]) -> None:
    """Close a lease scope: release every lease it acquired (no-op for
    nested scopes — the outermost exit releases)."""
    if token is None or token is _NESTED:
        return
    owner, ctx_token = token
    _OWNER.reset(ctx_token)
    arb = _ARBITER
    if arb is not None:
        arb.release(owner)


def release_current() -> None:
    """Release the running query's leases without closing the scope —
    the OOM ladder calls this before a degraded re-plan so the retry's
    admit decisions start from a clean slate."""
    arb = _ARBITER
    owner = _OWNER.get()
    if arb is not None and owner is not None:
        arb.release(owner)


# ---------------------------------------------------------------------------
# Budget gates (the former per-query deviceBudget read sites call these)
# ---------------------------------------------------------------------------


def admit_scan_resident(conf, leaf) -> bool:
    """May this scan's working set stay device-resident? The ONE
    residency verdict consulted by every out-of-core gate (external
    collect, streaming partial spill, resident-preference):

    - explicit per-query deviceBudget (a test conf or the OOM ladder's
      rung-2 overlay) keeps legacy semantics: est <= budget, unknown
      est streams;
    - otherwise, with an arbiter installed, the query leases the
      estimated footprint from the shared pool (False = denied =
      spill/stream re-plan);
    - otherwise legacy: no budget configured = always resident.
    """
    from ..io.device_cache import (estimated_scan_bytes, is_cached,
                                   scan_cache_key)
    budget = int(conf.get(DEVICE_BUDGET_KEY))
    arb = _ARBITER
    if budget > 0:
        est = estimated_scan_bytes(leaf)
        return est is not None and est <= budget
    if arb is None:
        return True
    if is_cached(leaf):
        # already device-resident: its bytes count against the pool as
        # STORAGE (headroom subtracts CACHE.nbytes), so taking a lease
        # too would double-count — and evict the very table the query
        # is about to reuse. Pin it instead: lease pressure must not
        # evict bytes this execution still references.
        arb.pin_storage(_OWNER.get(), scan_cache_key(leaf))
        return True
    est = estimated_scan_bytes(leaf)
    if est is None:
        return False  # unsizeable lease: stream it
    key = scan_cache_key(leaf) or ("scan", id(leaf))
    # per-session share quota: one session's leases are capped at
    # hbmShare * pool — over-share scans stream instead of pinning HBM
    share = float(conf.get(SESSION_HBM_SHARE_KEY))
    return arb.try_acquire(_OWNER.get(), key, est, share=share)


def note_scan_cached(key) -> None:
    """Hook from io/device_cache.load_scan: the scan keyed `key` just
    landed in the device cache. If the running query leased residency
    for it, convert the lease to a storage pin (no double-count)."""
    arb = _ARBITER
    if arb is not None:
        arb.convert_lease_to_pin(_OWNER.get(), key)


def out_of_core_active(conf) -> bool:
    """Whether ANY out-of-core budget discipline is in force — the
    cheap early gate executor._try_external_collect uses before doing
    plan-shape work."""
    return int(conf.get(DEVICE_BUDGET_KEY)) > 0 or _ARBITER is not None


# ---------------------------------------------------------------------------
# Plan-fingerprint result cache (the CacheManager seat, promoted from
# the per-session `_data_cache` dict)
# ---------------------------------------------------------------------------


class ResultCache:
    """Size-bounded, thread-safe LRU of materialized Arrow tables keyed
    by plan fingerprint. Drop-in for the former per-session dict (the
    subset of the mapping protocol `_apply_cache` and session cache
    bookkeeping use). `max_bytes=0` disables bounding."""

    def __init__(self, max_bytes: int = 0, metrics=None):
        self.max_bytes = int(max_bytes)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self._bytes = 0

    def get(self, fp, default=None):
        with self._lock:
            entry = self._entries.get(fp)
            if entry is None:
                return default
            self._entries.move_to_end(fp)
            return entry[0]

    def __contains__(self, fp) -> bool:
        with self._lock:
            return fp in self._entries

    def __setitem__(self, fp, table) -> None:
        nbytes = int(getattr(table, "nbytes", 0))
        with self._lock:
            old = self._entries.pop(fp, None)
            if old is not None:
                self._bytes -= old[1]
            if self.max_bytes > 0 and nbytes > self.max_bytes:
                self._count("result_cache_rejected")
                return  # larger than the whole bound: don't thrash
            self._entries[fp] = (table, nbytes)
            self._bytes += nbytes
            while self.max_bytes > 0 and self._bytes > self.max_bytes \
                    and len(self._entries) > 1:
                _, (_, evicted) = self._entries.popitem(last=False)
                self._bytes -= evicted
                self._count("result_cache_evictions")

    def pop(self, fp, default=None):
        with self._lock:
            entry = self._entries.pop(fp, None)
            if entry is None:
                return default
            self._bytes -= entry[1]
            return entry[0]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc()

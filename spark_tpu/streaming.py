"""Structured streaming: the micro-batch execution loop.

A scaled-to-this-engine implementation of the reference's structured
streaming core (`execution/streaming/MicroBatchExecution.scala:39`,
`StreamExecution.scala:69`): a host-driven loop that

1. polls sources for their latest offsets and WRITES THE PLANNED RANGE
   to the offset log BEFORE executing (`offsetLog:219`, an
   `HDFSMetadataLog` analog — JSON files named by batch id);
2. runs the query over exactly the logged range — stateless plans
   execute the batch slice through the normal engine; streaming
   aggregations fold the slice into versioned accumulator tables (the
   `StateStore:101` role is played by the direct-aggregate tables that
   already power batch streaming);
3. commits to the commit log (`commitLog:226`) and emits to the sink.

Exactly-once = offset log ∧ commit log ∧ versioned state: on restart,
a planned-but-uncommitted batch re-runs over the SAME logged range
against the last committed state version, so replays are idempotent.

The TPU angle: each micro-batch is one jitted SPMD program over a
statically-shaped batch slice; state lives in HBM as accumulator tables
between triggers (no RocksDB tier — state is bounded by the aggregate's
padded domain, and the host checkpoint serializes it as numpy).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa

from . import functions as F  # noqa: F401  (user convenience re-export)
from .columnar import Batch
from .plan import logical as L


class MemoryStream:
    """An appendable in-memory source (the reference's `MemoryStream` —
    the deterministic test source behind StreamTest.scala:342)."""

    def __init__(self, session, schema_df: pd.DataFrame):
        self.session = session
        self._table = pa.Table.from_pandas(schema_df.iloc[0:0],
                                           preserve_index=False)
        self._batches: List[pa.Table] = []

    def add_data(self, df: pd.DataFrame) -> None:
        self._batches.append(pa.Table.from_pandas(df, preserve_index=False))

    addData = add_data

    def latest_offset(self) -> int:
        return len(self._batches)

    def slice(self, start: int, end: int) -> pa.Table:
        tables = self._batches[start:end]
        if not tables:
            return self._table
        return pa.concat_tables(tables)

    def to_df(self):
        """A DataFrame over a placeholder scan; the streaming loop swaps
        the placeholder for each micro-batch's slice (the reference's
        logical-plan rewrite in `MicroBatchExecution.runBatch:525`)."""
        from .dataframe import DataFrame
        return DataFrame(self.session, _StreamSource(self))


class _StreamSource(L.LeafPlan):
    """Logical placeholder for a streaming source."""

    def __init__(self, stream: MemoryStream):
        self.stream = stream
        self.children = ()

    def schema(self):
        from .io.sources import ArrowTableSource
        return ArrowTableSource("__stream__", self.stream._table).schema()

    def simple_string(self):
        return "StreamSource(memory)"


class _MetadataLog:
    """Numbered JSON files with atomic rename — the
    `HDFSMetadataLog`/`CheckpointFileManager` contract in miniature."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def latest(self):
        ids = [int(f) for f in os.listdir(self.path) if f.isdigit()]
        if not ids:
            return None, None
        i = max(ids)
        with open(os.path.join(self.path, str(i))) as f:
            return i, json.load(f)

    def add(self, batch_id: int, payload: dict) -> None:
        final = os.path.join(self.path, str(batch_id))
        tmp = final + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, final)


class StreamingQuery:
    """One micro-batch query (reference: StreamExecution). Trigger is
    manual (`process_available()`) — the deterministic single-step mode
    StreamTest uses; a wall-clock trigger is a loop around it."""

    def __init__(self, session, plan: L.LogicalPlan, stream: MemoryStream,
                 checkpoint_dir: str, output_mode: str = "complete"):
        if output_mode not in ("complete", "append"):
            raise ValueError(f"unsupported outputMode {output_mode!r}")
        self.session = session
        self.plan = plan
        self.stream = stream
        self.output_mode = output_mode
        self.offset_log = _MetadataLog(os.path.join(checkpoint_dir,
                                                    "offsets"))
        self.commit_log = _MetadataLog(os.path.join(checkpoint_dir,
                                                    "commits"))
        self._state_dir = os.path.join(checkpoint_dir, "state")
        os.makedirs(self._state_dir, exist_ok=True)
        self._agg = self._find_aggregate(plan)
        self._watermark = self._find_watermark(plan)
        self._event_time = (self._agg is not None
                            and self._watermark is not None)
        if self._agg is not None and output_mode == "append" \
                and not self._event_time:
            # the reference rejects append-without-watermark for
            # aggregations at analysis time; silently re-emitting every
            # group each trigger would duplicate sink rows
            raise ValueError(
                "outputMode='append' on a streaming aggregation needs "
                "a watermark (with_watermark) so closed windows can be "
                "emitted exactly once; use 'complete' otherwise")
        self._results: List[pd.DataFrame] = []
        self._tables = None      # carried aggregate state (device)
        self._prep = None
        # event-time path: host state table + watermark (us)
        self._evstate: Optional[pd.DataFrame] = None
        self._wm: int = -(1 << 62)
        self._recover()

    # -- plan shape ---------------------------------------------------------

    @staticmethod
    def _find_aggregate(plan: L.LogicalPlan) -> Optional[L.Aggregate]:
        """The single streaming aggregate, if any (stateless otherwise).
        Nested/multiple aggregates are out of scope, as in the
        reference's UnsupportedOperationChecker."""
        aggs: List[L.Aggregate] = []

        def walk(n):
            if isinstance(n, L.Aggregate):
                aggs.append(n)
            for c in n.children:
                walk(c)

        walk(plan)
        if len(aggs) > 1:
            raise ValueError("multiple streaming aggregates unsupported")
        return aggs[0] if aggs else None

    @staticmethod
    def _find_watermark(plan: L.LogicalPlan):
        """(col_name, delay_us) of the single Watermark node, if any."""
        found = []

        def walk(n):
            if isinstance(n, L.Watermark):
                found.append((n.col_name, n.delay_us))
            for c in n.children:
                walk(c)

        walk(plan)
        return found[0] if found else None

    # -- recovery -----------------------------------------------------------

    def _recover(self) -> None:
        """Restart semantics: resume state at the last COMMITTED batch;
        a planned-but-uncommitted offset entry will re-run over its
        logged range (idempotent because state is versioned)."""
        last_commit, payload = self.commit_log.latest()
        self._committed_batch = -1 if last_commit is None else last_commit
        if self._agg is not None and last_commit is not None:
            if self._event_time:
                self._wm = int((payload or {}).get("wm", self._wm))
                p = self._event_state_path(last_commit)
                if os.path.exists(p):
                    self._evstate = pd.read_parquet(p)
            else:
                self._load_state(last_commit)

    def _state_path(self, batch_id: int) -> str:
        return os.path.join(self._state_dir, f"v{batch_id}.npz")

    def _save_state(self, batch_id: int, tables) -> None:
        cnt, accs = tables
        flat = {"cnt": np.asarray(cnt)}
        for i, row in enumerate(accs):
            for j, a in enumerate(row):
                flat[f"acc_{i}_{j}"] = np.asarray(a)
        tmp = self._state_path(batch_id) + ".tmp.npz"
        np.savez(tmp, **flat)
        os.replace(tmp, self._state_path(batch_id))

    def _load_state(self, batch_id: int) -> None:
        self._ensure_prep()
        with np.load(self._state_path(batch_id)) as z:
            cnt = jnp.asarray(z["cnt"])
            accs = []
            i = 0
            while f"acc_{i}_0" in z:
                row = []
                j = 0
                while f"acc_{i}_{j}" in z:
                    row.append(jnp.asarray(z[f"acc_{i}_{j}"]))
                    j += 1
                accs.append(row)
                i += 1
        self._tables = (cnt, accs)

    # -- event-time (watermark) path ----------------------------------------

    def _event_state_path(self, batch_id: int) -> str:
        return os.path.join(self._state_dir, f"ev_v{batch_id}.parquet")

    def _ensure_event_prep(self):
        """Build the per-trigger PARTIAL-aggregate program: chain replay
        + partial-mode compute (sort path, no domain bound needed). The
        state store is a HOST table of group keys + raw accumulator
        columns, merged per trigger with each accumulator's reduce op —
        the versioned StateStore:101 analog with host RAM as the
        backing tier."""
        if getattr(self, "_ev_update", None) is not None:
            return
        self._ensure_prep_common()
        import copy
        from .plan.physical import ExecContext
        agg = self._agg_exec
        partial = copy.copy(agg)
        partial.mode = "partial"
        partial.est_groups = None
        base = agg._base_schema()
        self._ev_specs = [a.func.accumulators(base)
                          for a in agg.agg_exprs]
        self._ev_acc_cols = [
            [agg._acc_col_name(i, j, spec)
             for j, spec in enumerate(self._ev_specs[i])]
            for i, a in enumerate(agg.agg_exprs)]
        self._ev_group_cols = [g.name() for g in agg.group_exprs]
        self._ev_base = base
        # window duration for eviction (group key must include window())
        from .expr_fns import TumbleWindow
        from . import types as T
        self._ev_window = None
        for g in agg.group_exprs:
            e = g
            from .expr import Alias
            while isinstance(e, Alias):
                e = e.child
            if isinstance(e, TumbleWindow):
                self._ev_window = (g.name(), e.duration_us,
                                   isinstance(e.dtype(base),
                                              T.TimestampType))
        if self.output_mode == "append" and self._ev_window is None:
            raise ValueError(
                "append mode needs an event-time window() group key so "
                "closed windows can be emitted exactly once")

        if any(a.func.uses_row_base for a in agg.agg_exprs):
            raise ValueError(
                "first/last are not supported in event-time streaming "
                "aggregations (host-merged partials have no global row "
                "order)")

        def update(b):
            ctx = ExecContext(self.session.conf)
            for op in reversed(self._chain):
                b = op.compute(ctx, [b])
            return partial.compute(ctx, [b])

        self._ev_update = jax.jit(update)


    def _event_merge(self, state: Optional[pd.DataFrame],
                     partial_pdf: pd.DataFrame) -> pd.DataFrame:
        """Fold a trigger's partial table into the state with each
        accumulator's reduce op (pure — replay safety)."""
        if state is None or not len(state):
            return partial_pdf
        both = pd.concat([state, partial_pdf], ignore_index=True)
        ops = {}
        for specs, cols in zip(self._ev_specs, self._ev_acc_cols):
            for spec, c in zip(specs, cols):
                ops[c] = spec.reduce
        return (both.groupby(self._ev_group_cols, dropna=False,
                             sort=False, as_index=False).agg(ops))

    def _event_finalize(self, state: pd.DataFrame) -> pd.DataFrame:
        """Host finalize of (a subset of) the state table."""
        agg = self._agg_exec
        out = {c: state[c].to_numpy() for c in self._ev_group_cols}
        for i, a in enumerate(agg.agg_exprs):
            accs = [state[c].to_numpy() for c in self._ev_acc_cols[i]]
            data, validity = a.func.finalize(accs, self._ev_base)
            vals = pd.Series(np.asarray(data))
            if validity is not None:
                vals = vals.where(pd.Series(np.asarray(validity)))
            out[a.out_name] = vals.to_numpy()
        return pd.DataFrame(out)

    def _run_batch_event(self, batch_id: int, table: pa.Table) -> None:
        import pyarrow.compute as pc
        self._ensure_event_prep()
        col, delay = self._watermark
        wm = self._wm
        new_state = self._evstate
        batch_max = None
        if table.num_rows:
            ts = table.column(col)
            if pa.types.is_timestamp(ts.type):
                ts_us = ts.cast(pa.timestamp("us")).cast(pa.int64())
            else:
                ts_us = ts.cast(pa.int64())
            batch_max = pc.max(ts_us).as_py()
            # late-data drop: strictly older than the CURRENT watermark
            keep = pc.greater_equal(ts_us, pa.scalar(wm, pa.int64()))
            table = table.filter(pc.fill_null(keep, False))
        if table.num_rows:
            pb = self._ev_update(self._batch_for(table))
            partial_pdf = pb.to_arrow().to_pandas()
            # normalize window keys to int64 microseconds for the host
            # merge + eviction arithmetic
            if self._ev_window is not None:
                wcol = self._ev_window[0]
                if str(partial_pdf[wcol].dtype).startswith("datetime"):
                    partial_pdf[wcol] = pd.to_datetime(
                        partial_pdf[wcol]).astype("datetime64[us]") \
                        .astype("int64")
            new_state = self._event_merge(new_state, partial_pdf)
        if batch_max is not None:
            wm = max(wm, batch_max - delay)

        emitted = None
        if self.output_mode == "append" and new_state is not None \
                and len(new_state):
            wcol, dur, _ = self._ev_window
            closed = (new_state[wcol] + dur) <= wm
            if closed.any():
                emitted = new_state[closed]
                new_state = new_state[~closed].reset_index(drop=True)

        # persist BEFORE adopting (exactly-once on replay)
        tmp = self._event_state_path(batch_id) + ".tmp"
        (new_state if new_state is not None else
         pd.DataFrame()).to_parquet(tmp)
        os.replace(tmp, self._event_state_path(batch_id))
        self._evstate = new_state
        self._wm = wm

        if self.output_mode == "complete":
            if new_state is not None and len(new_state):
                self._results.append(
                    self._apply_above(self._event_finalize(new_state)))
            else:
                self._results.append(pd.DataFrame())
        elif emitted is not None and len(emitted):
            self._results.append(
                self._apply_above(self._event_finalize(emitted)))

    def _apply_above(self, pdf: pd.DataFrame) -> pd.DataFrame:
        """Re-apply operators above the aggregate (HAVING/ORDER BY/...)
        to a finalized host table."""
        if not self._above or not len(pdf):
            return self._restore_window_type(pdf)
        from .plan.physical import ExecContext
        out = Batch.from_arrow(pa.Table.from_pandas(
            pdf, preserve_index=False))
        ctx = ExecContext(self.session.conf)
        for op in reversed(self._above):
            out = op.compute(ctx, [out])
        return self._restore_window_type(out.to_arrow().to_pandas())

    def _restore_window_type(self, pdf: pd.DataFrame) -> pd.DataFrame:
        # only TIMESTAMP event-time keys round-trip through int64 us
        # (integer event-time columns stay integers — code-review r5)
        if self._ev_window is not None and len(pdf) \
                and self._ev_window[2]:
            wcol = self._ev_window[0]
            if wcol in pdf.columns and \
                    np.issubdtype(pdf[wcol].dtype, np.integer):
                pdf = pdf.assign(**{wcol: pd.to_datetime(
                    pdf[wcol], unit="us")})
        return pdf

    # -- execution ----------------------------------------------------------

    def _ensure_prep_common(self):
        """Plan surgery shared by the device-table and event-time
        paths: plan the swapped batch query, locate the aggregate, and
        split the operator chain below/above it."""
        if getattr(self, "_agg_exec", None) is not None:
            return
        from .io.sources import ArrowTableSource
        from .plan.planner import plan_physical
        import spark_tpu.plan.physical as P

        def swap(n):
            if isinstance(n, _StreamSource):
                return L.Scan(ArrowTableSource("__stream_probe__",
                                               self.stream._table))
            return None

        phys = plan_physical(self.plan.transform_down(swap),
                             self.session.conf)

        agg_exec = None

        def walk(n):
            nonlocal agg_exec
            if isinstance(n, P.HashAggregateExec) and agg_exec is None:
                agg_exec = n
            for c in n.children:
                walk(c)

        walk(phys)
        if agg_exec is None:
            raise ValueError("aggregate lost during planning")
        self._agg_exec = agg_exec

        def unary_path(root, target):
            """Operators from (under) `root` down to `target`, refusing
            non-unary nodes (stream-static joins are unsupported — fail
            with a named error, not an unpack crash)."""
            path = []
            node = root
            while node is not target:
                if len(node.children) != 1:
                    from .expr import AnalysisError
                    raise AnalysisError(
                        f"streaming aggregation supports a single unary "
                        f"operator chain; {type(node).__name__} "
                        f"(e.g. a stream-static join) is not supported")
                path.append(node)
                node = node.children[0]
            return path

        # operators ABOVE the aggregate (HAVING filters, projections,
        # sort/limit) re-apply to each trigger's finalized table;
        # operators BELOW replay per micro-batch slice
        self._above = unary_path(phys, agg_exec)
        chain = []
        node = agg_exec.children[0]
        while node.children:
            if len(node.children) != 1:
                from .expr import AnalysisError
                raise AnalysisError(
                    f"streaming aggregation supports a single unary "
                    f"operator chain below the aggregate; "
                    f"{type(node).__name__} is not supported")
            chain.append(node)
            node = node.children[0]
        self._chain = chain

    def _ensure_prep(self):
        if self._prep is not None or self._agg is None:
            return
        self._ensure_prep_common()
        agg_exec = self._agg_exec
        from .plan.physical import ExecContext
        probe = self._batch_for(self.stream.slice(0, 0))
        ctx = ExecContext(self.session.conf)
        replayed = probe
        for op in reversed(self._chain):
            replayed = op.compute(ctx, [replayed])
        from . import types as T
        base = agg_exec.child.schema()
        for g in agg_exec.group_exprs:
            if isinstance(g.dtype(base), T.StringType):
                # the prep is built from an empty probe slice, so
                # per-batch dictionary codes would never share an
                # encoding across triggers — unsupported, not broken
                raise ValueError(
                    "string group keys are not supported in streaming "
                    "aggregations (per-batch dictionaries have no "
                    "stable shared encoding)")
        prep = agg_exec.prepare_direct(replayed, self.session.conf)
        if prep is None:
            raise ValueError(
                "streaming aggregation requires a statically-bounded "
                "integer group domain (e.g. pmod keys)")
        self._prep = prep

        def update(tables, b, row_base):
            ctx = ExecContext(self.session.conf)
            for op in reversed(self._chain):
                b = op.compute(ctx, [b])
            # row_base = the trigger's stream offset: packed First/Last
            # positions stay globally unique across triggers (and exact
            # replays of a logged range reuse the same base, keeping
            # recovery idempotent)
            return self._agg_exec.direct_update_tables(
                tables, b, prep, self.session.conf, row_base=row_base)

        # one jitted step per trigger (no donation: a save failure must
        # leave the PRE-update tables alive for an exact replay)
        self._update = jax.jit(update)

    def _batch_for(self, table: pa.Table) -> Batch:
        return Batch.from_arrow(table)

    def process_available(self) -> None:
        """Run micro-batches until the source is drained (the
        `Trigger.AvailableNow` analog; each iteration = one batch of the
        `MicroBatchExecution` loop)."""
        while True:
            batch_id = self._committed_batch + 1
            planned_id, planned = self.offset_log.latest()
            if planned_id is not None and planned_id == batch_id:
                # planned but not committed (crash between the logs):
                # replay exactly the logged range
                start, end = planned["start"], planned["end"]
            else:
                start = planned["end"] if planned is not None else 0
                end = self.stream.latest_offset()
                if end <= start:
                    return  # drained
                self.offset_log.add(batch_id, {"start": start, "end": end})
            self._run_batch(batch_id, start, end)
            payload = {"ok": True}
            if self._event_time:
                payload["wm"] = int(self._wm)
            self.commit_log.add(batch_id, payload)
            self._committed_batch = batch_id
            self._prune(batch_id)

    def _prune(self, committed: int, retain: int = 2) -> None:
        """Drop state versions and log entries older than the retained
        window (the reference's minBatchesToRetain); recovery only ever
        reads the last committed version."""
        floor = committed - retain
        for log in (self.offset_log, self.commit_log):
            for f in os.listdir(log.path):
                if f.isdigit() and int(f) < floor:
                    os.remove(os.path.join(log.path, f))
        for f in os.listdir(self._state_dir):
            if f.startswith("ev_v") and f.endswith(".parquet"):
                try:
                    vid = int(f[4:-8])
                except ValueError:
                    continue
                if vid < floor:
                    os.remove(os.path.join(self._state_dir, f))
            elif f.startswith("v") and f.endswith(".npz"):
                try:
                    vid = int(f[1:-4])
                except ValueError:
                    continue
                if vid < floor:
                    os.remove(os.path.join(self._state_dir, f))

    processAllAvailable = process_available

    def _run_batch(self, batch_id: int, start: int, end: int) -> None:
        table = self.stream.slice(start, end)
        if self._event_time:
            self._run_batch_event(batch_id, table)
            return
        if self._agg is None:
            # stateless: swap the stream placeholder for this slice and
            # run the normal engine
            from .io.sources import ArrowTableSource

            def swap(n):
                # constant name: the compiled-stage cache keys on the
                # plan fingerprint incl. source.name, so one jitted
                # program serves every trigger
                if isinstance(n, _StreamSource):
                    return L.Scan(ArrowTableSource("__microbatch__",
                                                   table))
                return None

            from .execution.executor import QueryExecution
            out = QueryExecution(
                self.session, self.plan.transform_down(swap)).collect()
            self._results.append(out.to_pandas())
            return
        # stateful: fold the slice into carried accumulator tables
        self._ensure_prep()
        if self._tables is None:
            self._tables = self._agg_exec.direct_init_tables(self._prep)
        new_tables = self._tables
        if table.num_rows:
            b = self._batch_for(table)
            if start + b.capacity >= (1 << 30) and any(
                    a.func.uses_row_base
                    for a in self._agg_exec.agg_exprs):
                raise RuntimeError(
                    "first/last over a stream exceeds the 2^30 "
                    "packed-position bound")
            import jax.numpy as jnp
            new_tables = self._update(self._tables, b,
                                      jnp.asarray(start, jnp.int64))
        # persist BEFORE adopting: a save failure must leave the
        # pre-update tables in place so an in-process retry replays the
        # same range without double-counting
        self._save_state(batch_id, new_tables)
        self._tables = new_tables
        out = self._agg_exec.direct_finalize_tables(self._tables,
                                                    self._prep)
        from .plan.physical import ExecContext
        ctx = ExecContext(self.session.conf)
        for op in reversed(self._above):
            out = op.compute(ctx, [out])
        self._results.append(out.to_arrow().to_pandas())

    # -- sink ---------------------------------------------------------------

    def latest(self) -> Optional[pd.DataFrame]:
        """Memory sink: the latest result table (complete mode) or the
        last appended slice."""
        return self._results[-1] if self._results else None

    def results(self) -> List[pd.DataFrame]:
        return list(self._results)

    def stop(self) -> None:
        pass  # manual trigger: nothing running between calls

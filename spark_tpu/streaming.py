"""Structured streaming: the micro-batch execution loop.

A scaled-to-this-engine implementation of the reference's structured
streaming core (`execution/streaming/MicroBatchExecution.scala:39`,
`StreamExecution.scala:69`): a host-driven loop that

1. polls sources for their latest offsets and WRITES THE PLANNED RANGE
   to the offset log BEFORE executing (`offsetLog:219`, an
   `HDFSMetadataLog` analog — JSON files named by batch id);
2. runs the query over exactly the logged range — stateless plans
   execute the batch slice through the normal engine; streaming
   aggregations fold the slice into versioned accumulator tables whose
   persistence is the incremental state store
   (`execution/state_store.py`: changed-group deltas between periodic
   snapshots, the RocksDBStateStoreProvider seat);
3. emits to the sink, then commits to the commit log (`commitLog:226`).

Exactly-once = offset log ∧ versioned state ∧ idempotent sinks: on
restart, a planned-but-uncommitted batch re-runs over the SAME logged
range against the last committed state version, and sinks are keyed by
batch id (the memory sink replaces a replayed batch's entry; the file
sink's atomic per-batch manifest makes a replay overwrite its own
parts), so replays change nothing. The in-memory state is only adopted
AFTER the commit-log write, so an in-process failure anywhere in the
batch leaves the query at the committed version — retrying
`process_available()` on the same object is as safe as a restart.

Crash seams: `stream_source_list`, `stream_offset_write`,
`stream_state_commit` and `stream_sink_emit` (testing/faults.py) each
fire before their boundary's action; the durability chaos matrix
(tests/test_streaming_durability.py) kills the loop at every seam and
proves a fresh query over the same checkpoint loses and duplicates
nothing.

Unattended operation: `query.start(trigger_ms=...)` runs the same
loop on a supervised daemon thread — a wall-clock trigger that
classifies batch failures through the execution/failures.py taxonomy
(TRANSIENT ticks retry under the bounded RetryPolicy backoff ladder;
FATAL errors park the query in a structured FAILED status instead of
wedging or dying silently), paces with skip-don't-queue overrun
semantics, and is cancellable/deadline-capped through the
execution/lifecycle.py token (`stop()` joins the thread bounded; the
SQL service lists live streams under GET /queries and DELETE stops
them). The socket source (io/network_source.py) extends exactly-once
over a network hop: frames are persisted before they become visible
as offsets, so the reconnect ladder replays nothing and loses
nothing. Event-time keyed state larger than
`spark_tpu.streaming.state.spillBytes` reroutes residency through the
hash-partitioned host backend (execution/external.py:
SpillableKeyedState); the persisted deltas/snapshots are identical,
so crash recovery is unchanged. The unattended seams —
`stream_net_connect`, `stream_net_recv`, `trigger_tick`,
`state_spill` — get their own chaos matrix in
tests/test_streaming_unattended.py.

Sources: `MemoryStream` (the deterministic test source) and
`FileStreamSource` (directory tailing with a persisted seen-file log;
corrupt files quarantine instead of wedging the stream). Sink:
in-memory results per batch, optionally tee'd to a `FileStreamSink`
(per-batch parquet parts + `_metadata` manifest — readers only see
manifested batches).

The TPU angle: each micro-batch is one jitted SPMD program over a
statically-shaped batch slice; state lives in HBM as accumulator tables
between triggers. Persistence pulls the full tables to host each
trigger and diffs there — the device->host transfer is O(state), but
only the CHANGED groups reach DISK (the delta), which is where the
per-trigger durability cost used to be O(state) too.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import warnings
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa

from . import functions as F  # noqa: F401  (user convenience re-export)
from .columnar import Batch
from .plan import logical as L

FILE_STRICT_KEY = "spark_tpu.streaming.source.file.strict"
RETAIN_KEY = "spark_tpu.streaming.retainBatches"
TRIGGER_MAX_RESTARTS_KEY = "spark_tpu.streaming.trigger.maxRestarts"
TRIGGER_BACKOFF_KEY = "spark_tpu.streaming.trigger.backoffMs"
SPILL_BYTES_KEY = "spark_tpu.streaming.state.spillBytes"
SPILL_PARTS_KEY = "spark_tpu.streaming.state.spillPartitions"


class _MetadataLog:
    """Numbered JSON files with atomic rename — the
    `HDFSMetadataLog`/`CheckpointFileManager` contract in miniature.

    Durability: entries are flushed + fsync'd before the rename, so a
    power cut can tear at most the not-yet-renamed tmp file. A torn or
    empty NEWEST entry (crash mid-write on a filesystem that reordered
    the flush) is skipped by `latest()` with a warning and the
    `streaming_log_corrupt` counter — recovery falls back one entry
    instead of crashing the whole restart."""

    def __init__(self, path: str, metrics=None):
        self.path = path
        self.metrics = metrics
        os.makedirs(path, exist_ok=True)

    def _ids(self) -> List[int]:
        return sorted(int(f) for f in os.listdir(self.path)
                      if f.isdigit())

    def _read(self, i: int):
        with open(os.path.join(self.path, str(i))) as f:
            return json.load(f)

    def _note_corrupt(self, i: int, exc) -> None:
        warnings.warn(
            f"skipping corrupt metadata log entry "
            f"{os.path.join(self.path, str(i))} "
            f"({type(exc).__name__}: {exc}); falling back to the "
            f"previous entry")
        if self.metrics is not None:
            self.metrics.counter("streaming_log_corrupt").inc()

    def latest(self):
        for i in reversed(self._ids()):
            try:
                return i, self._read(i)
            except (ValueError, OSError) as e:
                # a torn/empty newest entry must not wedge recovery
                self._note_corrupt(i, e)
        return None, None

    def read_all(self) -> List[dict]:
        """Entries 0..n-1 in id order, stopping at the first gap or
        corrupt entry (entries are written in order, so anything past
        a tear is from a torn future, not the committed past)."""
        out: List[dict] = []
        for want, i in enumerate(self._ids()):
            if i != want:
                break
            try:
                out.append(self._read(i))
            except (ValueError, OSError) as e:
                self._note_corrupt(i, e)
                break
        return out

    def read_all_items(self) -> List[tuple]:
        """(id, payload) for every readable entry, id order — ids may
        be sparse (the file sink's manifest skips batches that emitted
        nothing)."""
        out = []
        for i in self._ids():
            try:
                out.append((i, self._read(i)))
            except (ValueError, OSError) as e:
                self._note_corrupt(i, e)
        return out

    def add(self, batch_id: int, payload: dict) -> None:
        from .execution.state_store import fsync_replace
        final = os.path.join(self.path, str(batch_id))
        tmp = final + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        fsync_replace(tmp, final)


class MemoryStream:
    """An appendable in-memory source (the reference's `MemoryStream` —
    the deterministic test source behind StreamTest.scala:342)."""

    source_kind = "memory"

    def __init__(self, session, schema_df: pd.DataFrame):
        self.session = session
        self._table = pa.Table.from_pandas(schema_df.iloc[0:0],
                                           preserve_index=False)
        self._batches: List[pa.Table] = []

    def add_data(self, df: pd.DataFrame) -> None:
        self._batches.append(pa.Table.from_pandas(df, preserve_index=False))

    addData = add_data

    def attach_checkpoint(self, path: str) -> None:
        pass  # in-memory offsets need no persisted seen log

    def latest_offset(self) -> int:
        return len(self._batches)

    def slice(self, start: int, end: int) -> pa.Table:
        tables = self._batches[start:end]
        if not tables:
            return self._table
        return pa.concat_tables(tables)

    def to_df(self):
        """A DataFrame over a placeholder scan; the streaming loop swaps
        the placeholder for each micro-batch's slice (the reference's
        logical-plan rewrite in `MicroBatchExecution.runBatch:525`)."""
        from .dataframe import DataFrame
        return DataFrame(self.session, _StreamSource(self))


class FileStreamSource:
    """Directory-tailing source (the `FileStreamSource.scala:73`
    analog): offsets are indices into a PERSISTED seen-file log
    (`<checkpoint>/sources/0/`, one fsync'd JSON entry per discovered
    file, discovery ordered by (mtime, name)), so a restart replays
    exactly the files a planned batch covered.

    Corrupt/partial files: a file that fails to decode is QUARANTINED
    — the failure is recorded into its seen-log entry, the
    `streaming_files_quarantined` counter ticks, and the batch (and
    every replay of it) skips the file — unless
    `spark_tpu.streaming.source.file.strict` is set, in which case the
    batch fails instead."""

    source_kind = "file"

    def __init__(self, session, path: str,
                 schema_df: Optional[pd.DataFrame] = None,
                 format: str = "parquet"):
        from .io.sources import decode_stream_file, list_stream_files
        self.session = session
        self.path = path
        self.format = format
        os.makedirs(path, exist_ok=True)
        if schema_df is not None:
            self._table = pa.Table.from_pandas(schema_df.iloc[0:0],
                                               preserve_index=False)
        else:
            entries = list_stream_files(path)
            if not entries:
                raise ValueError(
                    f"file stream over empty directory {path!r} needs "
                    f"an explicit schema_df (no file to infer from)")
            first = decode_stream_file(
                os.path.join(path, entries[0]["name"]), format)
            self._table = first.slice(0, 0)
        self._seen: List[dict] = []
        self._log: Optional[_MetadataLog] = None

    def attach_checkpoint(self, path: str) -> None:
        """Bind (or re-bind on restart) the persisted seen-file log;
        the log on disk is authoritative over any in-memory view."""
        self._log = _MetadataLog(path, metrics=self.session.metrics)
        self._seen = self._log.read_all()

    def _persist(self, idx: int) -> None:
        if self._log is not None:
            self._log.add(idx, self._seen[idx])

    def latest_offset(self) -> int:
        """Discover new files and append them to the seen log; the
        offset is simply how many files have ever been seen."""
        from .io.sources import list_stream_files
        known = {e["name"] for e in self._seen}
        for e in list_stream_files(self.path):
            if e["name"] in known:
                continue
            e["quarantined"] = None
            self._seen.append(e)
            self._persist(len(self._seen) - 1)
        return len(self._seen)

    def slice(self, start: int, end: int) -> pa.Table:
        from .io.sources import decode_stream_file
        if end > len(self._seen):
            # a torn seen-log tail lost entries a PLANNED offset range
            # covers. Discovery order is deterministic ((mtime, name),
            # already-seen names skipped), so re-discovering appends
            # the lost files back at their original indices — the
            # self-healing path. Still short afterwards = the files
            # themselves are gone: fail loudly rather than silently
            # committing a batch that skipped planned data.
            self.latest_offset()
        if end > len(self._seen):
            raise RuntimeError(
                f"seen-file log under {self.path!r} has "
                f"{len(self._seen)} entries but the planned offset "
                f"range is [{start}, {end}): files covered by a "
                f"planned batch vanished; cannot recover exactly-once")
        strict = bool(self.session.conf.get(FILE_STRICT_KEY))
        tables = []
        for i in range(start, end):
            entry = self._seen[i]
            if entry.get("quarantined"):
                continue  # quarantined on a previous attempt: stays out
            full = os.path.join(self.path, entry["name"])
            try:
                t = decode_stream_file(full, self.format)
                t = self._conform(t)
            except Exception as e:  # noqa: BLE001 — decode = quarantine
                if strict:
                    raise RuntimeError(
                        f"stream file {full!r} failed to decode under "
                        f"streaming.source.file.strict: "
                        f"{type(e).__name__}: {e}") from e
                entry["quarantined"] = f"{type(e).__name__}: {e}"[:200]
                self._persist(i)
                self.session.metrics.counter(
                    "streaming_files_quarantined").inc()
                warnings.warn(
                    f"quarantined corrupt stream file {full!r}: "
                    f"{entry['quarantined']}")
                continue
            if t.num_rows:
                tables.append(t)
        if not tables:
            return self._table
        return pa.concat_tables(tables)

    def _conform(self, t: pa.Table) -> pa.Table:
        """Project/cast a decoded file onto the stream schema; a file
        that cannot conform is as corrupt as one that cannot parse."""
        if t.schema == self._table.schema:
            return t
        return t.select(self._table.column_names).cast(self._table.schema)

    def quarantined(self) -> List[dict]:
        """The quarantined seen-log entries (path + failure reason)."""
        return [dict(e, path=os.path.join(self.path, e["name"]))
                for e in self._seen if e.get("quarantined")]

    def to_df(self):
        from .dataframe import DataFrame
        return DataFrame(self.session, _StreamSource(self))


class _StreamSource(L.LeafPlan):
    """Logical placeholder for a streaming source."""

    def __init__(self, stream):
        self.stream = stream
        self.children = ()

    def schema(self):
        from .io.sources import ArrowTableSource
        return ArrowTableSource("__stream__", self.stream._table).schema()

    def simple_string(self):
        return f"StreamSource({getattr(self.stream, 'source_kind', '?')})"


class FileStreamSink:
    """Per-batch parquet parts committed by an atomic batch manifest —
    the `FileStreamSink.scala` / `_spark_metadata` contract: a part
    file only exists for readers once its batch's manifest entry
    landed (fsync + atomic rename), and a REPLAYED batch rewrites its
    own deterministically-named parts, so crash-replay can neither
    lose nor duplicate sink rows."""

    def __init__(self, session, path: str, output_mode: str):
        self.session = session
        self.path = path
        self.output_mode = output_mode
        os.makedirs(path, exist_ok=True)
        self._manifest = _MetadataLog(os.path.join(path, "_metadata"),
                                      metrics=session.metrics)

    def emit(self, batch_id: int, pdf: pd.DataFrame) -> int:
        import pyarrow.parquet as pq
        from .execution.state_store import fsync_replace
        name = f"part-{batch_id:05d}.parquet"
        full = os.path.join(self.path, name)
        tmp = full + ".tmp"
        pq.write_table(pa.Table.from_pandas(pdf, preserve_index=False),
                       tmp)
        fsync_replace(tmp, full)
        self._manifest.add(batch_id, {"parts": [name],
                                      "rows": int(len(pdf)),
                                      "mode": self.output_mode})
        return 1

    def prune(self, committed: int, retain: int) -> None:
        """Complete-mode garbage collection: every batch rewrites the
        FULL result, so parts superseded by more than the retention
        window are dead weight — retire their manifest entries and
        files. Append-mode parts ARE the data and are never pruned."""
        if self.output_mode != "complete":
            return
        floor = committed - int(retain)
        for batch_id, payload in self._manifest.read_all_items():
            if batch_id >= floor:
                continue
            try:
                os.remove(os.path.join(self._manifest.path,
                                       str(batch_id)))
            except OSError:
                pass
            for part in payload.get("parts", []):
                try:
                    os.remove(os.path.join(self.path, part))
                except OSError:
                    pass

    @staticmethod
    def read(path: str) -> pd.DataFrame:
        """Manifested rows only (unmanifested parts are invisible —
        they belong to a batch that never committed). Append-mode
        output concatenates every manifested batch; complete-mode
        output is the LATEST manifested batch (each batch rewrites the
        whole result)."""
        log = _MetadataLog(os.path.join(path, "_metadata"))
        items = log.read_all_items()
        if not items:
            return pd.DataFrame()
        mode = items[-1][1].get("mode", "append")
        if mode == "complete":
            items = items[-1:]
        frames = []
        for _, payload in items:
            for part in payload.get("parts", []):
                frames.append(pd.read_parquet(os.path.join(path, part)))
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)


def read_sink(path: str) -> pd.DataFrame:
    """Module-level alias of FileStreamSink.read (the reader side of
    the manifest contract)."""
    return FileStreamSink.read(path)


# ---------------------------------------------------------------------------
# Live-query registry + supervised trigger status
# ---------------------------------------------------------------------------

#: queries with a RUNNING trigger loop, keyed by the "stream-<n>" live
#: id the SQL service exposes (GET /queries folds these rows in;
#: DELETE /queries/stream-<n> stops the loop). Registered in start(),
#: unregistered by the loop's finally (and again, idempotently, by
#: stop()). Lock: analysis/concurrency/registry.py `streaming.live`.
_LIVE_LOCK = threading.Lock()
_LIVE: Dict[str, "StreamingQuery"] = {}
_LIVE_SEQ = 0


def _register_live(q: "StreamingQuery") -> str:
    global _LIVE_SEQ
    with _LIVE_LOCK:
        _LIVE_SEQ += 1
        live_id = f"stream-{_LIVE_SEQ}"
        _LIVE[live_id] = q
    return live_id


def _unregister_live(live_id: Optional[str]) -> None:
    if live_id is None:
        return
    with _LIVE_LOCK:
        _LIVE.pop(live_id, None)


def get_live(live_id: str) -> Optional["StreamingQuery"]:
    with _LIVE_LOCK:
        return _LIVE.get(live_id)


def live_queries() -> List[dict]:
    """Status rows for every live trigger loop. Snapshot the registry
    under its lock, build the rows OUTSIDE it: each row takes that
    query's _TriggerStatus lock, and the two locks are never held
    together (registry rank 25 < trigger rank 27 would allow it, but
    one-at-a-time needs no edge)."""
    with _LIVE_LOCK:
        items = sorted(_LIVE.items())
    return [dict(q.state(), id=live_id) for live_id, q in items]


class _TriggerStatus:
    """The CROSS-THREAD slice of a supervised streaming query: the
    trigger-loop thread writes it; `status`/`state()`, the service
    listing and `stop()` read it. Kept in its own tiny class so the
    concurrency lint audits exactly these fields — everything else on
    StreamingQuery stays confined to whichever thread currently
    drives the loop (start() hands the whole object to the trigger
    thread; the manual-trigger path never starts one)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.status = "INITIALIZED"
        self.error: Optional[str] = None
        self.ticks = 0
        self.skipped_ticks = 0
        self.restarts = 0
        self.last_skew_ms = 0.0
        self.trigger_ms: Optional[float] = None

    def snapshot(self) -> dict:
        with self._lock:
            return {"status": self.status, "error": self.error,
                    "ticks": self.ticks,
                    "skipped_ticks": self.skipped_ticks,
                    "restarts": self.restarts,
                    "last_skew_ms": self.last_skew_ms,
                    "trigger_ms": self.trigger_ms}

    def set_running(self, trigger_ms: float) -> None:
        with self._lock:
            self.status = "RUNNING"
            self.error = None
            self.trigger_ms = float(trigger_ms)

    def finish(self, status: str, error: Optional[str]) -> None:
        with self._lock:
            self.status = status
            self.error = error

    def tick(self, skew_ms: float) -> int:
        with self._lock:
            self.ticks += 1
            self.last_skew_ms = float(skew_ms)
            return self.ticks

    def skip(self, n: int) -> None:
        with self._lock:
            self.skipped_ticks += int(n)

    def restart(self) -> int:
        with self._lock:
            self.restarts += 1
            return self.restarts


class StreamingQuery:
    """One micro-batch query (reference: StreamExecution). Trigger is
    manual (`process_available()`) — the deterministic single-step mode
    StreamTest uses — or the supervised wall-clock loop behind
    `start(trigger_ms=...)`."""

    def __init__(self, session, plan: L.LogicalPlan, stream,
                 checkpoint_dir: str, output_mode: str = "complete",
                 sink_path: Optional[str] = None):
        if output_mode not in ("complete", "append"):
            raise ValueError(f"unsupported outputMode {output_mode!r}")
        self.session = session
        self.plan = plan
        self.stream = stream
        self.output_mode = output_mode
        self.offset_log = _MetadataLog(
            os.path.join(checkpoint_dir, "offsets"),
            metrics=session.metrics)
        self.commit_log = _MetadataLog(
            os.path.join(checkpoint_dir, "commits"),
            metrics=session.metrics)
        from .execution.state_store import StateStore
        self._state_dir = os.path.join(checkpoint_dir, "state")
        self._store = StateStore(self._state_dir, session.conf,
                                 metrics=session.metrics)
        stream.attach_checkpoint(
            os.path.join(checkpoint_dir, "sources", "0"))
        self._agg = self._find_aggregate(plan)
        self._watermark = self._find_watermark(plan)
        self._event_time = (self._agg is not None
                            and self._watermark is not None)
        if self._agg is not None and output_mode == "append" \
                and not self._event_time:
            # the reference rejects append-without-watermark for
            # aggregations at analysis time; silently re-emitting every
            # group each trigger would duplicate sink rows
            raise ValueError(
                "outputMode='append' on a streaming aggregation needs "
                "a watermark (with_watermark) so closed windows can be "
                "emitted exactly once; use 'complete' otherwise")
        #: memory sink keyed by BATCH ID: a replayed batch REPLACES its
        #: own entry instead of appending a duplicate (exactly-once at
        #: the sink, not just the state)
        self._sink_results: Dict[int, pd.DataFrame] = {}
        self._file_sink = (FileStreamSink(session, sink_path, output_mode)
                           if sink_path else None)
        self._tables = None      # committed aggregate state (device)
        self._flat = None        # committed aggregate state (host copy)
        self._prep = None
        self._pending = None     # post-batch state awaiting commit
        # event-time path: host state table + watermark (us)
        self._evstate: Optional[pd.DataFrame] = None
        self._wm: int = -(1 << 62)
        # host-spillable keyed state (engages lazily when the resident
        # event-time frame exceeds streaming.state.spillBytes)
        self._spill = None
        self._spill_dir = os.path.join(checkpoint_dir, "state", "spill")
        # supervised trigger loop (start()/stop())
        self._trigger = _TriggerStatus()
        self._loop_thread: Optional[threading.Thread] = None
        self._token = None
        self._live_id: Optional[str] = None
        self._recover()

    # -- plan shape ---------------------------------------------------------

    @staticmethod
    def _find_aggregate(plan: L.LogicalPlan) -> Optional[L.Aggregate]:
        """The single streaming aggregate, if any (stateless otherwise).
        Nested/multiple aggregates are out of scope, as in the
        reference's UnsupportedOperationChecker."""
        aggs: List[L.Aggregate] = []

        def walk(n):
            if isinstance(n, L.Aggregate):
                aggs.append(n)
            for c in n.children:
                walk(c)

        walk(plan)
        if len(aggs) > 1:
            raise ValueError("multiple streaming aggregates unsupported")
        return aggs[0] if aggs else None

    @staticmethod
    def _find_watermark(plan: L.LogicalPlan):
        """(col_name, delay_us) of the single Watermark node, if any."""
        found = []

        def walk(n):
            if isinstance(n, L.Watermark):
                found.append((n.col_name, n.delay_us))
            for c in n.children:
                walk(c)

        walk(plan)
        return found[0] if found else None

    def _shape(self) -> str:
        if self._event_time:
            return "event_time"
        return "stateful" if self._agg is not None else "stateless"

    # -- recovery -----------------------------------------------------------

    def _recover(self) -> None:
        """Restart semantics: resume state at the last COMMITTED batch;
        a planned-but-uncommitted offset entry will re-run over its
        logged range (idempotent because state is versioned and sinks
        are batch-id keyed)."""
        t0 = time.perf_counter()
        last_commit, payload = self.commit_log.latest()
        self._committed_batch = -1 if last_commit is None else last_commit
        # the committed batch's END offset: the floor for the next
        # planned range. Guards the asymmetric-corruption case — the
        # offset log's newest entry torn while its commit survived —
        # where falling back one OFFSET entry would re-plan (and
        # double-fold) a range the committed state already contains.
        self._committed_end = int((payload or {}).get("end", 0)) \
            if last_commit is not None else 0
        if self._agg is not None and last_commit is not None:
            if self._event_time:
                self._wm = int((payload or {}).get("wm", self._wm))
                self._evstate = self._store.load_frame(last_commit)
            else:
                self._load_state(last_commit)
            self.session.metrics.counter("streaming_restore_ms").inc(
                round((time.perf_counter() - t0) * 1e3, 3))

    def _save_state(self, batch_id: int, tables) -> dict:
        """Persist the POST-batch accumulator tables as version
        `batch_id` through the incremental state store (delta of the
        changed groups, or a snapshot on the cadence)."""
        cnt, accs = tables
        flat = {"cnt": np.asarray(cnt)}
        for i, row in enumerate(accs):
            for j, a in enumerate(row):
                flat[f"acc_{i}_{j}"] = np.asarray(a)
        info = self._store.commit_tables(batch_id, flat, self._flat)
        self._pending = {"tables": tables, "flat": flat}
        return info

    def _load_state(self, batch_id: int) -> None:
        self._ensure_prep()
        flat = self._store.load_tables(batch_id)
        cnt = jnp.asarray(flat["cnt"])
        accs = []
        i = 0
        while f"acc_{i}_0" in flat:
            row = []
            j = 0
            while f"acc_{i}_{j}" in flat:
                row.append(jnp.asarray(flat[f"acc_{i}_{j}"]))
                j += 1
            accs.append(row)
            i += 1
        self._tables = (cnt, accs)
        self._flat = flat

    def _adopt_pending(self) -> None:
        """Adopt the post-batch state AFTER the commit-log write: an
        in-process failure anywhere in the batch leaves the query at
        the committed version, so re-calling process_available() on
        the same object replays exactly like a fresh restart."""
        p, self._pending = self._pending, None
        if p is None:
            return
        if "tables" in p:
            self._tables = p["tables"]
            self._flat = p["flat"]
        if "evstate" in p:
            if p.get("spilled"):
                # the spill partitions only move AFTER the commit-log
                # write, and only the partitions this batch touched; a
                # no-change batch rewrites nothing
                if p.get("touched"):
                    self._spill.adopt(p["evstate"], p["touched"])
                self._evstate = None
            else:
                self._evstate = p["evstate"]
            self._wm = p["wm"]

    # -- event-time (watermark) path ----------------------------------------

    def _ensure_event_prep(self):
        """Build the per-trigger PARTIAL-aggregate program: chain replay
        + partial-mode compute (sort path, no domain bound needed). The
        state store is a HOST table of group keys + raw accumulator
        columns, merged per trigger with each accumulator's reduce op —
        the versioned StateStore:101 analog with host RAM as the
        backing tier."""
        if getattr(self, "_ev_update", None) is not None:
            return
        self._ensure_prep_common()
        import copy
        from .plan.physical import ExecContext
        agg = self._agg_exec
        partial = copy.copy(agg)
        partial.mode = "partial"
        partial.est_groups = None
        base = agg._base_schema()
        self._ev_specs = [a.func.accumulators(base)
                          for a in agg.agg_exprs]
        self._ev_acc_cols = [
            [agg._acc_col_name(i, j, spec)
             for j, spec in enumerate(self._ev_specs[i])]
            for i, a in enumerate(agg.agg_exprs)]
        self._ev_group_cols = [g.name() for g in agg.group_exprs]
        self._ev_base = base
        # window duration for eviction (group key must include window())
        from .expr_fns import TumbleWindow
        from . import types as T
        self._ev_window = None
        for g in agg.group_exprs:
            e = g
            from .expr import Alias
            while isinstance(e, Alias):
                e = e.child
            if isinstance(e, TumbleWindow):
                self._ev_window = (g.name(), e.duration_us,
                                   isinstance(e.dtype(base),
                                              T.TimestampType))
        if self.output_mode == "append" and self._ev_window is None:
            raise ValueError(
                "append mode needs an event-time window() group key so "
                "closed windows can be emitted exactly once")

        if any(a.func.uses_row_base for a in agg.agg_exprs):
            raise ValueError(
                "first/last are not supported in event-time streaming "
                "aggregations (host-merged partials have no global row "
                "order)")

        def update(b):
            ctx = ExecContext(self.session.conf)
            for op in reversed(self._chain):
                b = op.compute(ctx, [b])
            return partial.compute(ctx, [b])

        self._ev_update = jax.jit(update)

    def _event_merge(self, state: Optional[pd.DataFrame],
                     partial_pdf: pd.DataFrame) -> pd.DataFrame:
        """Fold a trigger's partial table into the state with each
        accumulator's reduce op (pure — replay safety)."""
        if state is None or not len(state):
            return partial_pdf
        both = pd.concat([state, partial_pdf], ignore_index=True)
        ops = {}
        for specs, cols in zip(self._ev_specs, self._ev_acc_cols):
            for spec, c in zip(specs, cols):
                ops[c] = spec.reduce
        return (both.groupby(self._ev_group_cols, dropna=False,
                             sort=False, as_index=False).agg(ops))

    def _event_finalize(self, state: pd.DataFrame) -> pd.DataFrame:
        """Host finalize of (a subset of) the state table."""
        agg = self._agg_exec
        out = {c: state[c].to_numpy() for c in self._ev_group_cols}
        for i, a in enumerate(agg.agg_exprs):
            accs = [state[c].to_numpy() for c in self._ev_acc_cols[i]]
            data, validity = a.func.finalize(accs, self._ev_base)
            vals = pd.Series(np.asarray(data))
            if validity is not None:
                vals = vals.where(pd.Series(np.asarray(validity)))
            out[a.out_name] = vals.to_numpy()
        return pd.DataFrame(out)

    def _maybe_engage_spill(self) -> None:
        """Reroute event-time state residency through the host spill
        backend once the resident frame exceeds its byte budget
        (streaming.state.spillBytes; 0 = never). The persisted
        deltas/snapshots are identical either way, so crash recovery
        never notices — after a restart the store hands back a
        resident frame and the very next trigger re-engages here."""
        budget = int(self.session.conf.get(SPILL_BYTES_KEY))
        if not budget or self._spill is not None \
                or self._evstate is None:
            return
        if self._frame_bytes(self._evstate) <= budget:
            return
        from .execution.external import SpillableKeyedState
        self._spill = SpillableKeyedState(
            self._spill_dir, self._ev_group_cols,
            int(self.session.conf.get(SPILL_PARTS_KEY)),
            metrics=self.session.metrics)
        self._spill.reset(self._evstate)
        self._evstate = None

    @staticmethod
    def _frame_bytes(pdf: pd.DataFrame) -> int:
        return int(pdf.memory_usage(index=False, deep=True).sum())

    def _run_batch_event(self, batch_id: int, table: pa.Table):
        import pyarrow.compute as pc
        self._ensure_event_prep()
        self._maybe_engage_spill()
        spilled = self._spill is not None
        state0 = self._spill.materialize() if spilled else self._evstate
        col, delay = self._watermark
        wm = self._wm
        new_state = state0
        touched: List[int] = []
        batch_max = None
        if table.num_rows:
            ts = table.column(col)
            if pa.types.is_timestamp(ts.type):
                ts_us = ts.cast(pa.timestamp("us")).cast(pa.int64())
            else:
                ts_us = ts.cast(pa.int64())
            batch_max = pc.max(ts_us).as_py()
            # late-data drop: strictly older than the CURRENT watermark
            keep = pc.greater_equal(ts_us, pa.scalar(wm, pa.int64()))
            table = table.filter(pc.fill_null(keep, False))
        if table.num_rows:
            pb = self._ev_update(self._batch_for(table))
            partial_pdf = pb.to_arrow().to_pandas()
            # normalize window keys to int64 microseconds for the host
            # merge + eviction arithmetic
            if self._ev_window is not None:
                wcol = self._ev_window[0]
                if str(partial_pdf[wcol].dtype).startswith("datetime"):
                    partial_pdf[wcol] = pd.to_datetime(
                        partial_pdf[wcol]).astype("datetime64[us]") \
                        .astype("int64")
            if spilled:
                new_state, touched = self._spill.merge(
                    partial_pdf, self._event_merge)
            else:
                new_state = self._event_merge(new_state, partial_pdf)
        if batch_max is not None:
            wm = max(wm, batch_max - delay)

        emitted = None
        if self.output_mode == "append" and new_state is not None \
                and len(new_state):
            wcol, dur, _ = self._ev_window
            closed = (new_state[wcol] + dur) <= wm
            if closed.any():
                emitted = new_state[closed]
                new_state = new_state[~closed].reset_index(drop=True)
                if spilled:
                    # evicted groups SHRANK their partitions: those
                    # must rewrite at adoption too
                    touched = sorted(
                        set(touched) | set(
                            self._spill.touched_by(emitted)))

        # persist BEFORE emitting/adopting (exactly-once on replay):
        # the store diffs against the COMMITTED state and writes a
        # changed-rows delta (or a snapshot on the cadence)
        info = self._store.commit_frame(batch_id, new_state, state0,
                                        self._ev_group_cols)
        self._pending = {"evstate": new_state, "wm": wm,
                         "spilled": spilled, "touched": touched}

        out = None
        if self.output_mode == "complete":
            if new_state is not None and len(new_state):
                out = self._apply_above(self._event_finalize(new_state))
            else:
                out = pd.DataFrame()
        elif emitted is not None and len(emitted):
            out = self._apply_above(self._event_finalize(emitted))
        return out, info

    def _apply_above(self, pdf: pd.DataFrame) -> pd.DataFrame:
        """Re-apply operators above the aggregate (HAVING/ORDER BY/...)
        to a finalized host table."""
        if not self._above or not len(pdf):
            return self._restore_window_type(pdf)
        from .plan.physical import ExecContext
        out = Batch.from_arrow(pa.Table.from_pandas(
            pdf, preserve_index=False))
        ctx = ExecContext(self.session.conf)
        for op in reversed(self._above):
            out = op.compute(ctx, [out])
        return self._restore_window_type(out.to_arrow().to_pandas())

    def _restore_window_type(self, pdf: pd.DataFrame) -> pd.DataFrame:
        # only TIMESTAMP event-time keys round-trip through int64 us
        # (integer event-time columns stay integers — code-review r5)
        if self._ev_window is not None and len(pdf) \
                and self._ev_window[2]:
            wcol = self._ev_window[0]
            if wcol in pdf.columns and \
                    np.issubdtype(pdf[wcol].dtype, np.integer):
                pdf = pdf.assign(**{wcol: pd.to_datetime(
                    pdf[wcol], unit="us")})
        return pdf

    # -- execution ----------------------------------------------------------

    def _ensure_prep_common(self):
        """Plan surgery shared by the device-table and event-time
        paths: plan the swapped batch query, locate the aggregate, and
        split the operator chain below/above it."""
        if getattr(self, "_agg_exec", None) is not None:
            return
        from .io.sources import ArrowTableSource
        from .plan.planner import plan_physical
        import spark_tpu.plan.physical as P

        def swap(n):
            if isinstance(n, _StreamSource):
                return L.Scan(ArrowTableSource("__stream_probe__",
                                               self.stream._table))
            return None

        phys = plan_physical(self.plan.transform_down(swap),
                             self.session.conf)

        agg_exec = None

        def walk(n):
            nonlocal agg_exec
            if isinstance(n, P.HashAggregateExec) and agg_exec is None:
                agg_exec = n
            for c in n.children:
                walk(c)

        walk(phys)
        if agg_exec is None:
            raise ValueError("aggregate lost during planning")
        self._agg_exec = agg_exec

        def unary_path(root, target):
            """Operators from (under) `root` down to `target`, refusing
            non-unary nodes (stream-static joins are unsupported — fail
            with a named error, not an unpack crash)."""
            path = []
            node = root
            while node is not target:
                if len(node.children) != 1:
                    from .expr import AnalysisError
                    raise AnalysisError(
                        f"streaming aggregation supports a single unary "
                        f"operator chain; {type(node).__name__} "
                        f"(e.g. a stream-static join) is not supported")
                path.append(node)
                node = node.children[0]
            return path

        # operators ABOVE the aggregate (HAVING filters, projections,
        # sort/limit) re-apply to each trigger's finalized table;
        # operators BELOW replay per micro-batch slice
        self._above = unary_path(phys, agg_exec)
        chain = []
        node = agg_exec.children[0]
        while node.children:
            if len(node.children) != 1:
                from .expr import AnalysisError
                raise AnalysisError(
                    f"streaming aggregation supports a single unary "
                    f"operator chain below the aggregate; "
                    f"{type(node).__name__} is not supported")
            chain.append(node)
            node = node.children[0]
        self._chain = chain

    def _ensure_prep(self):
        if self._prep is not None or self._agg is None:
            return
        self._ensure_prep_common()
        agg_exec = self._agg_exec
        from .plan.physical import ExecContext
        probe = self._batch_for(self.stream.slice(0, 0))
        ctx = ExecContext(self.session.conf)
        replayed = probe
        for op in reversed(self._chain):
            replayed = op.compute(ctx, [replayed])
        from . import types as T
        base = agg_exec.child.schema()
        for g in agg_exec.group_exprs:
            if isinstance(g.dtype(base), T.StringType):
                # the prep is built from an empty probe slice, so
                # per-batch dictionary codes would never share an
                # encoding across triggers — unsupported, not broken
                raise ValueError(
                    "string group keys are not supported in streaming "
                    "aggregations (per-batch dictionaries have no "
                    "stable shared encoding)")
        prep = agg_exec.prepare_direct(replayed, self.session.conf)
        if prep is None:
            raise ValueError(
                "streaming aggregation requires a statically-bounded "
                "integer group domain (e.g. pmod keys)")
        self._prep = prep

        if getattr(self.stream, "source_kind", "memory") == "file" \
                and any(a.func.uses_row_base
                        for a in agg_exec.agg_exprs):
            raise ValueError(
                "first/last are not supported over file stream sources "
                "(file offsets are file indices, not row positions, so "
                "packed positions would collide across batches)")

        def update(tables, b, row_base):
            ctx = ExecContext(self.session.conf)
            for op in reversed(self._chain):
                b = op.compute(ctx, [b])
            # row_base = the trigger's stream offset: packed First/Last
            # positions stay globally unique across triggers (and exact
            # replays of a logged range reuse the same base, keeping
            # recovery idempotent)
            return self._agg_exec.direct_update_tables(
                tables, b, prep, self.session.conf, row_base=row_base)

        # one jitted step per trigger (no donation: a save failure must
        # leave the PRE-update tables alive for an exact replay)
        self._update = jax.jit(update)

    def _batch_for(self, table: pa.Table) -> Batch:
        return Batch.from_arrow(table)

    def process_available(self) -> None:
        """Run micro-batches until the source is drained (the
        `Trigger.AvailableNow` analog; each iteration = one batch of the
        `MicroBatchExecution` loop). Loop order per batch: source list
        -> offset write -> run (state commit) -> sink emit -> commit
        log -> adopt state -> prune; the stream_* chaos seams fire
        before each persistent action."""
        from .execution import lifecycle
        from .testing import faults
        faults.arm(self.session.conf)
        while True:
            # cooperative cancellation boundary once per trigger: a
            # cancel/deadline between micro-batches stops the loop
            # with the durable state at the last COMMITTED batch, so
            # a fresh query over the same checkpoint resumes
            # exactly-once (execution/lifecycle.py)
            lifecycle.checkpoint("stream_trigger")
            self._pending = None
            batch_id = self._committed_batch + 1
            # chaos seam: a crash before the loop even polls the source
            faults.fire("stream_source_list")
            planned_id, planned = self.offset_log.latest()
            if planned_id is not None and planned_id == batch_id:
                # planned but not committed (crash between the logs):
                # replay exactly the logged range
                start, end = planned["start"], planned["end"]
            else:
                start = planned["end"] if planned is not None else 0
                # never re-plan below the committed watermark: a torn
                # newest OFFSET entry whose commit survived would
                # otherwise hand back an already-folded range
                start = max(start, self._committed_end)
                end = self.stream.latest_offset()
                if end <= start:
                    return  # drained
                # chaos seam: crash before the planned range persists
                faults.fire("stream_offset_write")
                self.offset_log.add(batch_id, {"start": start,
                                               "end": end})
            t0 = time.perf_counter()
            q0 = self.session.metrics.counter(
                "streaming_files_quarantined").value
            out, info = self._run_batch(batch_id, start, end)
            # chaos seam: state committed, sink not yet emitted
            faults.fire("stream_sink_emit")
            sink_parts = self._emit(batch_id, out)
            # `end` rides the commit entry: recovery floors the next
            # planned range at it (see _recover)
            payload = {"ok": True, "end": int(end)}
            if self._event_time:
                payload["wm"] = int(self._pending["wm"])
            self.commit_log.add(batch_id, payload)
            self._committed_batch = batch_id
            self._committed_end = int(end)
            self._adopt_pending()
            self._record_batch(
                batch_id, start, end, out, info,
                wall_ms=(time.perf_counter() - t0) * 1e3,
                quarantined=int(self.session.metrics.counter(
                    "streaming_files_quarantined").value - q0),
                sink_parts=sink_parts)
            self._prune(batch_id)

    def _prune(self, committed: int) -> None:
        """Drop log entries older than the retained window (the
        reference's minBatchesToRetain) and let the state store
        compact deltas/snapshots no retained restore needs; recovery
        only ever reads the last committed version."""
        retain = int(self.session.conf.get(RETAIN_KEY))
        floor = committed - retain
        for log in (self.offset_log, self.commit_log):
            for f in os.listdir(log.path):
                if f.isdigit() and int(f) < floor:
                    os.remove(os.path.join(log.path, f))
        self._store.prune(committed, retain)
        if self._file_sink is not None:
            self._file_sink.prune(committed, retain)
        if self.output_mode == "complete":
            # complete mode rewrites the FULL result per batch: memory
            # -sink entries outside the window are superseded dead
            # weight on a long-running stream (append entries ARE the
            # data and stay)
            for k in [k for k in self._sink_results if k < floor]:
                del self._sink_results[k]

    processAllAvailable = process_available

    def _run_batch(self, batch_id: int, start: int, end: int):
        table = self.stream.slice(start, end)
        if self._event_time:
            out, info = self._run_batch_event(batch_id, table)
            info["rows_in"] = int(table.num_rows)
            return out, info
        if self._agg is None:
            # stateless: swap the stream placeholder for this slice and
            # run the normal engine
            from .io.sources import ArrowTableSource

            def swap(n):
                # constant name: the compiled-stage cache keys on the
                # plan fingerprint incl. source.name, so one jitted
                # program serves every trigger
                if isinstance(n, _StreamSource):
                    return L.Scan(ArrowTableSource("__microbatch__",
                                                   table))
                return None

            from .execution.executor import QueryExecution
            out = QueryExecution(
                self.session, self.plan.transform_down(swap)).collect()
            return out.to_pandas(), {"kind": "stateless", "bytes": None,
                                     "changed": None,
                                     "rows_in": int(table.num_rows)}
        # stateful: fold the slice into carried accumulator tables
        self._ensure_prep()
        if self._tables is None:
            self._tables = self._agg_exec.direct_init_tables(self._prep)
            self._flat = None
        new_tables = self._tables
        if table.num_rows:
            b = self._batch_for(table)
            if start + b.capacity >= (1 << 30) and any(
                    a.func.uses_row_base
                    for a in self._agg_exec.agg_exprs):
                raise RuntimeError(
                    "first/last over a stream exceeds the 2^30 "
                    "packed-position bound")
            new_tables = self._update(self._tables, b,
                                      jnp.asarray(start, jnp.int64))
        # persist BEFORE emitting/adopting: the incremental store
        # diffs the host copies against the committed version and
        # writes only the changed groups (or a snapshot on cadence)
        info = self._save_state(batch_id, new_tables)
        info["rows_in"] = int(table.num_rows)
        out = self._agg_exec.direct_finalize_tables(new_tables,
                                                    self._prep)
        from .plan.physical import ExecContext
        ctx = ExecContext(self.session.conf)
        for op in reversed(self._above):
            out = op.compute(ctx, [out])
        return out.to_arrow().to_pandas(), info

    # -- sink ---------------------------------------------------------------

    def _emit(self, batch_id: int, out: Optional[pd.DataFrame]) -> int:
        """Route a batch's output to the sinks, KEYED BY BATCH ID: a
        replayed batch replaces its own memory-sink entry, and the file
        sink's manifest makes the part overwrite invisible until
        re-manifested."""
        if out is None:
            return 0
        self._sink_results[batch_id] = out
        if self._file_sink is not None:
            return self._file_sink.emit(batch_id, out)
        return 0

    def _record_batch(self, batch_id: int, start: int, end: int, out,
                      info: dict, wall_ms: float, quarantined: int,
                      sink_parts: int) -> None:
        m = self.session.metrics
        m.counter("streaming_batches").inc()
        m.counter("streaming_rows").inc(int(info.get("rows_in") or 0))
        record = {
            "batch_id": int(batch_id),
            "start": int(start), "end": int(end),
            "rows_in": int(info.get("rows_in") or 0),
            "rows_out": int(len(out)) if out is not None else 0,
            "kind": str(info.get("kind") or "stateless"),
            "state_bytes": info.get("bytes"),
            "changed_groups": info.get("changed"),
            "quarantined": int(quarantined),
            "sink_parts": int(sink_parts),
            "source": str(getattr(self.stream, "source_kind", "memory")),
            "wall_ms": round(float(wall_ms), 3),
        }
        from .observability.listener import StreamingBatchEvent
        self.session.listeners.post(
            "on_streaming_batch",
            StreamingBatchEvent(
                query_id=self.session._next_query_id(), ts=time.time(),
                plan=f"StreamingQuery[{self._shape()},"
                     f"{self.output_mode}]",
                record=record))

    def latest(self) -> Optional[pd.DataFrame]:
        """Memory sink: the latest batch's result table (complete mode)
        or the last appended slice."""
        if not self._sink_results:
            return None
        return self._sink_results[max(self._sink_results)]

    def results(self) -> List[pd.DataFrame]:
        """Every emitted batch's table in batch order (replays
        replaced, never duplicated)."""
        return [self._sink_results[k]
                for k in sorted(self._sink_results)]

    # -- supervised trigger loop --------------------------------------------

    @property
    def status(self) -> str:
        """INITIALIZED | RUNNING | STOPPED | FAILED."""
        return self._trigger.snapshot()["status"]

    def exception(self) -> Optional[str]:
        """The parking error of a FAILED query (None otherwise)."""
        return self._trigger.snapshot()["error"]

    def state(self) -> dict:
        """Structured status — the GET /queries row for live streams:
        trigger counters plus the committed frontier."""
        s = self._trigger.snapshot()
        s.update({
            "shape": self._shape(),
            "output_mode": self.output_mode,
            "source": str(getattr(self.stream, "source_kind",
                                  "memory")),
            "committed_batch": int(self._committed_batch),
        })
        return s

    def start(self, trigger_ms: float = 100.0, clock=None, sleep=None,
              rng=None) -> "StreamingQuery":
        """Run the micro-batch loop unattended: a daemon thread calls
        `process_available()` every `trigger_ms` of wall clock under a
        restart supervisor. TRANSIENT/TIMEOUT tick failures (the
        execution/failures.py taxonomy — network resets classify
        TRANSIENT) retry under one bounded RetryPolicy ladder
        (trigger.{maxRestarts,backoffMs}); any successful tick resets
        the streak; FATAL errors (and an exhausted ladder) park the
        query in FAILED status with the error preserved. A tick slower
        than the interval SKIPS the missed ticks — wall-clock pacing
        never queues a backlog. The loop runs under a fresh lifecycle
        token (deadline from execution.queryDeadlineMs when set):
        `stop()`/DELETE cancels it, a deadline parks FAILED; either
        way the durable state stays at the last committed batch, so a
        restart resumes exactly-once.

        `clock`/`sleep`/`rng` are test seams (injected monotonic
        clock, pacing+backoff sleep, backoff jitter)."""
        if self._loop_thread is not None \
                and self._loop_thread.is_alive():
            raise RuntimeError("trigger loop already running")
        from .execution import lifecycle
        deadline_ms = int(self.session.conf.get(lifecycle.DEADLINE_KEY))
        self._token = lifecycle.CancelToken(
            deadline_ms=deadline_ms if deadline_ms > 0 else None)
        self._trigger.set_running(trigger_ms)
        self._live_id = _register_live(self)
        t = threading.Thread(
            target=self._trigger_loop,
            args=(float(trigger_ms) / 1e3, clock or time.monotonic,
                  sleep, rng),
            daemon=True,
            name=f"spark-tpu-stream-trigger-{self._live_id}")
        self._loop_thread = t
        try:
            t.start()
        except BaseException:
            # thread exhaustion: undo the registration or the service
            # would list a stream nothing is running
            self._trigger.finish("FAILED",
                                 "trigger thread failed to start")
            _unregister_live(self._live_id)
            self._loop_thread = None
            raise
        return self

    def _trigger_loop(self, trigger_s: float, clock, sleep_fn, rng):
        from .execution import failures, lifecycle
        from .testing import faults
        ctx_token = lifecycle.install(self._token)
        status, error = "STOPPED", None
        policy = None
        nominal = clock()  # when the CURRENT tick was scheduled
        try:
            try:
                while True:
                    skew_ms = max(0.0, (clock() - nominal) * 1e3)
                    before = self._committed_batch
                    rc0 = int(self.session.metrics.counter(
                        "streaming_reconnects").value)
                    try:
                        faults.arm(self.session.conf)
                        # chaos seam: a crash at the very top of a tick
                        faults.fire("trigger_tick")
                        lifecycle.checkpoint("trigger_tick")
                        self.process_available()
                    except (lifecycle.QueryCancelledError,
                            lifecycle.QueryDeadlineError):
                        raise  # the outer handlers own these
                    except Exception as e:  # noqa: BLE001 — supervised
                        kind = failures.classify(e)
                        if kind in (failures.FailureClass.TRANSIENT,
                                    failures.FailureClass.TIMEOUT):
                            if policy is None:
                                policy = failures.RetryPolicy(
                                    int(self.session.conf.get(
                                        TRIGGER_MAX_RESTARTS_KEY)),
                                    int(self.session.conf.get(
                                        TRIGGER_BACKOFF_KEY)),
                                    sleep=sleep_fn, rng=rng)
                            if policy.attempt_retry() is not None:
                                self._trigger.restart()
                                continue  # re-tick now, no pacing wait
                        # FATAL (or ladder exhausted): park, visibly
                        status = "FAILED"
                        error = f"{type(e).__name__}: {e}"[:400]
                        snap = self._trigger.snapshot()
                        self._record_trigger(
                            snap["ticks"] + 1, skew_ms,
                            int(self._committed_batch - before),
                            int(self.session.metrics.counter(
                                "streaming_reconnects").value - rc0),
                            restarts=snap["restarts"])
                        return
                    policy = None  # a clean tick resets the streak
                    tick = self._trigger.tick(skew_ms)
                    batches = int(self._committed_batch - before)
                    if batches > 0:
                        self._record_trigger(
                            tick, skew_ms, batches,
                            int(self.session.metrics.counter(
                                "streaming_reconnects").value - rc0))
                    # pacing: skip missed ticks, never queue them
                    now = clock()
                    k = max(1, int(math.floor((now - nominal)
                                              / trigger_s)) + 1)
                    if k > 1:
                        self._trigger.skip(k - 1)
                    nominal += k * trigger_s
                    wait = nominal - now
                    if wait > 0:
                        if sleep_fn is not None:
                            self._token.check("trigger_sleep")
                            sleep_fn(wait)
                        else:
                            lifecycle.sleep(wait)  # interruptible
            except lifecycle.QueryCancelledError:
                status, error = "STOPPED", None
            except lifecycle.QueryDeadlineError as e:
                status, error = "FAILED", \
                    f"{type(e).__name__}: {e}"[:400]
        finally:
            self._trigger.finish(status, error)
            _unregister_live(self._live_id)
            lifecycle.uninstall(ctx_token)

    def _record_trigger(self, tick: int, skew_ms: float,
                        batches_run: int, reconnects: int,
                        restarts: Optional[int] = None) -> None:
        """Post the schema-v6 `trigger` observability record (one per
        tick that ran batches, plus the parking tick of a FAILED
        query)."""
        if restarts is None:
            restarts = self._trigger.snapshot()["restarts"]
        record = {
            "tick": int(tick),
            "skew_ms": round(float(skew_ms), 3),
            "batches_run": int(batches_run),
            "restarts": int(restarts),
            "source": str(getattr(self.stream, "source_kind",
                                  "memory")),
            "reconnects": int(reconnects),
        }
        from .observability.listener import StreamingTriggerEvent
        self.session.listeners.post(
            "on_streaming_trigger",
            StreamingTriggerEvent(
                query_id=self.session._next_query_id(), ts=time.time(),
                plan=f"StreamingQuery[{self._shape()},"
                     f"{self.output_mode}]",
                record=record))

    def stop(self, timeout_s: float = 10.0) -> None:
        """Stop the trigger loop (a no-op for manual-trigger queries):
        cancel the lifecycle token — which interrupts a pacing or
        backoff sleep immediately — and join the thread BOUNDED.
        Idempotent. The durable state stays at the last committed
        batch, so a later start() or a fresh query resumes
        exactly-once."""
        t, self._loop_thread = self._loop_thread, None
        if t is None:
            return
        if self._token is not None:
            self._token.cancel()
        t.join(timeout=timeout_s)
        if t.is_alive():
            self._loop_thread = t  # keep it stoppable again
            raise RuntimeError(
                f"trigger loop failed to stop within {timeout_s}s")
        # the loop's finally normally unregisters; stay safe against a
        # thread that died before reaching it
        _unregister_live(self._live_id)

"""Structured streaming: the micro-batch execution loop.

A scaled-to-this-engine implementation of the reference's structured
streaming core (`execution/streaming/MicroBatchExecution.scala:39`,
`StreamExecution.scala:69`): a host-driven loop that

1. polls sources for their latest offsets and WRITES THE PLANNED RANGE
   to the offset log BEFORE executing (`offsetLog:219`, an
   `HDFSMetadataLog` analog — JSON files named by batch id);
2. runs the query over exactly the logged range — stateless plans
   execute the batch slice through the normal engine; streaming
   aggregations fold the slice into versioned accumulator tables (the
   `StateStore:101` role is played by the direct-aggregate tables that
   already power batch streaming);
3. commits to the commit log (`commitLog:226`) and emits to the sink.

Exactly-once = offset log ∧ commit log ∧ versioned state: on restart,
a planned-but-uncommitted batch re-runs over the SAME logged range
against the last committed state version, so replays are idempotent.

The TPU angle: each micro-batch is one jitted SPMD program over a
statically-shaped batch slice; state lives in HBM as accumulator tables
between triggers (no RocksDB tier — state is bounded by the aggregate's
padded domain, and the host checkpoint serializes it as numpy).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa

from . import functions as F  # noqa: F401  (user convenience re-export)
from .columnar import Batch
from .plan import logical as L


class MemoryStream:
    """An appendable in-memory source (the reference's `MemoryStream` —
    the deterministic test source behind StreamTest.scala:342)."""

    def __init__(self, session, schema_df: pd.DataFrame):
        self.session = session
        self._table = pa.Table.from_pandas(schema_df.iloc[0:0],
                                           preserve_index=False)
        self._batches: List[pa.Table] = []

    def add_data(self, df: pd.DataFrame) -> None:
        self._batches.append(pa.Table.from_pandas(df, preserve_index=False))

    addData = add_data

    def latest_offset(self) -> int:
        return len(self._batches)

    def slice(self, start: int, end: int) -> pa.Table:
        tables = self._batches[start:end]
        if not tables:
            return self._table
        return pa.concat_tables(tables)

    def to_df(self):
        """A DataFrame over a placeholder scan; the streaming loop swaps
        the placeholder for each micro-batch's slice (the reference's
        logical-plan rewrite in `MicroBatchExecution.runBatch:525`)."""
        from .dataframe import DataFrame
        return DataFrame(self.session, _StreamSource(self))


class _StreamSource(L.LeafPlan):
    """Logical placeholder for a streaming source."""

    def __init__(self, stream: MemoryStream):
        self.stream = stream
        self.children = ()

    def schema(self):
        from .io.sources import ArrowTableSource
        return ArrowTableSource("__stream__", self.stream._table).schema()

    def simple_string(self):
        return "StreamSource(memory)"


class _MetadataLog:
    """Numbered JSON files with atomic rename — the
    `HDFSMetadataLog`/`CheckpointFileManager` contract in miniature."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def latest(self):
        ids = [int(f) for f in os.listdir(self.path) if f.isdigit()]
        if not ids:
            return None, None
        i = max(ids)
        with open(os.path.join(self.path, str(i))) as f:
            return i, json.load(f)

    def add(self, batch_id: int, payload: dict) -> None:
        final = os.path.join(self.path, str(batch_id))
        tmp = final + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, final)


class StreamingQuery:
    """One micro-batch query (reference: StreamExecution). Trigger is
    manual (`process_available()`) — the deterministic single-step mode
    StreamTest uses; a wall-clock trigger is a loop around it."""

    def __init__(self, session, plan: L.LogicalPlan, stream: MemoryStream,
                 checkpoint_dir: str, output_mode: str = "complete"):
        if output_mode not in ("complete", "append"):
            raise ValueError(f"unsupported outputMode {output_mode!r}")
        self.session = session
        self.plan = plan
        self.stream = stream
        self.output_mode = output_mode
        self.offset_log = _MetadataLog(os.path.join(checkpoint_dir,
                                                    "offsets"))
        self.commit_log = _MetadataLog(os.path.join(checkpoint_dir,
                                                    "commits"))
        self._state_dir = os.path.join(checkpoint_dir, "state")
        os.makedirs(self._state_dir, exist_ok=True)
        self._agg = self._find_aggregate(plan)
        if self._agg is not None and output_mode == "append":
            # the reference rejects append-without-watermark for
            # aggregations at analysis time; silently re-emitting every
            # group each trigger would duplicate sink rows
            raise ValueError(
                "outputMode='append' on a streaming aggregation is not "
                "supported (no watermark support); use 'complete'")
        self._results: List[pd.DataFrame] = []
        self._tables = None      # carried aggregate state (device)
        self._prep = None
        self._recover()

    # -- plan shape ---------------------------------------------------------

    @staticmethod
    def _find_aggregate(plan: L.LogicalPlan) -> Optional[L.Aggregate]:
        """The single streaming aggregate, if any (stateless otherwise).
        Nested/multiple aggregates are out of scope, as in the
        reference's UnsupportedOperationChecker."""
        aggs: List[L.Aggregate] = []

        def walk(n):
            if isinstance(n, L.Aggregate):
                aggs.append(n)
            for c in n.children:
                walk(c)

        walk(plan)
        if len(aggs) > 1:
            raise ValueError("multiple streaming aggregates unsupported")
        return aggs[0] if aggs else None

    # -- recovery -----------------------------------------------------------

    def _recover(self) -> None:
        """Restart semantics: resume state at the last COMMITTED batch;
        a planned-but-uncommitted offset entry will re-run over its
        logged range (idempotent because state is versioned)."""
        last_commit, _ = self.commit_log.latest()
        self._committed_batch = -1 if last_commit is None else last_commit
        if self._agg is not None and last_commit is not None:
            self._load_state(last_commit)

    def _state_path(self, batch_id: int) -> str:
        return os.path.join(self._state_dir, f"v{batch_id}.npz")

    def _save_state(self, batch_id: int, tables) -> None:
        cnt, accs = tables
        flat = {"cnt": np.asarray(cnt)}
        for i, row in enumerate(accs):
            for j, a in enumerate(row):
                flat[f"acc_{i}_{j}"] = np.asarray(a)
        tmp = self._state_path(batch_id) + ".tmp.npz"
        np.savez(tmp, **flat)
        os.replace(tmp, self._state_path(batch_id))

    def _load_state(self, batch_id: int) -> None:
        self._ensure_prep()
        with np.load(self._state_path(batch_id)) as z:
            cnt = jnp.asarray(z["cnt"])
            accs = []
            i = 0
            while f"acc_{i}_0" in z:
                row = []
                j = 0
                while f"acc_{i}_{j}" in z:
                    row.append(jnp.asarray(z[f"acc_{i}_{j}"]))
                    j += 1
                accs.append(row)
                i += 1
        self._tables = (cnt, accs)

    # -- execution ----------------------------------------------------------

    def _ensure_prep(self):
        if self._prep is not None or self._agg is None:
            return
        from .io.sources import ArrowTableSource
        from .plan.planner import plan_physical
        import spark_tpu.plan.physical as P

        def swap(n):
            if isinstance(n, _StreamSource):
                return L.Scan(ArrowTableSource("__stream_probe__",
                                               self.stream._table))
            return None

        phys = plan_physical(self.plan.transform_down(swap),
                             self.session.conf)

        agg_exec = None

        def walk(n):
            nonlocal agg_exec
            if isinstance(n, P.HashAggregateExec) and agg_exec is None:
                agg_exec = n
            for c in n.children:
                walk(c)

        walk(phys)
        if agg_exec is None:
            raise ValueError("aggregate lost during planning")
        self._agg_exec = agg_exec

        def unary_path(root, target):
            """Operators from (under) `root` down to `target`, refusing
            non-unary nodes (stream-static joins are unsupported — fail
            with a named error, not an unpack crash)."""
            path = []
            node = root
            while node is not target:
                if len(node.children) != 1:
                    from .expr import AnalysisError
                    raise AnalysisError(
                        f"streaming aggregation supports a single unary "
                        f"operator chain; {type(node).__name__} "
                        f"(e.g. a stream-static join) is not supported")
                path.append(node)
                node = node.children[0]
            return path

        # operators ABOVE the aggregate (HAVING filters, projections,
        # sort/limit) re-apply to each trigger's finalized table;
        # operators BELOW replay per micro-batch slice
        self._above = unary_path(phys, agg_exec)
        chain = []
        node = agg_exec.children[0]
        while node.children:
            if len(node.children) != 1:
                from .expr import AnalysisError
                raise AnalysisError(
                    f"streaming aggregation supports a single unary "
                    f"operator chain below the aggregate; "
                    f"{type(node).__name__} is not supported")
            chain.append(node)
            node = node.children[0]
        self._chain = chain
        from .plan.physical import ExecContext
        probe = self._batch_for(self.stream.slice(0, 0))
        ctx = ExecContext(self.session.conf)
        replayed = probe
        for op in reversed(chain):
            replayed = op.compute(ctx, [replayed])
        from . import types as T
        base = agg_exec.child.schema()
        for g in agg_exec.group_exprs:
            if isinstance(g.dtype(base), T.StringType):
                # the prep is built from an empty probe slice, so
                # per-batch dictionary codes would never share an
                # encoding across triggers — unsupported, not broken
                raise ValueError(
                    "string group keys are not supported in streaming "
                    "aggregations (per-batch dictionaries have no "
                    "stable shared encoding)")
        prep = agg_exec.prepare_direct(replayed, self.session.conf)
        if prep is None:
            raise ValueError(
                "streaming aggregation requires a statically-bounded "
                "integer group domain (e.g. pmod keys)")
        self._prep = prep

        def update(tables, b, row_base):
            ctx = ExecContext(self.session.conf)
            for op in reversed(self._chain):
                b = op.compute(ctx, [b])
            # row_base = the trigger's stream offset: packed First/Last
            # positions stay globally unique across triggers (and exact
            # replays of a logged range reuse the same base, keeping
            # recovery idempotent)
            return self._agg_exec.direct_update_tables(
                tables, b, prep, self.session.conf, row_base=row_base)

        # one jitted step per trigger (no donation: a save failure must
        # leave the PRE-update tables alive for an exact replay)
        self._update = jax.jit(update)

    def _batch_for(self, table: pa.Table) -> Batch:
        return Batch.from_arrow(table)

    def process_available(self) -> None:
        """Run micro-batches until the source is drained (the
        `Trigger.AvailableNow` analog; each iteration = one batch of the
        `MicroBatchExecution` loop)."""
        while True:
            batch_id = self._committed_batch + 1
            planned_id, planned = self.offset_log.latest()
            if planned_id is not None and planned_id == batch_id:
                # planned but not committed (crash between the logs):
                # replay exactly the logged range
                start, end = planned["start"], planned["end"]
            else:
                start = planned["end"] if planned is not None else 0
                end = self.stream.latest_offset()
                if end <= start:
                    return  # drained
                self.offset_log.add(batch_id, {"start": start, "end": end})
            self._run_batch(batch_id, start, end)
            self.commit_log.add(batch_id, {"ok": True})
            self._committed_batch = batch_id
            self._prune(batch_id)

    def _prune(self, committed: int, retain: int = 2) -> None:
        """Drop state versions and log entries older than the retained
        window (the reference's minBatchesToRetain); recovery only ever
        reads the last committed version."""
        floor = committed - retain
        for log in (self.offset_log, self.commit_log):
            for f in os.listdir(log.path):
                if f.isdigit() and int(f) < floor:
                    os.remove(os.path.join(log.path, f))
        for f in os.listdir(self._state_dir):
            if f.startswith("v") and f.endswith(".npz"):
                try:
                    vid = int(f[1:-4])
                except ValueError:
                    continue
                if vid < floor:
                    os.remove(os.path.join(self._state_dir, f))

    processAllAvailable = process_available

    def _run_batch(self, batch_id: int, start: int, end: int) -> None:
        table = self.stream.slice(start, end)
        if self._agg is None:
            # stateless: swap the stream placeholder for this slice and
            # run the normal engine
            from .io.sources import ArrowTableSource

            def swap(n):
                # constant name: the compiled-stage cache keys on the
                # plan fingerprint incl. source.name, so one jitted
                # program serves every trigger
                if isinstance(n, _StreamSource):
                    return L.Scan(ArrowTableSource("__microbatch__",
                                                   table))
                return None

            from .execution.executor import QueryExecution
            out = QueryExecution(
                self.session, self.plan.transform_down(swap)).collect()
            self._results.append(out.to_pandas())
            return
        # stateful: fold the slice into carried accumulator tables
        self._ensure_prep()
        if self._tables is None:
            self._tables = self._agg_exec.direct_init_tables(self._prep)
        new_tables = self._tables
        if table.num_rows:
            b = self._batch_for(table)
            if start + b.capacity >= (1 << 30) and any(
                    a.func.uses_row_base
                    for a in self._agg_exec.agg_exprs):
                raise RuntimeError(
                    "first/last over a stream exceeds the 2^30 "
                    "packed-position bound")
            import jax.numpy as jnp
            new_tables = self._update(self._tables, b,
                                      jnp.asarray(start, jnp.int64))
        # persist BEFORE adopting: a save failure must leave the
        # pre-update tables in place so an in-process retry replays the
        # same range without double-counting
        self._save_state(batch_id, new_tables)
        self._tables = new_tables
        out = self._agg_exec.direct_finalize_tables(self._tables,
                                                    self._prep)
        from .plan.physical import ExecContext
        ctx = ExecContext(self.session.conf)
        for op in reversed(self._above):
            out = op.compute(ctx, [out])
        self._results.append(out.to_arrow().to_pandas())

    # -- sink ---------------------------------------------------------------

    def latest(self) -> Optional[pd.DataFrame]:
        """Memory sink: the latest result table (complete mode) or the
        last appended slice."""
        return self._results[-1] if self._results else None

    def results(self) -> List[pd.DataFrame]:
        return list(self._results)

    def stop(self) -> None:
        pass  # manual trigger: nothing running between calls

"""Typed query-lifecycle event stream + listener bus.

The SparkListener analog (`SparkListenerBus` / `LiveListenerBus.scala`):
the executor posts typed events at query-lifecycle boundaries and every
subscriber — the event-log writer, the Chrome-trace writer, the metrics
sinks, user listeners, tests — observes the same stream. A listener
raising can never fail a query: the bus isolates callbacks, warns, and
counts the drop (the reference logs and continues likewise).
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class QueryStartEvent:
    """Posted when execute_batch enters (once per execution, nested
    subquery executions included — query_id disambiguates)."""

    query_id: int
    ts: float
    plan: str


@dataclass
class StageCompiledEvent:
    """Posted on a compiled-stage cache MISS (the stage was actually
    jitted). `cost` carries the XLA cost/memory analysis when capture
    is on (observability.xlaCost), else None."""

    query_id: int
    ts: float
    stage_key: str
    key_hash: str
    mesh_n: int
    cost: Optional[Dict] = None


@dataclass
class StageCompletedEvent:
    """Posted after each successful stage dispatch (one per AQE
    capacity attempt; `overflow` lists the flags that forced another
    attempt, empty on the converged one)."""

    query_id: int
    ts: float
    stage_key: str
    key_hash: str
    attempt: int
    elapsed_ms: float
    metrics: Dict = field(default_factory=dict)
    overflow: List[str] = field(default_factory=list)


@dataclass
class AnalysisEvent:
    """Posted once per execution when the pre-compile static analyzer
    (spark_tpu/analysis/) ran and produced findings. `findings` is the
    event-log-serializable dict form (Finding.to_dict)."""

    query_id: int
    ts: float
    findings: List[Dict] = field(default_factory=list)


@dataclass
class FaultEvent:
    """Posted for every recovery action the failure ladder takes
    (transient retry, stage timeout, OOM rung, mesh fallback)."""

    query_id: int
    ts: float
    action: str
    error: str = ""
    site: Optional[str] = None


@dataclass
class ServiceEvent:
    """Posted by the SQL service (spark_tpu/service/) for every
    admission/lifecycle transition of a submitted query: `action` is
    one of submitted / admitted / queued / rejected / queue_timeout /
    finished / failed / evicted. `query_id` is the SERVICE query id
    (the `GET /queries/<id>` handle), not a session-internal one."""

    query_id: str
    ts: float
    action: str
    session: str = ""
    detail: str = ""


@dataclass
class ShardChunkEvent:
    """Posted by the mesh chunk drivers' per-shard telemetry
    (ShardStreamTelemetry) at each chunk-boundary flush: one record per
    (shard, chunk) with rows/bytes and the per-shard completion wait.
    The StragglerMonitor consumes this stream."""

    query_id: int
    ts: float
    chunk: int
    records: List[Dict] = field(default_factory=list)


@dataclass
class StragglerEvent:
    """Posted by the StragglerMonitor when a shard's rolling median
    per-chunk wait exceeds `spark_tpu.sql.straggler.factor` x the
    all-shard baseline (after `straggler.minChunks` samples). The
    detection half of straggler mitigation — the elastic-mesh
    rebalancer subscribes here."""

    query_id: int
    ts: float
    shard: int
    host: int
    median_ms: float
    baseline_ms: float
    chunks: int
    factor: float


@dataclass
class StreamingBatchEvent:
    """Posted by the micro-batch loop (streaming.py) once per COMMITTED
    batch: `record` is the event-log `streaming` record — batch id,
    offset range, rows in/out, state persistence kind (delta vs
    snapshot) + bytes, quarantined files, sink parts. The event-log
    listener writes it as its own (schema v4, additive) line;
    `history.streaming_summary` replays it."""

    query_id: int
    ts: float
    plan: str
    record: Dict = field(default_factory=dict)


@dataclass
class StreamingTriggerEvent:
    """Posted by the supervised trigger loop (streaming.py): one per
    tick that ran batches, plus the parking tick of a FAILED query.
    `record` is the event-log `trigger` record — tick id, wall-clock
    skew, batches run, supervisor restarts, source kind, reconnects.
    The event-log listener writes it as its own (schema v6, additive)
    line; `history.streaming_summary` folds it in."""

    query_id: int
    ts: float
    plan: str
    record: Dict = field(default_factory=dict)


@dataclass
class QueryEndEvent:
    """Posted when an execution finishes (status 'ok') or fails past
    recovery (status 'error'). `event` is the full event-log record —
    plan, phase times, metrics, spans, stage costs, fault summary."""

    query_id: int
    ts: float
    status: str
    event: Dict
    spans: List = field(default_factory=list)


#: callback names the bus will deliver (anything else is a bug)
CALLBACKS = ("on_query_start", "on_analysis", "on_stage_compiled",
             "on_stage_completed", "on_fault", "on_query_end",
             "on_service", "on_shard_records", "on_straggler",
             "on_streaming_batch", "on_streaming_trigger")


class QueryListener:
    """Subscriber base class — override any subset of the callbacks.

    The SparkListener seat: `session.add_listener(MyListener())`.
    Callbacks run synchronously on the driver thread (the engine's
    driver is single-threaded; the reference's async bus exists to
    decouple executor heartbeats, which have no analog here).
    """

    def on_query_start(self, event: QueryStartEvent) -> None:
        pass

    def on_analysis(self, event: AnalysisEvent) -> None:
        pass

    def on_stage_compiled(self, event: StageCompiledEvent) -> None:
        pass

    def on_stage_completed(self, event: StageCompletedEvent) -> None:
        pass

    def on_fault(self, event: FaultEvent) -> None:
        pass

    def on_query_end(self, event: QueryEndEvent) -> None:
        pass

    def on_service(self, event: ServiceEvent) -> None:
        pass

    def on_shard_records(self, event: ShardChunkEvent) -> None:
        pass

    def on_straggler(self, event: StragglerEvent) -> None:
        pass

    def on_streaming_batch(self, event: StreamingBatchEvent) -> None:
        pass

    def on_streaming_trigger(self,
                             event: StreamingTriggerEvent) -> None:
        pass


class ListenerBus:
    """Synchronous delivery to registered listeners, failure-isolated.

    Lock-guarded (GUARDED_BY: obs.bus): the SQL service posts from
    concurrent worker threads while tests and the service (un)register
    listeners — the listener list and the drop counter are shared
    read-modify-write state. Delivery runs OUTSIDE the lock over a
    snapshot: listeners take their own locks (straggler monitor,
    event-log writer), and holding the bus lock across them would
    invert the registry's lock-order ranking."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._listeners: List[QueryListener] = []
        #: callbacks dropped because a listener raised
        self.dropped = 0

    def register(self, listener: QueryListener) -> None:
        with self._lock:
            if listener not in self._listeners:
                self._listeners.append(listener)

    def unregister(self, listener: QueryListener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    @property
    def listeners(self) -> List[QueryListener]:
        with self._lock:
            return list(self._listeners)

    def post(self, callback: str, event) -> None:
        assert callback in CALLBACKS, callback
        # snapshot: service threads may (un)register listeners while
        # another thread's query is mid-post
        for listener in self.listeners:
            fn = getattr(listener, callback, None)
            if fn is None:
                continue
            try:
                fn(event)
            except Exception as e:  # noqa: BLE001 — never fail the query
                with self._lock:
                    self.dropped += 1
                warnings.warn(
                    f"query listener {type(listener).__name__}.{callback} "
                    f"raised (dropped): {type(e).__name__}: {e}")

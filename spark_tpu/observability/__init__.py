"""Observability layer: listener bus, spans, XLA cost accounting, metrics.

The reference splits observability across a typed listener event stream
(`SparkListener` / `EventLoggingListener.scala`), per-operator
`SQLMetrics`, the SQL UI status store (`SQLAppStatusListener`), and the
codahale-backed `MetricsSystem` with pluggable sinks. This package is
the engine-sized analog, organized the same way:

- ``listener``: the typed event stream. ``QueryListener`` is the
  SparkListener seat (on_query_start / on_stage_compiled /
  on_stage_completed / on_fault / on_query_end); ``ListenerBus``
  delivers events so the event log, the Chrome-trace writer, the
  metrics sinks, and tests are all just subscribers.
- ``spans``: per-stage spans (analysis -> optimize -> plan -> compile
  -> ingest -> dispatch -> AQE-replan -> retry) with a wall-clock
  anchor, exportable as Chrome trace-event JSON (Perfetto-loadable).
- ``xla_cost``: XLA cost/HBM accounting off the AOT API
  (``compiled.cost_analysis()`` / ``memory_analysis()``) — flops,
  bytes accessed, argument/output/temp sizes and the derived peak-HBM
  demand per compiled stage.
- ``metrics``: process metrics registry (counters/gauges/timers/
  log-bucketed latency histograms) with JSONL + Prometheus
  text-exposition sinks, plus the registered traced-metric name
  prefixes ``scripts/metrics_lint.py`` enforces.
- ``sinks``: the built-in bus subscribers (event-log writer with
  rotation, Chrome-trace writer, metrics-sink updater) a session
  installs at construction.
- ``status_store``: the ``AppStatusStore`` seat — bounded, typed,
  listener-bus-fed rolling view of engine health (in-flight queries,
  queue depth, lease occupancy, cache hit rates, latency percentiles,
  SLO burn), heartbeat-sampled into ring time-series and served by
  the SQL service's ``GET /status`` endpoints.
- ``flight_recorder``: always-on bounded rings of recent events per
  subsystem; dumps a self-contained diagnostic bundle (rings, plans,
  conf, metrics, thread stacks, event-log tail) on FATAL / OOM-ladder
  exhaustion / non-convergent recovery or on demand.
"""

from .listener import (AnalysisEvent, FaultEvent, ListenerBus,
                       QueryEndEvent, QueryListener, QueryStartEvent,
                       ServiceEvent, ShardChunkEvent, StageCompiledEvent,
                       StageCompletedEvent, StragglerEvent)
from .flight_recorder import FlightRecorder
from .metrics import (METRIC_PREFIXES, Histogram, MetricsRegistry,
                      is_registered_metric)
from .spans import (ShardStreamTelemetry, Span, SpanRecorder,
                    current_shard_telemetry, to_chrome_trace,
                    use_shard_telemetry)
from .status_store import StatusStore
from .straggler import StragglerMonitor

__all__ = [
    "AnalysisEvent", "FaultEvent", "FlightRecorder", "Histogram",
    "ListenerBus", "MetricsRegistry", "METRIC_PREFIXES",
    "QueryEndEvent", "QueryListener", "QueryStartEvent", "ServiceEvent",
    "ShardChunkEvent", "ShardStreamTelemetry", "Span", "SpanRecorder",
    "StageCompiledEvent", "StageCompletedEvent", "StatusStore",
    "StragglerEvent", "StragglerMonitor", "current_shard_telemetry",
    "is_registered_metric", "to_chrome_trace", "use_shard_telemetry",
]

"""Per-stage spans with Chrome-trace export.

Each QueryExecution records named spans over its lifecycle phases
(analysis -> optimize -> plan -> compile -> ingest -> dispatch ->
AQE-replan -> retry). Spans use `time.perf_counter` internally (cheap,
monotonic) with a wall-clock anchor captured at recorder creation, so
export maps to epoch microseconds — the Chrome trace-event "X"
(complete-event) format, loadable in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import contextlib
import os
import time
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Span:
    name: str
    t0: float            # perf_counter seconds
    t1: float
    attrs: Dict = field(default_factory=dict)

    @property
    def dur_ms(self) -> float:
        return (self.t1 - self.t0) * 1e3


class SpanRecorder:
    """Bounded span list for one QueryExecution (query_id = trace tid)."""

    def __init__(self, query_id: int, max_spans: int = 1000,
                 max_shard_records: int = 4096):
        self.query_id = query_id
        self.max_spans = max_spans
        self.spans: List[Span] = []
        #: spans dropped past the bound (surfaced so truncation is
        #: visible, never silent)
        self.dropped = 0
        #: per-shard telemetry records (mesh runs): dicts with shard,
        #: host, chunk, phase, rows, bytes, t0_ms, dur_ms, wait_ms,
        #: source — the event log's `shards` field (schema v3)
        self.shard_records: List[Dict] = []
        self.max_shard_records = max_shard_records
        self.shard_dropped = 0
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()

    def add_shard_records(self, records: List[Dict]) -> None:
        room = self.max_shard_records - len(self.shard_records)
        if room < len(records):
            self.shard_dropped += len(records) - max(room, 0)
            records = records[:max(room, 0)]
        self.shard_records.extend(records)

    def rel_ms(self, t_perf: float) -> float:
        """Perf-counter time as milliseconds since the recorder anchor
        (the shared origin of span t0_ms and shard-record t0_ms)."""
        return round((t_perf - self._anchor_perf) * 1e3, 3)

    def record(self, name: str, t0: float, t1: Optional[float] = None,
               **attrs) -> None:
        if len(self.spans) >= self.max_spans:
            self.dropped += 1
            return
        self.spans.append(Span(name, t0, t1 if t1 is not None else t0,
                               attrs))

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, t0, time.perf_counter(), **attrs)

    def mark(self, name: str, **attrs) -> None:
        """Zero-duration span (exported as a Chrome instant event)."""
        t = time.perf_counter()
        self.record(name, t, t, **attrs)

    def wall(self, t_perf: float) -> float:
        """Map a perf_counter time onto the epoch clock."""
        return self._anchor_wall + (t_perf - self._anchor_perf)

    def to_dicts(self) -> List[Dict]:
        """Event-log form: relative start + duration in milliseconds."""
        out = []
        for s in self.spans:
            d = {"name": s.name,
                 "t0_ms": round((s.t0 - self._anchor_perf) * 1e3, 3),
                 "dur_ms": round(s.dur_ms, 3)}
            if s.attrs:
                d["attrs"] = s.attrs
            out.append(d)
        return out


def to_chrome_trace(recorder: SpanRecorder,
                    pid: Optional[int] = None) -> Dict:
    """Chrome trace-event JSON ({"traceEvents": [...]}) from a
    recorder's spans. Zero-duration spans export as instant events
    (ph "i"), the rest as complete events (ph "X")."""
    pid = pid if pid is not None else os.getpid()
    events = []
    for s in recorder.spans:
        ts_us = recorder.wall(s.t0) * 1e6
        ev = {"name": s.name, "cat": "spark_tpu", "pid": pid,
              "tid": recorder.query_id, "ts": ts_us}
        dur_us = (s.t1 - s.t0) * 1e6
        if dur_us <= 0:
            ev["ph"] = "i"
            ev["s"] = "t"  # thread-scoped instant
        else:
            ev["ph"] = "X"
            ev["dur"] = dur_us
        if s.attrs:
            ev["args"] = {k: v for k, v in s.attrs.items()}
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Per-shard telemetry (mesh chunk drivers)
# ---------------------------------------------------------------------------

#: the executor installs the current execution's telemetry here around
#: the streaming-materialization phase; the mesh chunk drivers read it
#: (the same context-threading pattern the arbiter's enter_query uses,
#: so driver signatures — which tests monkeypatch — stay unchanged)
_SHARD_TELEMETRY: ContextVar[Optional["ShardStreamTelemetry"]] = \
    ContextVar("spark_tpu_shard_telemetry", default=None)


def current_shard_telemetry() -> Optional["ShardStreamTelemetry"]:
    return _SHARD_TELEMETRY.get()


@contextlib.contextmanager
def use_shard_telemetry(telem: Optional["ShardStreamTelemetry"]):
    token = _SHARD_TELEMETRY.set(telem)
    try:
        yield telem
    finally:
        try:
            if telem is not None:
                telem.finish()
        finally:
            # the reset must survive a raising finish: a stale context
            # var would leak this query's telemetry into the next
            _SHARD_TELEMETRY.reset(token)


class ShardStreamTelemetry:
    """Per-shard/per-chunk flight recorder for the mesh chunk drivers.

    The hot path stays sync-free: each chunk dispatch hands over the
    step's per-shard live-row array (a device-resident [n] int64,
    sharded on the data axis — appending it costs no transfer), and the
    PREVIOUS chunk's buffer is flushed at the next chunk boundary,
    where the driver is already doing host work (Parquet decode of the
    next chunk). A flush walks the array's addressable shards in mesh
    order, timing the block-until-ready wait it pays on each — the
    per-shard completion profile: a straggling device inflates its own
    wait window while shards that kept up read back instantly — then
    pulls the row counts in one device_get and emits one record per
    (shard, chunk) plus a host-side ingest record. Records land on the
    SpanRecorder (event-log `shards`, schema v3) and are posted on the
    listener bus (`on_shard_records`) for the StragglerMonitor.

    The `shard_chunk` chaos seam fires once per (chunk, shard) inside
    the timed wait window, so an injected `slow` fault models exactly
    one straggling shard (hit ordinal = chunk * n_shards + shard + 1).
    """

    def __init__(self, recorder: SpanRecorder, mesh, query_id: int,
                 bus=None, source: str = "stream_mesh"):
        from ..parallel.mesh import shard_hosts
        self.recorder = recorder
        self.query_id = query_id
        self.bus = bus
        self.source = source
        self.hosts = shard_hosts(mesh)
        self.n = len(self.hosts)
        self._dev_pos = {d.id: i for i, d in enumerate(mesh.devices.flat)}
        #: (chunk, shard_rows device array, row_width, t_dispatch0)
        self._pending: Optional[tuple] = None

    # -- driver-facing hooks (hot path: no device sync) ---------------------

    def chunk_ingested(self, chunk: int, rows: int, nbytes: int,
                       t0: float, t1: float) -> None:
        """Host-side decode of one chunk (the ingest phase): recorded
        directly — it is already host wall-clock, nothing to flush."""
        import jax
        self.recorder.add_shard_records([{
            "shard": None, "host": int(jax.process_index()),
            "chunk": int(chunk), "phase": "ingest", "rows": int(rows),
            "bytes": int(nbytes), "t0_ms": self.recorder.rel_ms(t0),
            "dur_ms": round((t1 - t0) * 1e3, 3), "source": self.source}])

    def chunk_dispatched(self, chunk: int, shard_rows, row_width: int,
                         t_dispatch: float) -> None:
        """Buffer one chunk's per-shard live-row array (device-side;
        no sync) after flushing the previous chunk's buffer."""
        if self._pending is not None and self._pending[0] == int(chunk):
            # retried attempt of the SAME chunk (ChunkRetrier replay):
            # discard the failed attempt's buffer — flushing it would
            # emit duplicate (shard, chunk) records (double-counting
            # row totals, skewing straggler medians) off an array the
            # failed dispatch may have poisoned
            self._pending = None
        self._flush_pending()
        self._pending = (int(chunk), shard_rows, int(row_width),
                         t_dispatch)

    def finish(self) -> None:
        self._flush_pending()

    # -- flush (chunk boundary / stream end) --------------------------------

    def _shard_pieces(self, arr) -> List:
        """The array's addressable shards in mesh-axis order (None
        placeholders for shards this process cannot see — multi-host)."""
        pieces = [None] * self.n
        for s in getattr(arr, "addressable_shards", ()) or ():
            i = self._dev_pos.get(getattr(s.device, "id", None))
            if i is not None:
                pieces[i] = s.data
        return pieces

    def _flush_pending(self) -> None:
        """Flush the buffered chunk into records. The WHOLE flush is
        failure-isolated: an async device error surfacing through
        block_until_ready here must neither fail the query nor mask
        the stream's own exception (finish() runs on unwind paths) —
        the dispatch that owns the error re-raises it at the engine's
        own sync point, where the failure ladder classifies it. A
        raising fault injected at the shard_chunk seam is likewise
        swallowed: the seam models a SLOW shard, not a dead one."""
        if self._pending is None:
            return
        try:
            self._flush_pending_inner()
        except Exception as e:  # noqa: BLE001 — never fail the query
            import warnings
            warnings.warn(f"per-shard telemetry flush failed (records "
                          f"dropped): {type(e).__name__}: {e}")

    def _flush_pending_inner(self) -> None:
        import jax
        from ..testing import faults
        chunk, arr, row_width, t0 = self._pending
        self._pending = None
        pieces = self._shard_pieces(arr)
        waits = []
        for i in range(self.n):
            w0 = time.perf_counter()
            # chaos seam INSIDE the timed window: `slow` on hit
            # chunk*n + shard + 1 models that one shard straggling
            faults.fire("shard_chunk")
            if pieces[i] is not None:
                jax.block_until_ready(pieces[i])
            waits.append((time.perf_counter() - w0) * 1e3)
        t_done = time.perf_counter()
        # read each shard's count from its ADDRESSABLE piece — a
        # device_get of the global array raises on a multi-host mesh
        # (non-addressable devices). Shards owned by other processes
        # get no record HERE: every host runs this same driver and
        # records its own shards, so the fleet's logs union to full
        # coverage instead of each host fabricating remote waits.
        rows = [None if pieces[i] is None
                else int(jax.device_get(pieces[i]).reshape(-1)[0])
                for i in range(self.n)]
        records = [{
            "shard": i, "host": self.hosts[i], "chunk": chunk,
            "phase": "compute", "rows": rows[i],
            "bytes": rows[i] * row_width,
            "t0_ms": self.recorder.rel_ms(t0),
            "dur_ms": round((t_done - t0) * 1e3, 3),
            "wait_ms": round(waits[i], 3), "source": self.source,
        } for i in range(self.n) if rows[i] is not None]
        self.recorder.add_shard_records(records)
        if self.bus is not None:
            from .listener import ShardChunkEvent
            self.bus.post("on_shard_records", ShardChunkEvent(
                query_id=self.query_id, ts=time.time(), chunk=chunk,
                records=records))

"""Per-stage spans with Chrome-trace export.

Each QueryExecution records named spans over its lifecycle phases
(analysis -> optimize -> plan -> compile -> ingest -> dispatch ->
AQE-replan -> retry). Spans use `time.perf_counter` internally (cheap,
monotonic) with a wall-clock anchor captured at recorder creation, so
export maps to epoch microseconds — the Chrome trace-event "X"
(complete-event) format, loadable in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import contextlib
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Span:
    name: str
    t0: float            # perf_counter seconds
    t1: float
    attrs: Dict = field(default_factory=dict)

    @property
    def dur_ms(self) -> float:
        return (self.t1 - self.t0) * 1e3


class SpanRecorder:
    """Bounded span list for one QueryExecution (query_id = trace tid)."""

    def __init__(self, query_id: int, max_spans: int = 1000):
        self.query_id = query_id
        self.max_spans = max_spans
        self.spans: List[Span] = []
        #: spans dropped past the bound (surfaced so truncation is
        #: visible, never silent)
        self.dropped = 0
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()

    def record(self, name: str, t0: float, t1: Optional[float] = None,
               **attrs) -> None:
        if len(self.spans) >= self.max_spans:
            self.dropped += 1
            return
        self.spans.append(Span(name, t0, t1 if t1 is not None else t0,
                               attrs))

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, t0, time.perf_counter(), **attrs)

    def mark(self, name: str, **attrs) -> None:
        """Zero-duration span (exported as a Chrome instant event)."""
        t = time.perf_counter()
        self.record(name, t, t, **attrs)

    def wall(self, t_perf: float) -> float:
        """Map a perf_counter time onto the epoch clock."""
        return self._anchor_wall + (t_perf - self._anchor_perf)

    def to_dicts(self) -> List[Dict]:
        """Event-log form: relative start + duration in milliseconds."""
        out = []
        for s in self.spans:
            d = {"name": s.name,
                 "t0_ms": round((s.t0 - self._anchor_perf) * 1e3, 3),
                 "dur_ms": round(s.dur_ms, 3)}
            if s.attrs:
                d["attrs"] = s.attrs
            out.append(d)
        return out


def to_chrome_trace(recorder: SpanRecorder,
                    pid: Optional[int] = None) -> Dict:
    """Chrome trace-event JSON ({"traceEvents": [...]}) from a
    recorder's spans. Zero-duration spans export as instant events
    (ph "i"), the rest as complete events (ph "X")."""
    pid = pid if pid is not None else os.getpid()
    events = []
    for s in recorder.spans:
        ts_us = recorder.wall(s.t0) * 1e6
        ev = {"name": s.name, "cat": "spark_tpu", "pid": pid,
              "tid": recorder.query_id, "ts": ts_us}
        dur_us = (s.t1 - s.t0) * 1e6
        if dur_us <= 0:
            ev["ph"] = "i"
            ev["s"] = "t"  # thread-scoped instant
        else:
            ev["ph"] = "X"
            ev["dur"] = dur_us
        if s.attrs:
            ev["args"] = {k: v for k, v in s.attrs.items()}
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}

"""XLA cost/HBM accounting off the AOT compilation API.

`jax.jit(f).lower(args).compile()` yields a Compiled whose
`cost_analysis()` (flops, bytes accessed) and `memory_analysis()`
(argument/output/temp/generated-code sizes) expose what XLA actually
allocated — the measured side of the HBM story the OOM degradation
ladder (execution/failures.py) reacts to. `peak_hbm_bytes` is the
derived per-stage demand: arguments + outputs + temps + aliases.

Everything here is best-effort: a backend that cannot answer (some
cost analyses are unimplemented per-platform) degrades to an `error`
field, never an exception — observability must not fail a query.

Capture COSTS A SECOND COMPILE of the stage (the jit call path and the
AOT path do not share an executable in-process), so the executor gates
it on `spark_tpu.sql.observability.xlaCost` and memoizes per stage key.
"""

from __future__ import annotations

from typing import Dict, Optional

#: cost_analysis keys -> event field names
_COST_FIELDS = {"flops": "flops",
                "transcendentals": "transcendentals",
                "bytes accessed": "bytes_accessed"}

_MEM_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
               "temp_size_in_bytes", "alias_size_in_bytes",
               "generated_code_size_in_bytes")


def _first_dict(obj):
    """cost_analysis() returns a dict (new jax) or a list of per-
    computation dicts (jax<=0.4.x) — normalize to one dict."""
    if isinstance(obj, dict):
        return obj
    if isinstance(obj, (list, tuple)) and obj and isinstance(obj[0], dict):
        return obj[0]
    return None


def analyze_compiled(compiled) -> Dict:
    """Flatten a Compiled's cost + memory analysis into event fields."""
    out: Dict = {}
    try:
        cost = _first_dict(compiled.cost_analysis())
        if cost:
            for key, name in _COST_FIELDS.items():
                if key in cost:
                    out[name] = int(cost[key])
    except Exception as e:  # noqa: BLE001 — per-platform unimplemented
        out["cost_error"] = f"{type(e).__name__}: {e}"[:160]
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            peak = 0
            for f in _MEM_FIELDS:
                v = getattr(mem, f, None)
                if v is None:
                    continue
                out[f.replace("_size_in_bytes", "_bytes")] = int(v)
                if f != "generated_code_size_in_bytes":
                    peak += int(v)
            out["peak_hbm_bytes"] = peak
    except Exception as e:  # noqa: BLE001
        out["memory_error"] = f"{type(e).__name__}: {e}"[:160]
    return out


def analyze_jit(fn, args) -> Dict:
    """Lower + compile a jitted callable for analysis only. The caller
    is responsible for fault-injection suppression (lowering re-traces
    the stage, which would double-fire trace-time chaos sites)."""
    try:
        compiled = fn.lower(*args).compile()
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}
    return analyze_compiled(compiled)


def device_hbm_capacity() -> Optional[int]:
    """Per-device memory capacity in bytes (None when the backend does
    not report it — CPU usually does not)."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001
        return None
    if not stats:
        return None
    for key in ("bytes_limit", "bytes_reservable_limit"):
        if key in stats:
            return int(stats[key])
    return None

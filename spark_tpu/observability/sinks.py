"""Built-in listener-bus subscribers: event log, Chrome trace, metrics.

Installed by the session at construction; each checks conf AT EVENT
TIME, so flipping `eventLog.dir` / `trace.dir` / `metrics.sink`
mid-session takes effect on the next query (the tests' idiom). Every
subscriber is write-only observability: failures warn and the query
proceeds (the reference's EventLoggingListener logs and continues).
"""

from __future__ import annotations

import json
import os
import re
import warnings

from .listener import (QueryEndEvent, QueryListener,
                       StreamingBatchEvent, StreamingTriggerEvent)
from .spans import to_chrome_trace

# v3: per-shard telemetry (`shards` records + `shards_dropped`), the
# runtime-annotated `plan_tree`, and `predictions` (analyzer
# self-grading). v4: the per-batch `streaming` record (micro-batch
# lifecycle: offsets, delta-vs-snapshot state bytes, quarantines).
# v5: the per-query `udf` record (lane mode, Arrow batch/row totals,
# exec ms, worker restarts). v6: the per-tick `trigger` record from
# the supervised streaming trigger loop (tick id, skew, batches run,
# supervisor restarts, source kind, reconnects). v7: the per-query
# `rule_trace` record (per-(batch, rule) optimizer application
# counters + optional before/after tree diffs from
# analysis/plan_integrity.py). Purely additive — older logs replay
# unchanged (scripts/events_tool.py validates every published
# version).
EVENT_LOG_SCHEMA_VERSION = 7


def json_default(o):
    """`json.dumps(default=)` hook covering the scalar types that leak
    into event dicts: numpy/JAX scalars and 0-d arrays, numpy arrays,
    sets. Anything else degrades to repr — an event line must never
    fail to serialize."""
    item = getattr(o, "item", None)
    if item is not None and getattr(o, "shape", None) in ((), None):
        try:
            return item()
        except Exception:  # noqa: BLE001
            pass
    tolist = getattr(o, "tolist", None)
    if tolist is not None:
        try:
            return tolist()
        except Exception:  # noqa: BLE001
            pass
    if isinstance(o, (set, frozenset)):
        return sorted(o)
    return repr(o)


class EventLogListener(QueryListener):
    """Appends one JSON line per query execution to
    `<eventLog.dir>/app-<app_id>.jsonl` (the EventLoggingListener.scala
    seat). `app_id` is session-unique (pid + random token): a bare pid
    collides across reruns on the same machine.

    Rotation: when `spark_tpu.sql.eventLog.maxBytes` > 0 and the live
    file has reached it, the live file rolls to `app-<app_id>.N.jsonl`
    (N monotonically increasing) and a fresh live file starts —
    `history.read_event_log` replays rolled files in N order, live
    file last."""

    #: built-in subscribers don't force event construction on their
    #: own (executor._events_enabled ignores them); conf does
    _builtin = True

    DIR_KEY = "spark_tpu.sql.eventLog.dir"
    MAX_BYTES_KEY = "spark_tpu.sql.eventLog.maxBytes"

    def __init__(self, session):
        import threading
        self._session = session
        #: serializes roll+append: concurrent query-end events from
        #: service worker threads must not interleave half-written
        #: JSON lines or double-roll the live file
        self._write_lock = threading.Lock()

    def _roll(self, log_dir: str, base: str, max_bytes: int) -> None:
        try:
            size = os.path.getsize(base)
        except OSError:
            return
        if size < max_bytes:
            return
        rx = re.compile(
            re.escape(f"app-{self._session.app_id}.") + r"(\d+)\.jsonl$")
        n = 0
        for name in os.listdir(log_dir):
            m = rx.match(name)
            if m:
                n = max(n, int(m.group(1)))
        os.replace(base, os.path.join(
            log_dir, f"app-{self._session.app_id}.{n + 1}.jsonl"))

    def on_query_end(self, event: QueryEndEvent) -> None:
        log_dir = str(self._session.conf.get(self.DIR_KEY))
        if not log_dir:
            return
        try:
            with self._write_lock:
                os.makedirs(log_dir, exist_ok=True)
                base = os.path.join(log_dir,
                                    f"app-{self._session.app_id}.jsonl")
                max_bytes = int(self._session.conf.get(self.MAX_BYTES_KEY))
                if max_bytes > 0 and os.path.exists(base):
                    self._roll(log_dir, base, max_bytes)
                line = json.dumps(event.event, default=json_default)
                with open(base, "a") as f:
                    f.write(line + "\n")
        except (OSError, TypeError, ValueError) as e:
            # never fail a completed query over observability I/O
            warnings.warn(f"event log write failed: {e}")

    def on_streaming_batch(self, event: StreamingBatchEvent) -> None:
        """One (schema v4) line per committed micro-batch: the
        `streaming` record next to the regular per-execution lines, so
        `history.streaming_summary` replays batch lifecycle from the
        same log."""
        log_dir = str(self._session.conf.get(self.DIR_KEY))
        if not log_dir:
            return
        line_event = {
            "ts": event.ts, "query_id": event.query_id, "status": "ok",
            "plan": event.plan,
            "schema_version": EVENT_LOG_SCHEMA_VERSION,
            "streaming": event.record,
        }
        self.on_query_end(QueryEndEvent(
            query_id=event.query_id, ts=event.ts, status="ok",
            event=line_event))

    def on_streaming_trigger(self,
                             event: StreamingTriggerEvent) -> None:
        """One (schema v6) line per trigger-loop tick that ran
        batches (plus the parking tick of a FAILED query): the
        `trigger` record — unattended-operation lifecycle next to the
        per-batch `streaming` lines."""
        log_dir = str(self._session.conf.get(self.DIR_KEY))
        if not log_dir:
            return
        line_event = {
            "ts": event.ts, "query_id": event.query_id, "status": "ok",
            "plan": event.plan,
            "schema_version": EVENT_LOG_SCHEMA_VERSION,
            "trigger": event.record,
        }
        self.on_query_end(QueryEndEvent(
            query_id=event.query_id, ts=event.ts, status="ok",
            event=line_event))


class ChromeTraceListener(QueryListener):
    """Writes `<trace.dir>/query-<app_id>-<id>.trace.json` per
    execution when `spark_tpu.sql.trace.dir` is set — Chrome
    trace-event JSON, load in Perfetto / chrome://tracing.
    Re-executing the same QueryExecution (bench warmups) rewrites the
    file with the accumulated spans."""

    _builtin = True

    DIR_KEY = "spark_tpu.sql.trace.dir"

    def __init__(self, session):
        self._session = session

    def on_query_end(self, event: QueryEndEvent) -> None:
        trace_dir = str(self._session.conf.get(self.DIR_KEY))
        if not trace_dir or event.spans is None:
            return
        try:
            os.makedirs(trace_dir, exist_ok=True)
            # app_id in the name: query ids restart at 1 per session,
            # so two sessions sharing trace.dir must not clobber
            path = os.path.join(
                trace_dir,
                f"query-{self._session.app_id}"
                f"-{event.query_id:05d}.trace.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(to_chrome_trace(event.spans), f,
                          default=json_default)
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError) as e:
            warnings.warn(f"chrome trace write failed: {e}")


class MetricsSinkListener(QueryListener):
    """Folds each execution's observables into the session metrics
    registry and flushes the configured sinks — engine-wide counters
    (queries, compile cache, device cache, shuffle bytes, runtime
    filters, faults) live here, per-operator traced metrics stay in
    the event log."""

    _builtin = True

    #: SLO knob: 0 disables burn accounting
    SLO_KEY = "spark_tpu.service.slo.latencyMs"
    STATUS_KEY = "spark_tpu.sql.status.enabled"

    def __init__(self, session):
        self._session = session

    def on_query_end(self, event: QueryEndEvent) -> None:
        m = self._session.metrics
        m.counter("queries_total").inc()
        if event.status not in ("ok", "cancelled", "deadline_exceeded"):
            # lifecycle stops are not failures: they carry their own
            # query_cancelled / query_deadline_exceeded counters
            m.counter("queries_failed").inc()
        ev = event.event or {}
        phases = ev.get("phase_times_s") or {}
        if "execution" in phases:
            m.timer("query_execution").observe(float(phases["execution"]))
        metrics = ev.get("metrics") or {}
        for prefix, counter in (("exch_bytes_", "shuffle_bytes"),
                                ("exch_rows_", "shuffle_rows"),
                                ("rtf_tested_", "rtf_tested"),
                                ("rtf_pruned_", "rtf_pruned")):
            total = sum(int(v) for k, v in metrics.items()
                        if k.startswith(prefix))
            if total:
                m.counter(counter).inc(total)
        fault_summary = ev.get("fault_summary") or {}
        for action, count in fault_summary.items():
            # recovery-ACTION counts only: "events" is a record list
            # and retry_backoff_ms is a duration, not a count
            if action in ("events", "retry_backoff_ms"):
                continue
            if isinstance(count, (int, float)):
                m.counter(f"fault_{action}").inc(int(count))
        backoff_ms = fault_summary.get("retry_backoff_ms")
        if backoff_ms:
            m.timer("fault_retry_backoff").observe(
                float(backoff_ms) / 1e3)
        # device-cache state (pull model: the cache is process-global)
        try:
            from ..io.device_cache import CACHE
            for name, value in CACHE.stats().items():
                m.gauge(f"device_cache_{name}").set(value)
        except Exception:  # noqa: BLE001 — gauges are best-effort
            pass
        self._observe_latency(event, phases)
        m.flush(self._session.conf)

    def _observe_latency(self, event: QueryEndEvent, phases) -> None:
        """Log-bucketed latency histograms + SLO burn counters (the
        AppStatusStore's taskTime/SQL-metrics percentile seat):
        end-to-end and per-phase distributions, a per-query-class
        distribution keyed by the plan's root operator, and — when
        `service.slo.latencyMs` > 0 — attainment counters for the
        `/status` burn-rate line. Conf-gated at event time on
        `sql.status.enabled` (histograms off ⇒ zero cost here)."""
        if not phases:
            return  # streaming/trigger lines carry no phase data
        if not bool(self._session.conf.get(self.STATUS_KEY)):
            return
        m = self._session.metrics
        e2e_ms = sum(float(v) for v in phases.values()) * 1e3
        m.histogram("status_latency_ms").observe(e2e_ms)
        for phase, secs in phases.items():
            m.histogram(f"status_phase_ms_{phase}").observe(
                float(secs) * 1e3)
        cls = _query_class(event.event.get("plan"))
        if cls:
            m.histogram(f"status_class_ms_{cls}").observe(e2e_ms)
        target_ms = int(self._session.conf.get(self.SLO_KEY))
        if target_ms > 0:
            m.counter("slo_queries_total").inc()
            if e2e_ms > target_ms:
                m.counter("slo_burned_total").inc()
                m.counter("slo_burn_ms_total").inc(
                    int(e2e_ms - target_ms))

    def on_streaming_batch(self, event: StreamingBatchEvent) -> None:
        # the streaming_* counters are incremented at the source
        # (StreamingQuery / StateStore); per-batch flush keeps the
        # exposition file current for long-running streams that never
        # execute a regular (query-end-posting) batch query
        self._session.metrics.flush(self._session.conf)

    def on_streaming_trigger(self,
                             event: StreamingTriggerEvent) -> None:
        # same rationale: an unattended stream's reconnect/spill
        # counters must reach the exposition file between query ends
        self._session.metrics.flush(self._session.conf)


_CLASS_TOKEN = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _query_class(plan) -> str:
    """Query-class label for per-class latency histograms: the plan's
    root operator name (first identifier of the plan string — stable
    across literal/column differences, bounded cardinality: one class
    per operator type, not per query)."""
    if not plan:
        return ""
    m = _CLASS_TOKEN.search(str(plan)[:80])
    return m.group(0)[:24].lower() if m else ""


def install_default_listeners(session) -> None:
    """Register the built-in subscribers on a session's bus (order
    matters only for determinism: event log, trace, metrics, flight
    recorder, straggler monitor, elastic rebalancer — the rebalancer
    AFTER the monitor that feeds it)."""
    from ..parallel.elastic import ElasticRebalancer
    from .flight_recorder import FlightRecorder
    from .straggler import StragglerMonitor
    session.listeners.register(EventLogListener(session))
    session.listeners.register(ChromeTraceListener(session))
    session.listeners.register(MetricsSinkListener(session))
    session.listeners.register(FlightRecorder(session))
    session.listeners.register(StragglerMonitor(session))
    session.listeners.register(ElasticRebalancer())


def make_app_id() -> str:
    """Session-unique event-log identity: pid alone collides across
    reruns (satellite fix), so suffix a random token."""
    import uuid
    return f"{os.getpid()}-{uuid.uuid4().hex[:8]}"

"""Straggler detection over the per-shard telemetry stream.

The speculation seat (`TaskSetManager.checkSpeculatableTasks`, SURVEY
section 2.5), sized to gang SPMD: there are no independent task
attempts to re-launch — a slow shard stalls every chunk of the gang —
so the monitor's job is DETECTION: identify which mesh position (and
host) is consistently slow so the elastic-mesh layer can rebalance
chunk ranges away from it (the ROADMAP follow-on), and so operators
see the flag live (`straggler_flagged` counter, `on_straggler` event)
instead of diagnosing a 3x-slow query from wall-clock alone.

Signal: the per-shard completion wait (`wait_ms`) the mesh chunk
drivers' telemetry measures at each chunk boundary
(ShardStreamTelemetry) — walking the per-shard output pieces in mesh
order, a straggling device inflates its own block-until-ready window
while shards that kept up read back instantly. The monitor keeps a
rolling window of waits per (query, shard) and flags a shard once

    samples >= spark_tpu.sql.straggler.minChunks
    and median(shard) >  factor * median(all shards' medians)
    and median(shard) >= straggler.minLatencyMs   (noise floor)

Each (query, shard) flags at most once. Detection is conf-read at
event time (the sinks idiom), costs a few comparisons per chunk, and
— like every listener — can never fail a query (the bus isolates it).
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Set, Tuple

from .listener import QueryListener, ShardChunkEvent, StragglerEvent

FACTOR_KEY = "spark_tpu.sql.straggler.factor"
MIN_CHUNKS_KEY = "spark_tpu.sql.straggler.minChunks"
MIN_LATENCY_KEY = "spark_tpu.sql.straggler.minLatencyMs"

#: rolling window of per-chunk waits kept per (query, shard) — medians
#: over a bounded recent window track a shard that turns slow mid-query
WINDOW = 32

#: completed-query flag sets retained for report() (bounded)
_REPORT_BOUND = 64

#: queries tracked live at once. on_query_end is the precise cleanup,
#: but it only fires when the executor observes events — with
#: shardSpans=on and NO other observability output, on_shard_records
#: still streams, so the live maps must self-bound (oldest query
#: evicted) or a long-lived session leaks one entry per mesh query.
_LIVE_BOUND = 16


def evaluate_waits(waits_by_shard: Dict[int, List[float]],
                   factor: float, min_chunks: int, floor_ms: float
                   ) -> Tuple[Dict[int, float], Optional[float],
                              Set[int]]:
    """THE detection rule, as one pure function — (medians, baseline,
    flagged shards) over already-window-trimmed per-shard waits.
    Shared by the live monitor's _evaluate and the offline
    history.straggler_report so the two verdicts cannot drift:

    - a shard is `ready` once it has min_chunks samples; only ready
      shards feed the baseline or can be flagged;
    - baseline = median of ready shards' medians (None when fewer
      than two shards are ready — no baseline, no flags);
    - flag when median > factor * baseline and median >= floor_ms.
    """
    medians = {s: statistics.median(w)
               for s, w in waits_by_shard.items() if w}
    ready = {s: m for s, m in medians.items()
             if len(waits_by_shard[s]) >= min_chunks}
    baseline = statistics.median(sorted(ready.values())) \
        if len(ready) >= 2 else None
    flagged: Set[int] = set()
    if baseline is not None and factor > 0:
        for s, m in ready.items():
            if m >= floor_ms and m > factor * baseline:
                flagged.add(s)
    return medians, baseline, flagged


class StragglerMonitor(QueryListener):
    """Built-in bus subscriber: rolling per-shard chunk-wait medians
    with factor-threshold flagging. `session.add_listener` installs it
    by default; find it with `StragglerMonitor.of(session)`."""

    _builtin = True

    def __init__(self, session):
        self._session = session
        self._lock = threading.Lock()
        #: query_id -> shard -> deque of recent wait_ms
        self._waits: Dict[int, Dict[int, deque]] = {}
        #: query_id -> shard -> host (from the records)
        self._hosts: Dict[int, Dict[int, int]] = {}
        #: query_id -> flagged shard set (live + retained post-query)
        self._flagged: "OrderedDict[int, Set[int]]" = OrderedDict()

    @staticmethod
    def of(session) -> Optional["StragglerMonitor"]:
        for li in session.listeners.listeners:
            if isinstance(li, StragglerMonitor):
                return li
        return None

    def flagged(self, query_id: int) -> Set[int]:
        with self._lock:
            return set(self._flagged.get(query_id, ()))

    def report(self) -> Dict[int, Set[int]]:
        """{query_id: flagged shards} for recently seen queries."""
        with self._lock:
            return {q: set(s) for q, s in self._flagged.items() if s}

    # -- bus callbacks ------------------------------------------------------

    def on_shard_records(self, event: ShardChunkEvent) -> None:
        conf = self._session.conf
        factor = float(conf.get(FACTOR_KEY))
        if factor <= 0:
            return
        min_chunks = int(conf.get(MIN_CHUNKS_KEY))
        floor_ms = float(conf.get(MIN_LATENCY_KEY))
        with self._lock:
            waits = self._waits.setdefault(event.query_id, {})
            hosts = self._hosts.setdefault(event.query_id, {})
            # self-bound the live maps: insertion order == query order,
            # so dropping the first key evicts the oldest query (see
            # _LIVE_BOUND — on_query_end may never fire). Never evict
            # the query being recorded: a long-running stream that
            # became the oldest entry would have its window silently
            # reset every chunk and could never accumulate minChunks.
            while len(self._waits) > _LIVE_BOUND:
                old = next(k for k in self._waits
                           if k != event.query_id)
                self._waits.pop(old, None)
                self._hosts.pop(old, None)
            while len(self._flagged) > _REPORT_BOUND:
                self._flagged.popitem(last=False)
            # window >= minChunks: a minChunks above the default
            # rolling window must widen it, not silently make the
            # `ready` gate unsatisfiable (detection would turn off
            # with no indication)
            window = max(WINDOW, min_chunks)
            for rec in event.records:
                shard = rec.get("shard")
                if shard is None or rec.get("phase") != "compute":
                    continue
                waits.setdefault(int(shard), deque(maxlen=window)) \
                    .append(float(rec.get("wait_ms") or 0.0))
                hosts[int(shard)] = int(rec.get("host") or 0)
            newly = self._evaluate(event.query_id, factor, min_chunks,
                                   floor_ms)
        # post OUTSIDE the lock: a listener consuming on_straggler may
        # call back into flagged()/report()
        for shard, median, baseline, n in newly:
            self._session.metrics.counter("straggler_flagged").inc()
            self._session.listeners.post("on_straggler", StragglerEvent(
                query_id=event.query_id, ts=time.time(), shard=shard,
                host=self._hosts.get(event.query_id, {}).get(shard, 0),
                median_ms=round(median, 3),
                baseline_ms=round(baseline, 3), chunks=n, factor=factor))

    def on_query_end(self, event) -> None:
        with self._lock:
            self._waits.pop(event.query_id, None)
            self._hosts.pop(event.query_id, None)
            # retain the flag set for report(), bounded
            self._flagged.setdefault(event.query_id, set())
            while len(self._flagged) > _REPORT_BOUND:
                self._flagged.popitem(last=False)

    # -- detection (lock held) ----------------------------------------------

    def _evaluate(self, query_id: int, factor: float, min_chunks: int,
                  floor_ms: float):
        waits = self._waits.get(query_id) or {}
        medians, baseline, flag_now = evaluate_waits(
            {s: list(w) for s, w in waits.items()},
            factor, min_chunks, floor_ms)
        flagged = self._flagged.setdefault(query_id, set())
        newly = []
        for shard in sorted(flag_now - flagged):
            flagged.add(shard)
            newly.append((shard, medians[shard], baseline,
                          len(waits[shard])))
        return newly

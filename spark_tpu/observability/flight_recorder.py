"""Crash-time flight recorder: bounded rings + diagnostic bundles.

The black-box seat the reference lacks a single analog for (its
diagnosability is spread over event logs, thread-dump endpoints and
heap histograms): an always-armed, bounded ring of recent
events/spans/metric deltas per subsystem, costing one dict append
under a short lock per event — and, at the moment something
unrecoverable happens, a self-contained diagnostic bundle dumped to a
versioned directory so the post-mortem does not depend on the process
surviving long enough to be asked.

Dump triggers:

- the executor's surfaced-failure path (execute_batch): OOM-ladder
  exhaustion (`StageOOMError`), non-convergent recovery, and any other
  FATAL — reasons "oom" / "recovery_nonconvergent" / "fatal";
- on demand: `GET /debug/bundle` on the SQL service, bench.py section
  timeouts/errors, or `FlightRecorder.of(session).dump("reason")`.

Bundle layout (`bundle-<app_id>-<seq>-<reason>/`, versioned by
MANIFEST.json `bundle_version`):

- ``MANIFEST.json``  — version, reason, ts, app id, trigger error,
  caller extras, and the file list (written LAST: its presence marks
  the bundle complete);
- ``rings.jsonl``    — ring contents, one record per line with its
  subsystem;
- ``plans.json``     — recent logical plans + runtime-annotated plan
  trees;
- ``spans.json``     — recent queries' span dicts (phase timelines);
- ``conf.json``      — effective conf snapshot (every registered key);
- ``metrics.json``   — full metrics-registry snapshot;
- ``threads.txt``    — live thread stacks (sys._current_frames);
- ``lockwatch.json`` — lock stats/edges when a lockwatch is installed;
- ``eventlog_tail.jsonl`` — last N lines of the session's live event
  log (`spark_tpu.sql.flightRecorder.eventLogTail`).

Recording rides the listener bus (a `_builtin` subscriber, installed
by every session), so it observes exactly the event stream other
subscribers see and can never fail a query. Gating is conf-at-event-
time (`spark_tpu.sql.flightRecorder.enabled`, default on). Ring
capacity is `spark_tpu.sql.flightRecorder.ringSize` records per
subsystem. Dumping never raises — a failed dump warns and returns
None — and the recorder never perturbs results: it only observes, so
query output is byte-identical recorder-on vs recorder-off.

Locking: `_lock` ("obs.flightrec", rank 46) guards the rings and the
retained plan/span maps; file I/O, conf/metrics snapshots and thread
stack capture all run OUTSIDE it over copies.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
import traceback
import warnings
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from .listener import QueryListener

ENABLED_KEY = "spark_tpu.sql.flightRecorder.enabled"
DIR_KEY = "spark_tpu.sql.flightRecorder.dir"
RING_KEY = "spark_tpu.sql.flightRecorder.ringSize"
TAIL_KEY = "spark_tpu.sql.flightRecorder.eventLogTail"

#: bundle layout version, carried in MANIFEST.json
BUNDLE_VERSION = 1

#: recent queries whose full plan strings / runtime trees / span dicts
#: are retained for plans.json + spans.json (rings keep truncated
#: copies of everything else)
_DETAIL_BOUND = 8

_SLUG = re.compile(r"[^a-zA-Z0-9_.-]+")


def _default_dir() -> str:
    import tempfile
    return os.path.join(tempfile.gettempdir(), "spark-tpu-flightrec")


class FlightRecorder(QueryListener):
    """Built-in bus subscriber: per-subsystem rings + `dump()`."""

    _builtin = True

    def __init__(self, session):
        self._session = session
        self._lock = threading.Lock()
        #: subsystem -> deque of recent records (fixed capacity)
        self._rings: Dict[str, deque] = {}
        #: query_id -> logical plan string (bounded)
        self._plans: "OrderedDict[int, str]" = OrderedDict()
        #: query_id -> runtime-annotated plan tree (bounded)
        self._trees: "OrderedDict[int, object]" = OrderedDict()
        #: query_id -> span dict list from the query-end event (bounded)
        self._spans: "OrderedDict[int, List]" = OrderedDict()
        #: bundle sequence within this session (names stay unique)
        self._seq = 0

    @staticmethod
    def of(session) -> Optional["FlightRecorder"]:
        for li in session.listeners.listeners:
            if isinstance(li, FlightRecorder):
                return li
        return None

    def _enabled(self) -> bool:
        return bool(self._session.conf.get(ENABLED_KEY))

    # -- recording (hot path) -----------------------------------------------

    def _record(self, subsystem: str, kind: str, **fields) -> None:
        if not self._enabled():
            return
        rec = {"ts": fields.pop("ts", None) or time.time(),
               "kind": kind}
        rec.update(fields)
        # conf read OUTSIDE _lock: the conf registry has its own lock
        # and the recorder's must stay a leaf-ish short section
        cap = max(8, int(self._session.conf.get(RING_KEY)))
        with self._lock:
            ring = self._rings.get(subsystem)
            if ring is None:
                ring = self._rings[subsystem] = deque(maxlen=cap)
            ring.append(rec)

    def _retain(self, store: OrderedDict, key, value) -> None:
        with self._lock:
            store[key] = value
            while len(store) > _DETAIL_BOUND:
                store.popitem(last=False)

    def on_query_start(self, event) -> None:
        self._record("query", "start", ts=event.ts,
                     query_id=event.query_id,
                     plan=str(event.plan)[:400])
        if self._enabled():
            self._retain(self._plans, event.query_id, str(event.plan))

    def on_analysis(self, event) -> None:
        self._record("analysis", "findings", ts=event.ts,
                     query_id=event.query_id,
                     codes=[f.get("code") for f in event.findings][:16])

    def on_stage_compiled(self, event) -> None:
        self._record("stage", "compiled", ts=event.ts,
                     query_id=event.query_id, stage=event.key_hash,
                     mesh_n=event.mesh_n)

    def on_stage_completed(self, event) -> None:
        self._record("stage", "completed", ts=event.ts,
                     query_id=event.query_id, stage=event.key_hash,
                     attempt=event.attempt,
                     elapsed_ms=round(event.elapsed_ms, 3),
                     overflow=list(event.overflow or ()))

    def on_fault(self, event) -> None:
        self._record("fault", event.action, ts=event.ts,
                     query_id=event.query_id,
                     error=str(event.error)[:200], site=event.site)

    def on_service(self, event) -> None:
        self._record("service", event.action, ts=event.ts,
                     query_id=event.query_id, session=event.session,
                     detail=str(event.detail)[:120])

    def on_shard_records(self, event) -> None:
        # chunk-boundary hot path: ring a summary, never the records
        self._record("shards", "chunk", ts=event.ts,
                     query_id=event.query_id, chunk=event.chunk,
                     n_records=len(event.records))

    def on_straggler(self, event) -> None:
        self._record("straggler", "flagged", ts=event.ts,
                     query_id=event.query_id, shard=event.shard,
                     median_ms=event.median_ms,
                     baseline_ms=event.baseline_ms)

    def on_streaming_batch(self, event) -> None:
        r = event.record or {}
        self._record("streaming", "batch", ts=event.ts,
                     query_id=event.query_id,
                     batch_id=r.get("batch_id"),
                     rows_in=r.get("rows_in"),
                     rows_out=r.get("rows_out"), kind=r.get("kind"))

    def on_streaming_trigger(self, event) -> None:
        r = event.record or {}
        self._record("streaming", "trigger", ts=event.ts,
                     query_id=event.query_id, tick=r.get("tick"),
                     skew_ms=r.get("skew_ms"),
                     batches_run=r.get("batches_run"))

    def on_query_end(self, event) -> None:
        ev = event.event or {}
        phases = ev.get("phase_times_s") or {}
        err = ev.get("error")
        self._record("query", "end", ts=event.ts,
                     query_id=event.query_id, status=event.status,
                     phase_times_s={k: round(float(v), 4)
                                    for k, v in phases.items()},
                     error=str(err)[:200] if err else None)
        if not self._enabled():
            return
        spans = ev.get("spans")
        if isinstance(spans, list):
            self._retain(self._spans, event.query_id, spans)
        tree = ev.get("plan_tree")
        if tree is not None:
            self._retain(self._trees, event.query_id, tree)

    # -- dumping ------------------------------------------------------------

    def dump(self, reason: str, extra: Optional[Dict] = None,
             error: Optional[BaseException] = None) -> Optional[str]:
        """Write a diagnostic bundle; returns its directory path, or
        None when disabled or the dump itself failed (never raises —
        diagnostics must not compound the failure being diagnosed)."""
        if not self._enabled():
            return None
        try:
            return self._dump(reason, extra, error)
        except Exception as e:  # noqa: BLE001 — diagnostics only
            warnings.warn(f"flight-recorder dump failed: "
                          f"{type(e).__name__}: {e}")
            return None

    def _dump(self, reason: str, extra: Optional[Dict],
              error: Optional[BaseException]) -> str:
        from .sinks import json_default
        conf = self._session.conf
        base = str(conf.get(DIR_KEY)) or _default_dir()
        with self._lock:
            self._seq += 1
            seq = self._seq
            rings = {k: list(d) for k, d in self._rings.items()}
            plans = dict(self._plans)
            trees = dict(self._trees)
            spans = dict(self._spans)
        slug = _SLUG.sub("_", str(reason))[:40] or "unknown"
        name = f"bundle-{self._session.app_id}-{seq:03d}-{slug}"
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)

        def write_json(fname: str, payload) -> str:
            with open(os.path.join(path, fname), "w") as f:
                json.dump(payload, f, default=json_default, indent=1)
            return fname

        files = []
        with open(os.path.join(path, "rings.jsonl"), "w") as f:
            for subsystem in sorted(rings):
                for rec in rings[subsystem]:
                    f.write(json.dumps(dict(rec, subsystem=subsystem),
                                       default=json_default) + "\n")
        files.append("rings.jsonl")
        files.append(write_json("plans.json", {
            "plans": {str(q): p for q, p in plans.items()},
            "plan_trees": {str(q): t for q, t in trees.items()}}))
        files.append(write_json("spans.json", {
            "spans": {str(q): s for q, s in spans.items()}}))
        files.append(write_json("conf.json", self._conf_snapshot()))
        files.append(write_json("metrics.json",
                                self._session.metrics.snapshot()))
        with open(os.path.join(path, "threads.txt"), "w") as f:
            f.write(self._thread_stacks())
        files.append("threads.txt")
        files.append(write_json("lockwatch.json",
                                self._lockwatch_report()))
        tail = self._event_log_tail()
        if tail is not None:
            with open(os.path.join(path,
                                   "eventlog_tail.jsonl"), "w") as f:
                f.writelines(tail)
            files.append("eventlog_tail.jsonl")
        manifest = {
            "bundle_version": BUNDLE_VERSION,
            "reason": str(reason),
            "ts": time.time(),
            "app_id": self._session.app_id,
            "pid": os.getpid(),
            "error": (f"{type(error).__name__}: {error}"[:400]
                      if error is not None else None),
            "extra": extra or {},
            "files": files,
        }
        # MANIFEST last: its presence marks the bundle complete
        write_json("MANIFEST.json", manifest)
        self._session.metrics.counter("flightrec_bundles").inc()
        return path

    def _conf_snapshot(self) -> Dict:
        """Effective value of every registered conf key (+ which were
        explicitly set) — the 'what was this process actually running
        with' half of a post-mortem."""
        from ..config import registry
        conf = self._session.conf
        effective = {}
        explicit = []
        for key in sorted(registry()):
            try:
                effective[key] = conf.get(key)
                if conf.is_explicitly_set(key):
                    explicit.append(key)
            except Exception:  # noqa: BLE001 — partial > nothing
                effective[key] = "<unreadable>"
        return {"effective": effective, "explicitly_set": explicit}

    @staticmethod
    def _thread_stacks() -> str:
        """Every live thread's stack, flight-data-recorder style (the
        reference's /threadDump endpoint, as a file)."""
        frames = sys._current_frames()
        names = {t.ident: t for t in threading.enumerate()}
        out = []
        for ident, frame in sorted(frames.items()):
            t = names.get(ident)
            label = (f"{t.name} (daemon={t.daemon})"
                     if t is not None else "<unknown>")
            out.append(f'Thread {ident} "{label}":\n')
            out.extend(traceback.format_stack(frame))
            out.append("\n")
        return "".join(out)

    @staticmethod
    def _lockwatch_report() -> Dict:
        from ..testing.lockwatch import current_watch
        w = current_watch()
        if w is None:
            return {"installed": False}
        return dict(w.report(), installed=True)

    def _event_log_tail(self) -> Optional[List[str]]:
        """Last N lines of the session's LIVE event-log file (rolled
        files are already durable; the live tail is what a crashed
        process would otherwise lose context around)."""
        conf = self._session.conf
        n = int(conf.get(TAIL_KEY))
        log_dir = str(conf.get("spark_tpu.sql.eventLog.dir"))
        if n <= 0 or not log_dir:
            return None
        base = os.path.join(log_dir,
                            f"app-{self._session.app_id}.jsonl")
        try:
            with open(base) as f:
                return list(deque(f, maxlen=n))
        except OSError:
            return None

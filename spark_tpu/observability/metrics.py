"""Process metrics registry with JSONL + Prometheus sinks.

The MetricsSystem/`metrics.properties` analog, sized to this engine:
one process-level registry of counters/gauges/timers, flushed to
configured sinks at query end by the metrics listener (sinks.py).
Sink selection is conf-driven (`spark_tpu.sql.metrics.sink` =
"jsonl", "prometheus", or both comma-separated;
`spark_tpu.sql.metrics.dir` is the output directory):

- jsonl: one snapshot line appended per flush to `metrics.jsonl`
  (replayable next to the event log);
- prometheus: text exposition format atomically rewritten to
  `metrics.prom` on every flush — point node_exporter's textfile
  collector (or any scraper of files) at the directory.

`METRIC_PREFIXES` is the registered namespace for TRACED per-operator
metrics (`ctx.add_metric` inside compiled stages). Registration is
enforced twice: `ExecContext.add_metric` rejects unregistered names at
trace time, and `scripts/metrics_lint.py` statically asserts every
call site — so history summaries can never silently miss columns.
"""

from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time
from typing import Dict, List

# ---------------------------------------------------------------------------
# Traced-metric name registry (the SQLMetrics naming discipline)
# ---------------------------------------------------------------------------

#: every ctx.add_metric name must start with one of these. Extending the
#: engine with a new traced metric means adding its prefix HERE (and a
#: history/summary consumer), not just emitting it.
METRIC_PREFIXES = (
    "rows_",           # per-operator output rows (executor replay wrapper)
    "join_rows_",      # join true output-row total (AQE capacity channel)
    "exch_max_",       # exchange max per-(src,dst) bucket count
    "exch_rows_",      # exchange routed live rows
    "exch_bytes_",     # exchange routed payload bytes (shuffle volume)
    "agg_groups",      # aggregate distinct-group counts (+ _<tag> forms)
    "gen_rows_",       # generate/explode output rows
    "rtf_tested_",     # runtime-filter probe rows tested
    "rtf_pruned_",     # runtime-filter probe rows pruned
    "rtf_build_ms_",   # runtime-filter trace-time build cost
    "join_build_ms_",  # hash-join table build cost (trace-time, pmax)
    "join_probe_ms_",  # hash-join probe-program build cost
    "join_table_slots_",  # hash-join open-addressing table capacity
    # per-shard telemetry ([n] arrays: one slot per mesh position, the
    # executor unpacks them into event-log `shards` records; consumer:
    # history.shard_summary / straggler_report)
    "shard_rows_",     # per-shard routed/processed live rows
    "shard_bytes_",    # per-shard routed payload bytes
    # ingest pipeline (PrefetchChunkIterator): REGISTRY counters, not
    # traced per-operator metrics — listed here so the namespace is
    # closed in one place (consumers key on the prefixes)
    "ingest_stall_",   # consumer time blocked waiting on host decode
    "ingest_overlap_",  # host decode time hidden behind device compute
    # straggler detection (observability/straggler.py): REGISTRY
    # counter, listed for namespace closure like the ingest pair
    "straggler_",      # straggler_flagged: shards flagged this process
    # elastic mesh (parallel/elastic.py): REGISTRY counters, listed
    # for namespace closure — gang restarts applied and live rows the
    # straggler rebalancer shifted off flagged shards
    "mesh_restart_",   # mesh_restart_attempts: gang restarts applied
    "rebalance_",      # rebalance_rows: rows shifted off flagged shards
    # durable streaming (streaming.py + execution/state_store.py):
    # REGISTRY counters, listed for namespace closure — micro-batches
    # committed / input rows, incremental state-store bytes (delta vs
    # snapshot), restore wall-clock, quarantined source files and
    # corrupt metadata-log entries skipped
    "streaming_",      # streaming_batches/_rows/_state_delta_bytes/
                       # _state_snapshot_bytes/_restore_ms/
                       # _files_quarantined/_log_corrupt
    # compiled-stage caches (executor + execution/compile_cache.py):
    # REGISTRY counters, listed for namespace closure — in-memory
    # hits/misses plus the persistent cross-process seat's disk
    # hits/misses, deserialize wall-clock, bytes written, corrupt
    # entries recovered from, and warm-start entries installed
    "compile_cache_",  # compile_cache_hits/_misses/_disk_hits/
                       # _disk_misses/_deser_ms/_write_bytes/
                       # _corrupt/_warm_entries
    # query lifecycle control (execution/lifecycle.py + service/):
    # REGISTRY counters, listed for namespace closure — cancelled and
    # deadline-exceeded query totals (counted once per query: at the
    # executor when the engine saw the query, at the service when it
    # was cancelled out of the admission queue before executing) and
    # per-session quota rejections (admission maxConcurrent bound +
    # arbiter hbmShare lease denials)
    "query_cancelled",       # queries stopped by cancel()/DELETE
    "query_deadline_",       # query_deadline_exceeded: blown budgets
    "session_quota_",        # session_quota_rejections
    # out-of-process python UDF lane (udf_worker/ +
    # execution/python_eval.py worker mode): REGISTRY counters, listed
    # for namespace closure — batches/rows streamed through the pool,
    # cumulative in-worker wall-clock, workers killed+replaced after a
    # crash/timeout, and spawn+handshake wall-clock
    "udf_",            # udf_batches/udf_rows/udf_exec_ms/
                       # udf_worker_restarts/udf_worker_spawn_ms
    # serving fleet (service/fleet.py): REGISTRY counters/gauges on
    # the SUPERVISOR's registry, listed for namespace closure —
    # worker spawns/restarts/losses, quarantines, proxied and shed
    # requests, transparent read failovers, drains, death bundles
    "fleet_",          # fleet_workers_ready/fleet_spawns/
                       # fleet_restarts/fleet_worker_lost/
                       # fleet_quarantined/fleet_requests_proxied/
                       # fleet_requests_shed/fleet_failovers/
                       # fleet_drains/fleet_bundles
    # engine status store (observability/status_store.py + the metrics
    # sink listener): REGISTRY histograms/counters/gauges, listed for
    # namespace closure — end-to-end and per-phase latency
    # distributions, heartbeat samples, queries in flight
    "status_",         # status_latency_ms (e2e histogram)/
                       # status_phase_ms_<phase>/status_class_ms_<cls>/
                       # status_heartbeats/status_queries_inflight
    # SLO burn tracking against spark_tpu.service.slo.latencyMs:
    # REGISTRY counters a fleet router sheds on
    "slo_",            # slo_queries_total/slo_burned_total/
                       # slo_burn_ms_total
    # flight recorder (observability/flight_recorder.py): REGISTRY
    # counters, listed for namespace closure
    "flightrec_",      # flightrec_bundles: diagnostic bundles dumped
)


def is_registered_metric(name: str) -> bool:
    return name.startswith(METRIC_PREFIXES)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

class Counter:
    """Monotonic counter. `inc` is lock-guarded: under the concurrent
    SQL service, multiple query threads increment the same (shared-
    registry) counters, and `value += n` is a read-modify-write that
    loses updates un-locked."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def set(self, v) -> None:
        self.value = v  # single attribute store: atomic under the GIL


class Timer:
    __slots__ = ("count", "total_s", "min_s", "max_s", "_lock")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.min_s = float("inf")
        self.max_s = 0.0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.min_s = min(self.min_s, seconds)
            self.max_s = max(self.max_s, seconds)


class Histogram:
    """Log-bucketed value distribution (the latency-SLO metric type).

    Fixed power-of-two bucket boundaries (0.25 ms .. ~17.5 min for the
    default ms domain) so two processes' histograms are always
    mergeable and the Prometheus exposition is stable. `observe` is a
    bisect + one lock-guarded increment — cheap enough for every query
    end under the concurrent service. Quantiles interpolate linearly
    inside the landing bucket (the classic log-histogram estimate),
    clamped by the observed min/max so tiny-count histograms don't
    report a bucket bound nobody measured."""

    __slots__ = ("bounds", "counts", "count", "total", "min_v", "max_v",
                 "_lock")

    #: upper bounds, 2^-2 .. 2^20 — in ms: 0.25ms up to ~17.5 minutes
    DEFAULT_BOUNDS = tuple(2.0 ** i for i in range(-2, 21))

    def __init__(self):
        self.bounds = self.DEFAULT_BOUNDS
        #: one slot per bound + the overflow bucket
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.min_v = float("inf")
        self.max_v = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.total += value
            if value < self.min_v:
                self.min_v = value
            if value > self.max_v:
                self.max_v = value

    def _quantile_locked(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i, n in enumerate(self.counts):
            if n == 0:
                continue
            if cum + n >= target:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) \
                    else self.max_v
                frac = (target - cum) / n
                est = lo + (hi - lo) * frac
                return min(max(est, self.min_v), self.max_v)
            cum += n
        return self.max_v

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._quantile_locked(q)

    def percentiles(self) -> Dict[str, float]:
        """{p50, p95, p99} in one lock acquisition (the /status shape)."""
        with self._lock:
            return {"p50": round(self._quantile_locked(0.50), 3),
                    "p95": round(self._quantile_locked(0.95), 3),
                    "p99": round(self._quantile_locked(0.99), 3)}

    def snapshot(self) -> Dict:
        with self._lock:
            return {"count": self.count,
                    "sum": round(self.total, 6),
                    "min": round(self.min_v, 6) if self.count else 0.0,
                    "max": round(self.max_v, 6),
                    "bounds": list(self.bounds),
                    "counts": list(self.counts)}


class MetricsRegistry:
    """Named counters/gauges/timers/histograms, created on first use."""

    def __init__(self):
        self._lock = threading.Lock()
        #: serializes sink writes (concurrent query-end flushes from
        #: service worker threads must not interleave JSONL lines)
        self._flush_lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timer] = {}
        self._histograms: Dict[str, Histogram] = {}

    def _get(self, store, name, cls):
        with self._lock:
            m = store.get(name)
            if m is None:
                m = store[name] = cls()
            return m

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def timer(self, name: str) -> Timer:
        return self._get(self._timers, name, Timer)

    def histogram(self, name: str) -> Histogram:
        return self._get(self._histograms, name, Histogram)

    def histogram_names(self) -> List[str]:
        with self._lock:
            return sorted(self._histograms)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "timers": {k: {"count": t.count,
                               "total_s": round(t.total_s, 6),
                               "min_s": (round(t.min_s, 6)
                                         if t.count else 0.0),
                               "max_s": round(t.max_s, 6)}
                           for k, t in self._timers.items()},
                "histograms": {k: h.snapshot()
                               for k, h in self._histograms.items()},
            }

    # -- sinks --------------------------------------------------------------

    SINK_KEY = "spark_tpu.sql.metrics.sink"
    DIR_KEY = "spark_tpu.sql.metrics.dir"

    def flush(self, conf) -> None:
        """Write every configured sink; a sink failing warns, never
        raises (observability must not fail the query)."""
        sinks = [s.strip() for s in
                 str(conf.get(self.SINK_KEY) or "").split(",") if s.strip()]
        if not sinks:
            return
        out_dir = str(conf.get(self.DIR_KEY))
        snap = self.snapshot()
        try:
            with self._flush_lock:
                os.makedirs(out_dir, exist_ok=True)
                if "jsonl" in sinks:
                    line = json.dumps(dict(snap, ts=time.time()))
                    with open(os.path.join(out_dir,
                                           "metrics.jsonl"), "a") as f:
                        f.write(line + "\n")
                if "prometheus" in sinks:
                    write_prometheus(os.path.join(out_dir, "metrics.prom"),
                                     snap)
        except OSError as e:
            import warnings
            warnings.warn(f"metrics sink write failed: {e}")


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "spark_tpu_" + _PROM_BAD.sub("_", name)


def prometheus_text(snapshot: Dict) -> str:
    """Render a registry snapshot as Prometheus text exposition format
    0.0.4 (shared by the textfile sink below and the SQL service's
    live `GET /metrics` endpoint)."""
    lines = []
    for name, v in sorted(snapshot.get("counters", {}).items()):
        p = _prom_name(name)
        lines += [f"# TYPE {p} counter", f"{p} {v}"]
    for name, v in sorted(snapshot.get("gauges", {}).items()):
        p = _prom_name(name)
        lines += [f"# TYPE {p} gauge", f"{p} {v}"]
    for name, t in sorted(snapshot.get("timers", {}).items()):
        p = _prom_name(name)
        # legacy pair kept for existing scrapers, plus the native
        # summary form (`_sum`/`_count`) the round-trip contract names
        lines += [f"# TYPE {p}_count counter", f"{p}_count {t['count']}",
                  f"# TYPE {p}_seconds_total counter",
                  f"{p}_seconds_total {t['total_s']}",
                  f"# TYPE {p}_seconds summary",
                  f"{p}_seconds_sum {t['total_s']}",
                  f"{p}_seconds_count {t['count']}"]
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        p = _prom_name(name)
        lines.append(f"# TYPE {p} histogram")
        cum = 0
        for le, n in zip(h["bounds"], h["counts"]):
            cum += n
            lines.append(f'{p}_bucket{{le="{le:g}"}} {cum}')
        cum += h["counts"][-1]
        lines += [f'{p}_bucket{{le="+Inf"}} {cum}',
                  f"{p}_sum {h['sum']}", f"{p}_count {h['count']}"]
    return "\n".join(lines) + "\n"


def write_prometheus(path: str, snapshot: Dict) -> None:
    """Atomic rewrite in Prometheus text exposition format 0.0.4."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(prometheus_text(snapshot))
    os.replace(tmp, path)


#: one exposition sample: `name value` or `name{label="v",...} value`
#: (the labeled form is what histogram `_bucket{le="..."}` series use)
_PROM_SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'((?:\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?)'
    r'\s+(\S+)$')


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Scrape-parse text exposition back to {series: value} (tests and
    the preflight smokes prove the output is consumable this way).
    Labeled samples keep their label set in the key — a histogram
    bucket round-trips as e.g. `spark_tpu_status_latency_ms_bucket`
    `{le="4"}`; unlabeled series keep the bare name, so every consumer
    written against the counter/gauge/timer output keeps working."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_SAMPLE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labels, value = m.groups()
        try:
            out[name + labels] = float(value)
        except ValueError:
            raise ValueError(
                f"non-numeric sample value in line: {line!r}")
    return out


def parse_prometheus(path: str) -> Dict[str, float]:
    """`parse_prometheus_text` over a textfile-sink file."""
    with open(path) as f:
        return parse_prometheus_text(f.read())

"""Engine status store: live health behind `GET /status`.

The `AppStatusStore` seat (reference: `AppStatusListener` folding the
event stream into a kvstore served by `status/api/v1`, sampled by the
driver's `Heartbeater`), sized to this engine: one process-level
`StatusStore` fed two ways —

- **listener-bus feeds** (`bind(session, label)`): a tiny per-session
  subscriber counts queries in flight and folds every query end into
  per-status outcome counts, per-phase cumulative seconds and
  per-session attribution (the AppStatusListener half);
- **a heartbeat thread** (`start()`/`stop()`, the `Heartbeater`
  analog): every `spark_tpu.sql.status.heartbeatMs` it samples the
  wired providers (admission queue depth, arbiter HBM lease occupancy,
  session-pool size, UDF pool size), derives cache hit rates from the
  shared metrics registry, reads streaming trigger lag, and appends
  each value into a fixed-capacity ring time-series
  (`spark_tpu.sql.status.ringSize` points per series) served by
  `GET /status/timeseries`.

Latency distributions are NOT kept here: the metrics sink listener
(sinks.py) records them into the registry's `status_latency_ms` /
`status_phase_ms_<phase>` / `status_class_ms_<class>` histograms
(metrics.Histogram), and `snapshot()` reads p50/p95/p99 back out — so
standalone sessions and the pooled service share one distribution and
one Prometheus exposition.

Offline, the same health summary is replayable from the event log via
`history.status_summary` (no live process required).

Locking: `_lock` ("obs.status", rank 45) guards the rings and the
fold-in counters only. Providers, registry reads and listener posts
all run OUTSIDE it — providers take service-layer locks (admission cv,
arbiter cv, pool lock) that rank BELOW this one.
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from typing import Callable, Dict, List, Optional

from .listener import QueryEndEvent, QueryListener, QueryStartEvent

ENABLED_KEY = "spark_tpu.sql.status.enabled"
HEARTBEAT_KEY = "spark_tpu.sql.status.heartbeatMs"
RING_KEY = "spark_tpu.sql.status.ringSize"
SLO_KEY = "spark_tpu.service.slo.latencyMs"

#: terminal query statuses folded into outcome counts (anything else
#: lands under "other" so a new status can never be silently dropped)
STATUSES = ("ok", "error", "cancelled", "deadline_exceeded")

#: (hits metric, misses metric, series name) pairs the heartbeat
#: derives rolling hit rates from — counters first, gauges as fallback
_HIT_RATES = (
    ("compile_cache_hits", "compile_cache_misses",
     "compile_cache_hit_rate"),
    ("compile_cache_disk_hits", "compile_cache_disk_misses",
     "compile_cache_disk_hit_rate"),
    ("device_cache_hits", "device_cache_misses",
     "device_cache_hit_rate"),
    ("service_result_cache_hits", "service_result_cache_misses",
     "result_cache_hit_rate"),
)


class _SessionFeed(QueryListener):
    """Per-session bus subscriber: attributes lifecycle events to the
    store under the session's label. Registered by `bind()`; checks
    nothing itself — the store gates on conf at event time."""

    def __init__(self, store: "StatusStore", label: str):
        self._store = store
        self._label = label

    def on_query_start(self, event: QueryStartEvent) -> None:
        self._store._on_start(self._label, event)

    def on_query_end(self, event: QueryEndEvent) -> None:
        self._store._on_end(self._label, event)


class StatusStore:
    """Bounded, typed rolling store of engine health. Providers are
    callables returning flat(ish) stats dicts; every numeric leaf is
    sampled into its own ring series as `<provider>_<key>`."""

    def __init__(self, conf, metrics,
                 providers: Optional[Dict[str, Callable]] = None):
        self._conf = conf
        self._metrics = metrics
        self._providers: Dict[str, Callable] = dict(providers or {})
        self._lock = threading.Lock()
        self._ring_cap = max(2, int(conf.get(RING_KEY)))
        #: series name -> deque[(ts, value)] (fixed capacity)
        self._series: Dict[str, deque] = {}
        #: session label -> queries currently in flight (nested
        #: subquery executions start/end in pairs, so they balance)
        self._inflight: Dict[str, int] = {}
        #: session label -> outcome attribution
        self._sessions: Dict[str, Dict] = {}
        #: terminal status -> count, across every bound session
        self._status_counts: Dict[str, int] = {}
        #: phase name -> cumulative seconds (the per-phase outcome view)
        self._phase_totals: Dict[str, float] = {}
        self._queries_total = 0
        self._heartbeats = 0
        self._started_ts = time.time()
        self._stop_event = threading.Event()
        #: heartbeat thread handle; written by the owning control
        #: thread in start()/stop() only (guarded-by waiver)
        self._thread: Optional[threading.Thread] = None

    # -- wiring -------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return bool(self._conf.get(ENABLED_KEY))

    def add_provider(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._providers[name] = fn

    def bind(self, session, label: str) -> _SessionFeed:
        """Subscribe a per-session feed on `session`'s bus, attributed
        to `label`. Returns the feed (tests unregister it)."""
        with self._lock:
            self._sessions.setdefault(
                label, {"queries": 0, "last_ts": None})
            self._inflight.setdefault(label, 0)
        feed = _SessionFeed(self, label)
        session.add_listener(feed)
        return feed

    # -- listener fold-in ---------------------------------------------------

    def _on_start(self, label: str, event: QueryStartEvent) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._inflight[label] = self._inflight.get(label, 0) + 1
            total = sum(self._inflight.values())
        self._metrics.gauge("status_queries_inflight").set(total)

    def _on_end(self, label: str, event: QueryEndEvent) -> None:
        if not self.enabled:
            return
        status = event.status if event.status in STATUSES else "other"
        phases = (event.event or {}).get("phase_times_s") or {}
        with self._lock:
            n = self._inflight.get(label, 0)
            self._inflight[label] = max(0, n - 1)
            total = sum(self._inflight.values())
            self._queries_total += 1
            self._status_counts[status] = \
                self._status_counts.get(status, 0) + 1
            sess = self._sessions.setdefault(
                label, {"queries": 0, "last_ts": None})
            sess["queries"] = int(sess.get("queries", 0)) + 1
            sess[status] = int(sess.get(status, 0)) + 1
            sess["last_ts"] = event.ts
            for phase, s in phases.items():
                try:
                    self._phase_totals[phase] = \
                        self._phase_totals.get(phase, 0.0) + float(s)
                except (TypeError, ValueError):
                    continue
        self._metrics.gauge("status_queries_inflight").set(total)

    # -- heartbeat ----------------------------------------------------------

    def start(self) -> None:
        """Spawn the heartbeat thread (no-op when disabled or already
        running). The thread is named so lockwatch's no-thread-leak
        assertion can find a leaked one by prefix."""
        if self._thread is not None or not self.enabled:
            return
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="spark-tpu-status-heartbeat")
        self._thread.start()

    def stop(self) -> None:
        """Stop and JOIN the heartbeat thread (bounded): stop() must
        leave no thread behind."""
        self._stop_event.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        self._thread = None

    def _run(self) -> None:
        period = max(0.01, float(self._conf.get(HEARTBEAT_KEY)) / 1e3)
        while not self._stop_event.wait(period):
            try:
                self.sample()
            except Exception as e:  # noqa: BLE001 — heartbeat survives
                warnings.warn(f"status heartbeat sample failed: {e}")
            # re-read the period each tick: heartbeatMs is
            # runtime-settable like every conf (the sinks idiom)
            period = max(0.01,
                         float(self._conf.get(HEARTBEAT_KEY)) / 1e3)

    def sample(self) -> Dict[str, float]:
        """One heartbeat: gather every numeric observable OUTSIDE the
        store lock (providers take service-layer locks), then append
        the whole tick into the rings under ONE lock acquisition.
        Public so tests and embedded callers can tick deterministically
        without the thread."""
        ts = time.time()
        vals: Dict[str, float] = {}
        with self._lock:
            providers = list(self._providers.items())
        for pname, fn in providers:
            try:
                stats = fn() or {}
            except Exception:  # noqa: BLE001 — a provider never kills
                continue      # the heartbeat
            for k, v in stats.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                vals[f"{pname}_{k}"] = float(v)
        snap = self._metrics.snapshot()
        counters = snap.get("counters", {})
        gauges = snap.get("gauges", {})
        for hit_k, miss_k, series in _HIT_RATES:
            hits = counters.get(hit_k, gauges.get(hit_k))
            misses = counters.get(miss_k, gauges.get(miss_k))
            if hits is None and misses is None:
                continue
            total = float(hits or 0) + float(misses or 0)
            if total > 0:
                vals[series] = round(float(hits or 0) / total, 4)
        vals.update(self._streaming_lag())
        with self._lock:
            vals["queries_inflight"] = float(
                sum(self._inflight.values()))
            vals["queries_total"] = float(self._queries_total)
            for name, v in vals.items():
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = deque(
                        maxlen=self._ring_cap)
                ring.append((ts, v))
            self._heartbeats += 1
        self._metrics.counter("status_heartbeats").inc()
        return vals

    @staticmethod
    def _streaming_lag() -> Dict[str, float]:
        """Live streaming health: trigger-loop count and the worst
        last-tick wall-clock skew (the batch-lag signal of the
        supervised trigger loop)."""
        try:
            from ..streaming import live_queries
            rows = live_queries()
        except Exception:  # noqa: BLE001 — best-effort observable
            return {}
        out = {"streams_live": float(len(rows))}
        skews = [float(r["last_skew_ms"]) for r in rows
                 if isinstance(r.get("last_skew_ms"), (int, float))]
        if skews:
            out["streams_max_skew_ms"] = round(max(skews), 3)
        return out

    # -- serving ------------------------------------------------------------

    def _latency(self) -> Dict:
        """p50/p95/p99 views over the registry's status histograms
        (fed by the metrics sink listener at every query end)."""
        e2e = self._metrics.histogram("status_latency_ms")
        out = {"e2e_ms": dict(e2e.percentiles(),
                              count=e2e.snapshot()["count"]),
               "phases_ms": {}, "classes_ms": {}}
        for name in self._metrics.histogram_names():
            if name.startswith("status_phase_ms_"):
                out["phases_ms"][name[len("status_phase_ms_"):]] = \
                    self._metrics.histogram(name).percentiles()
            elif name.startswith("status_class_ms_"):
                out["classes_ms"][name[len("status_class_ms_"):]] = \
                    self._metrics.histogram(name).percentiles()
        return out

    def _slo(self) -> Dict:
        snap = self._metrics.snapshot().get("counters", {})
        target = float(self._conf.get(SLO_KEY) or 0)
        queries = int(snap.get("slo_queries_total", 0))
        burned = int(snap.get("slo_burned_total", 0))
        return {"target_ms": target,
                "queries": queries,
                "burned": burned,
                "burn_ms": int(snap.get("slo_burn_ms_total", 0)),
                "burn_rate": (round(burned / queries, 4)
                              if queries else 0.0)}

    def snapshot(self) -> Dict:
        """The `GET /status` payload: live health, one dict."""
        providers_live: Dict[str, Dict] = {}
        with self._lock:
            providers = list(self._providers.items())
        for pname, fn in providers:
            try:
                providers_live[pname] = fn() or {}
            except Exception as e:  # noqa: BLE001 — partial > nothing
                providers_live[pname] = {"error": str(e)[:120]}
        latency = self._latency()
        slo = self._slo()
        with self._lock:
            return {
                "enabled": self.enabled,
                "uptime_s": round(time.time() - self._started_ts, 1),
                "heartbeats": self._heartbeats,
                "heartbeat_ms": float(self._conf.get(HEARTBEAT_KEY)),
                "ring_capacity": self._ring_cap,
                "queries_inflight": dict(self._inflight),
                "queries_inflight_total": sum(self._inflight.values()),
                "queries_total": self._queries_total,
                "statuses": dict(self._status_counts),
                "phase_seconds": {k: round(v, 4) for k, v in
                                  sorted(self._phase_totals.items())},
                "sessions": {k: dict(v) for k, v in
                             sorted(self._sessions.items())},
                "latency": latency,
                "slo": slo,
                "providers": providers_live,
            }

    def timeseries(self, names: Optional[List[str]] = None,
                   limit: Optional[int] = None) -> Dict:
        """The `GET /status/timeseries` payload: ring contents per
        series as [ts, value] pairs (newest last), optionally filtered
        to `names` and trimmed to the last `limit` points."""
        with self._lock:
            data = {k: list(d) for k, d in sorted(self._series.items())
                    if names is None or k in names}
            cap = self._ring_cap
            beats = self._heartbeats
        if limit is not None:
            limit = max(1, int(limit))
            data = {k: pts[-limit:] for k, pts in data.items()}
        return {"ring_capacity": cap,
                "heartbeats": beats,
                "series": {k: [[round(ts, 3), v] for ts, v in pts]
                           for k, pts in data.items()}}

"""DataFrame API: a lazy logical-plan holder.

The analog of the reference's `Dataset.scala:191` — every method builds a
new logical plan; actions (`collect`, `count`, `to_pandas`) run the
QueryExecution pipeline. Naming follows pyspark (`python/pyspark/sql/
dataframe.py`) so a Spark user can switch with minimal friction.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import pyarrow as pa

from . import types as T
from .expr import (Alias, AnalysisError, ColumnRef, EQ, Expression, SortOrder)
from .expr_agg import AggExpr, AggregateFunction, Count
from .plan import logical as L


def _expr(e) -> Expression:
    from .functions import _expr as f
    return f(e)


class DataFrame:
    def __init__(self, session, plan: L.LogicalPlan):
        self.session = session
        self.plan = plan

    # -- transformations ----------------------------------------------------

    def _with(self, plan: L.LogicalPlan) -> "DataFrame":
        return DataFrame(self.session, plan)

    def select(self, *exprs) -> "DataFrame":
        es = [_expr(e) for e in exprs]
        plan, es = self._extract_windows(es)
        from .expr_array import contains_explode, extract_generators
        if any(contains_explode(e) for e in es):
            plan, es = extract_generators(plan, es)
        return self._with(L.Project(plan, es))

    def _extract_windows(self, exprs: List[Expression]):
        """Pull WindowExpr nodes out into Window plan nodes below the
        projection (the reference's ExtractWindowExpressions analog); the
        projection then references their output columns. Functions
        sharing a spec land in ONE Window node (one sort), and output
        names never collide with existing columns (the projection
        re-aliases)."""
        from .window import extract_window_exprs
        return extract_window_exprs(self.plan, exprs)

    def with_watermark(self, col_name: str, delay: str) -> "DataFrame":
        """Event-time watermark (reference: Dataset.withWatermark +
        WatermarkTracker.scala:1): rows older than max(event_time) -
        delay drop; closed windows evict/emit in append mode."""
        from .expr_fns import parse_duration_us
        return self._with(L.Watermark(self.plan, col_name,
                                      parse_duration_us(delay)))

    withWatermark = with_watermark

    def filter(self, condition: Expression) -> "DataFrame":
        return self._with(L.Filter(self.plan, condition))

    where = filter

    def with_column(self, name: str, e: Expression) -> "DataFrame":
        exprs: List[Expression] = []
        replaced = False
        for n in self.plan.schema().names:
            if n == name:
                exprs.append(Alias(_expr(e), name))
                replaced = True
            else:
                exprs.append(ColumnRef(n))
        if not replaced:
            exprs.append(Alias(_expr(e), name))
        plan, exprs = self._extract_windows(exprs)
        return self._with(L.Project(plan, exprs))

    withColumn = with_column

    def group_by(self, *group_exprs) -> "GroupedData":
        return GroupedData(self, [_expr(g) for g in group_exprs])

    groupBy = group_by

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    _JOIN_ALIASES = {
        "outer": "full", "full_outer": "full", "fullouter": "full",
        "left_outer": "left", "leftouter": "left",
        "right_outer": "right", "rightouter": "right",
        "semi": "left_semi", "leftsemi": "left_semi",
        "anti": "left_anti", "leftanti": "left_anti",
    }

    def cross_join(self, other: "DataFrame",
                   condition: Optional[Expression] = None) -> "DataFrame":
        """Cartesian product (reference: Dataset.crossJoin), lowered to a
        constant-key equi-join so the expansion kernel produces |L|x|R|."""
        from .expr import Literal
        one = Literal(1)
        return self._with(L.Join(self.plan, other.plan, [one], [one],
                                 "inner", condition))

    crossJoin = cross_join

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             left_on=None, right_on=None,
             condition: Optional[Expression] = None) -> "DataFrame":
        how = self._JOIN_ALIASES.get(how, how)
        if how == "cross":
            return self.cross_join(other, condition)
        names = None
        if on is not None:
            names = [on] if isinstance(on, str) else list(on)
            lk = [ColumnRef(n) for n in names]
            rk = [ColumnRef(n) for n in names]
        else:
            lk = [_expr(e) for e in (left_on if isinstance(left_on, (list, tuple))
                                     else [left_on])]
            rk = [_expr(e) for e in (right_on if isinstance(right_on, (list, tuple))
                                     else [right_on])]
        join = L.Join(self.plan, other.plan, lk, rk, how, condition)
        if names is not None and how not in ("left_semi", "left_anti"):
            # USING-join semantics (reference Dataset.join(df, usingColumns)):
            # one output key column — the left one (coalesced with the
            # right copy for right/full outer), right copies dropped
            from .expr import Coalesce
            name_map = join.right_name_map()
            drop = {name_map[n] for n in names if n in name_map}
            exprs: List[Expression] = []
            for n in join.schema().names:
                if n in drop:
                    continue
                if n in names and how in ("right", "full"):
                    exprs.append(Alias(Coalesce(ColumnRef(n),
                                                ColumnRef(name_map[n])), n))
                else:
                    exprs.append(ColumnRef(n))
            return self._with(L.Project(join, exprs))
        return self._with(join)

    def sort(self, *orders) -> "DataFrame":
        os = []
        for o in orders:
            if isinstance(o, SortOrder):
                os.append(o)
            else:
                os.append(SortOrder(_expr(o), ascending=True))
        return self._with(L.Sort(self.plan, os))

    orderBy = sort
    order_by = sort

    def limit(self, n: int) -> "DataFrame":
        return self._with(L.Limit(self.plan, n))

    def union(self, other: "DataFrame") -> "DataFrame":
        return self._with(L.Union(self.plan, other.plan))

    unionAll = union

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """INTERSECT (distinct): rows present in both sides, NULLs
        matching NULLs (reference: basicLogicalOperators Intersect ->
        ReplaceIntersectWithSemiJoin)."""
        return self._with(set_op_plan(self.plan, other.plan,
                                      "left_semi"))

    def except_(self, other: "DataFrame") -> "DataFrame":
        """EXCEPT (distinct): rows of this side absent from the other
        (ReplaceExceptWithAntiJoin)."""
        return self._with(set_op_plan(self.plan, other.plan,
                                      "left_anti"))

    subtract = except_

    def exceptAll(self, other: "DataFrame") -> "DataFrame":
        raise AnalysisError(
            "EXCEPT ALL (multiset) is not supported; use except_ for "
            "the DISTINCT form")

    def distinct(self) -> "DataFrame":
        """Deduplicate rows: an aggregate grouping on every column with no
        aggregate functions (reference: Dataset.distinct -> Deduplicate ->
        Aggregate rewrite)."""
        cols = [ColumnRef(n) for n in self.plan.schema().names]
        return self._with(L.Aggregate(self.plan, cols, []))

    def drop_duplicates(self, subset: Optional[Sequence[str]] = None
                        ) -> "DataFrame":
        """Keep one row per key (an arbitrary one, like the reference's
        Deduplicate): row_number over a window partitioned on the subset,
        filtered to 1."""
        if subset is None:
            return self.distinct()
        missing = [n for n in subset if n not in self.plan.schema().names]
        if missing:
            raise AnalysisError(f"dropDuplicates: unknown columns {missing}")
        if set(subset) == set(self.plan.schema().names):
            return self.distinct()
        from .window import Window, row_number
        w = Window.partition_by(*[ColumnRef(n) for n in subset]) \
            .order_by(ColumnRef(subset[0]))
        keep_cols = self.plan.schema().names
        rn = "__rn"
        while rn in keep_cols:  # never clobber a real column
            rn = "_" + rn
        return (self.with_column(rn, row_number().over(w))
                .filter(ColumnRef(rn) == 1)
                .select(*[ColumnRef(n) for n in keep_cols]))

    dropDuplicates = drop_duplicates

    # -- metadata -----------------------------------------------------------

    @property
    def schema(self) -> T.Schema:
        return self.plan.schema()

    @property
    def columns(self) -> List[str]:
        return self.plan.schema().names

    def explain(self, extended: bool = False, runtime: bool = False,
                analysis: bool = False, rules: bool = False) -> None:
        """Print the plan. runtime=True re-executes and annotates each
        operator with its output row count (SQLMetrics analog);
        analysis=True appends the pre-compile static analyzer's
        findings (spark_tpu/analysis/) — plan-level without executing.
        Combined with runtime=True, jaxpr-level findings ride along
        when the jaxpr half ran for that execution: always under
        `spark_tpu.sql.analysis.jaxpr=on`; under the default `auto`
        only when an observability output is configured or strict mode
        is set. rules=True appends the per-rule optimizer trace
        (effectiveness counts; before/after diffs under
        `spark_tpu.sql.planChangeLog`)."""
        qe = self._qe()
        if runtime:
            qe.execute_batch()
        print(qe.explain(extended, runtime=runtime, analysis=analysis,
                         rules=rules))

    # -- actions ------------------------------------------------------------

    def _qe(self):
        from .execution.executor import QueryExecution
        return QueryExecution(self.session, self.plan)

    def collect(self) -> pa.Table:
        return self._qe().collect()

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    @property
    def stat(self) -> "DataFrameStat":
        return DataFrameStat(self)

    def cache(self) -> "DataFrame":
        """Mark this plan for materialization on first action; later
        queries containing an equal subtree read the cached batch
        (reference: CacheManager.scala plan-fingerprint cache)."""
        self.session.mark_cache(self.plan)
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        self.session.uncache(self.plan)
        return self

    def write_stream(self, checkpoint_dir: str,
                     output_mode: str = "complete",
                     sink_path: str = None):
        """Start a micro-batch streaming query over this plan (the plan
        must contain one streaming source; reference:
        DataStreamWriter.start -> MicroBatchExecution). `sink_path`
        adds a FileStreamSink: per-batch parquet parts committed by an
        atomic `_metadata` manifest (read back with
        spark_tpu.streaming.read_sink), exactly-once under
        crash-replay."""
        from .streaming import StreamingQuery, _StreamSource
        streams = []

        def walk(n):
            if isinstance(n, _StreamSource):
                streams.append(n.stream)
            for c in n.children:
                walk(c)

        walk(self.plan)
        if len(streams) != 1:
            raise AnalysisError(
                f"write_stream needs exactly one streaming source "
                f"(found {len(streams)})")
        return StreamingQuery(self.session, self.plan, streams[0],
                              checkpoint_dir, output_mode,
                              sink_path=sink_path)

    writeStream = write_stream

    def checkpoint(self) -> "DataFrame":
        """Materialize and truncate lineage (reference: RDD.checkpoint /
        Dataset.checkpoint). With spark_tpu.sql.checkpoint.dir set, the
        result persists as Parquet (ReliableCheckpointRDD analog) and the
        returned frame scans it from disk; otherwise it is held in
        memory (localCheckpoint)."""
        import os
        import uuid

        ckpt_dir = str(self.session.conf.get("spark_tpu.sql.checkpoint.dir"))
        if ckpt_dir:
            path = os.path.join(ckpt_dir, f"ckpt-{uuid.uuid4().hex[:12]}")
            self.write.parquet(path)
            return self.session.read_parquet(path)
        return self.local_checkpoint()

    def local_checkpoint(self) -> "DataFrame":
        """In-memory materialization + lineage truncation (reference:
        Dataset.localCheckpoint — never reliable, ignores checkpoint.dir).
        The source name is unique per call: the fingerprint-keyed data
        cache would otherwise cross-match distinct checkpoints."""
        import uuid

        from .io.sources import ArrowTableSource
        table = self.collect()
        name = f"__checkpoint_{uuid.uuid4().hex[:12]}__"
        return self._with(L.Scan(ArrowTableSource(name, table)))

    localCheckpoint = local_checkpoint

    def to_pandas(self):
        return self.collect().to_pandas()

    toPandas = to_pandas

    def count(self) -> int:
        from .expr_agg import AggExpr, Count
        agg = L.Aggregate(self.plan, [], [AggExpr(Count(None), "count")])
        table = DataFrame(self.session, agg).collect()
        return table.column("count")[0].as_py()

    def show(self, n: int = 20) -> None:
        print(self.limit(n).to_pandas().to_string())


class DataFrameWriter:
    """df.write.mode(...).parquet(path) (reference: DataFrameWriter +
    FileFormatWriter.scala). Writes a directory of part files, so the
    output reads back through the same directory-dataset scan path."""

    _MODES = ("error", "errorifexists", "overwrite", "append", "ignore")

    def __init__(self, df: DataFrame):
        self._df = df
        self._mode = "error"

    def mode(self, m: str) -> "DataFrameWriter":
        m = m.lower()
        if m not in self._MODES:
            raise ValueError(f"unknown write mode {m!r}; one of "
                             f"{self._MODES}")
        self._mode = m
        return self

    def parquet(self, path: str) -> None:
        import glob
        import os
        import shutil

        import pyarrow.parquet as pq

        exists = os.path.exists(path) and (
            not os.path.isdir(path) or bool(os.listdir(path)))
        if exists:
            if self._mode in ("error", "errorifexists"):
                raise FileExistsError(
                    f"path {path!r} already exists (write mode=error)")
            if self._mode == "ignore":
                return
        # execute BEFORE touching the target: a failing query must not
        # destroy the previous output under mode=overwrite
        table = self._df.collect()
        if exists and self._mode == "overwrite":
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.remove(path)
        if os.path.exists(path) and not os.path.isdir(path):
            raise NotADirectoryError(
                f"append target {path!r} is a file, not a dataset "
                f"directory")
        os.makedirs(path, exist_ok=True)
        n = len(glob.glob(os.path.join(path, "part-*.parquet")))
        pq.write_table(table,
                       os.path.join(path, f"part-{n:05d}.parquet"))


class DataFrameStat:
    """df.stat.* (reference: DataFrameStatFunctions — the sketch entry
    points backed by common/sketch)."""

    def __init__(self, df: DataFrame):
        self._df = df

    def _column_device(self, col_name: str):
        from .execution.executor import QueryExecution
        qe = QueryExecution(self._df.session,
                            L.Project(self._df.plan, [ColumnRef(col_name)]))
        batch, _, _ = qe.execute_batch()
        c = batch.columns[batch.names[0]]
        sel = batch.selection_mask()
        mask = sel if c.validity is None else (sel & c.validity)
        return c.data, mask

    def bloom_filter(self, col_name: str, expected_items: int,
                     fpp: float = 0.03):
        from .sketch import BloomFilter
        data, mask = self._column_device(col_name)
        return BloomFilter.build(data, expected_items, fpp, mask=mask)

    bloomFilter = bloom_filter

    def count_min_sketch(self, col_name: str, eps: float = 0.001,
                         confidence: float = 0.99):
        from .sketch import CountMinSketch
        data, mask = self._column_device(col_name)
        return CountMinSketch.build(data, eps, confidence, mask=mask)

    countMinSketch = count_min_sketch


def set_op_plan(lp: L.LogicalPlan, rp: L.LogicalPlan,
                how: str) -> L.LogicalPlan:
    """INTERSECT/EXCEPT (distinct) as a tagged union + group-by: each
    side contributes a presence flag, one aggregate groups on every
    column (group keys are natively NULL-safe and support every dtype),
    and a filter keeps groups present on the right side(s). Equivalent
    to the reference's ReplaceIntersectWithSemiJoin /
    ReplaceExceptWithAntiJoin rewrites, expressed in the aggregate
    algebra the TPU engine is best at."""
    from .expr import Literal
    from .expr_agg import Max
    ls, rs = lp.schema(), rp.schema()
    if len(ls.fields) != len(rs.fields):
        raise AnalysisError(
            f"set operation needs equal column counts "
            f"({len(ls.fields)} vs {len(rs.fields)})")
    lnames = ls.names
    tag_l = L.Project(lp, [ColumnRef(n) for n in lnames]
                      + [Alias(Literal(1), "__in_l"),
                         Alias(Literal(0), "__in_r")])
    # right columns rename to the left's so the union lines up
    tag_r = L.Project(rp, [Alias(ColumnRef(rn), ln)
                           for rn, ln in zip(rs.names, lnames)]
                      + [Alias(Literal(0), "__in_l"),
                         Alias(Literal(1), "__in_r")])
    u = L.Union(tag_l, tag_r)
    g = L.Aggregate(u, [ColumnRef(n) for n in lnames],
                    [AggExpr(Max(ColumnRef("__in_l")), "__lf"),
                     AggExpr(Max(ColumnRef("__in_r")), "__rf")])
    lf = ColumnRef("__lf")
    rf = ColumnRef("__rf")
    cond = (lf == Literal(1)) & (rf == Literal(1)) \
        if how == "left_semi" else \
        (lf == Literal(1)) & (rf == Literal(0))
    return L.Project(L.Filter(g, cond),
                     [ColumnRef(n) for n in lnames])


class GroupedData:
    """Reference: RelationalGroupedDataset."""

    def __init__(self, df: DataFrame, group_exprs: List[Expression]):
        self._df = df
        self._groups = group_exprs

    def agg(self, *aggs) -> DataFrame:
        agg_exprs = []
        for a in aggs:
            if isinstance(a, AggExpr):
                agg_exprs.append(a)
            elif isinstance(a, Alias) and isinstance(a.child, AggregateFunction):
                agg_exprs.append(AggExpr(a.child, a.name()))
            elif isinstance(a, AggregateFunction):
                agg_exprs.append(AggExpr(a, repr(a)))
            else:
                raise AnalysisError(f"not an aggregate: {a!r}")
        plan = L.Aggregate(self._df.plan, self._groups, agg_exprs)
        return DataFrame(self._df.session, plan)

    def count(self) -> DataFrame:
        return self.agg(AggExpr(Count(None), "count"))

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """Grouped-map pandas UDF: ``fn(pdf) -> pdf`` per key group
        (reference: FlatMapGroupsInPandasExec over Arrow batches,
        `ArrowEvalPythonExec.scala:1` family). The input materializes
        host-side — the same stage cut the reference makes, minus the
        worker sockets. `schema` is "name type, ..." or a T.Schema."""
        import pandas as pd
        from . import types as T
        from .udf import _parse_return_type

        if isinstance(schema, str):
            fields = []
            for part in schema.split(","):
                name, typ = part.strip().rsplit(" ", 1)
                fields.append(T.Field(name.strip(),
                                      _parse_return_type(typ), True))
            out_schema = T.Schema(fields)
        else:
            out_schema = schema
        key_names = [g.name() for g in self._groups]
        pdf = self._df.select(
            *([*self._groups] + [ColumnRef(n)
                                 for n in self._df.plan.schema().names
                                 if n not in {g.name()
                                              for g in self._groups}])
        ).to_pandas() if self._groups else self._df.to_pandas()
        if key_names:
            groups = [g.reset_index(drop=True)
                      for _, g in pdf.groupby(key_names, sort=False,
                                              dropna=False)]
        else:
            groups = [pdf]
        mode = str(self._df.session.conf.get(
            "spark_tpu.sql.udf.mode") or "inprocess")
        if mode == "worker":
            # out-of-process lane: one EVAL frame per key group through
            # the session's worker pool (FlatMapGroupsInPandasExec)
            from .execution.python_eval import eval_grouped_map_worker
            pieces = eval_grouped_map_worker(
                self._df.session, fn, groups,
                [f.name for f in out_schema.fields])
        else:
            pieces = [fn(g) for g in groups]
        out = pd.concat(pieces, ignore_index=True) if pieces else \
            pd.DataFrame({f.name: [] for f in out_schema.fields})
        out = out[[f.name for f in out_schema.fields]]
        for f in out_schema.fields:  # pin declared dtypes
            if not isinstance(f.dtype, (T.StringType, T.DateType)):
                out[f.name] = out[f.name].astype(f.dtype.np_dtype)
        # a plain in-memory scan — never registered, so the session
        # catalog stays free of internal temp tables
        return self._df.session.create_dataframe(out, "__grouped_map__")

    applyInPandas = apply_in_pandas

"""Aggregate functions as declarative accumulator specs.

Mirrors the reference's DeclarativeAggregate contract
(`sql/catalyst/.../expressions/aggregate/interfaces.scala`): each function
declares flat *accumulator* columns with an associative/commutative reduce
kind (sum/min/max), an ``update`` producing per-row contributions (already
neutralized for NULL/unselected rows), and a host-side ``finalize``.
Because every reduce is associative+commutative, the same spec serves the
single-chip segment-reduce, the partial/final split across a shuffle, and
`psum`-tree merges across the mesh — replacing Spark's partial/final
physical planning in `AggUtils.scala`.

Decimal/integer SUM accumulates in int64 mod 2^64 (integer adds wrap):
intermediate wraparound is harmless because modular arithmetic recovers
the true sum whenever the final value fits int64 — which is the bound of
the scaled-decimal representation itself. This replaces the reference's
Decimal.scala + `UnsafeFixedWidthAggregationMap.java:39` with plain
vector adds (and the MXU limb kernel in execution/pallas_groupby.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from . import types as T
from .columnar import Batch
from .expr import Expression, Vec, cast_vec, _and_valid


@dataclass(frozen=True)
class AccSpec:
    """One accumulator column: reduce kind + device dtype + neutral value.

    `width` bounds the per-row contribution: width=8 promises values in
    [0, 256), letting the MXU group-by kernel carry the row as a single
    bf16 limb instead of eight (counts are the common case)."""

    suffix: str
    np_dtype: np.dtype
    reduce: str  # 'sum' | 'min' | 'max'
    width: int = 64

    @property
    def neutral(self):
        if self.reduce == "sum":
            return np.zeros((), self.np_dtype)
        if self.reduce == "min":
            return _max_of(self.np_dtype)
        return _min_of(self.np_dtype)


def _max_of(dt):
    return np.array(np.finfo(dt).max if np.issubdtype(dt, np.floating)
                    else np.iinfo(dt).max, dt)


def _min_of(dt):
    return np.array(np.finfo(dt).min if np.issubdtype(dt, np.floating)
                    else np.iinfo(dt).min, dt)


class AggregateFunction:
    """Base class. `child` may be None (COUNT(*))."""

    def __init__(self, child: Optional[Expression] = None):
        self.child = child
        self.children = (child,) if child is not None else ()

    def result_type(self, schema: T.Schema) -> T.DataType:
        raise NotImplementedError

    def result_nullable(self, schema: T.Schema) -> bool:
        return True

    def accumulators(self, schema: T.Schema) -> List[AccSpec]:
        raise NotImplementedError

    def update(self, batch: Batch, sel) -> List:
        """Per-row contribution arrays, one per accumulator, with the
        accumulator's neutral element wherever the row is unselected or
        the input is NULL."""
        raise NotImplementedError

    def finalize(self, accs: List[np.ndarray], schema: T.Schema):
        """host: accumulator arrays (one value per group) -> (np data, validity|None)."""
        raise NotImplementedError

    def device_finalize(self, accs: List, schema: T.Schema):
        """Traced finalize: accumulator device arrays -> (data, validity|None).
        Used when the aggregate output feeds further device operators; the
        host `finalize` is the exact (arbitrary-precision) egress path."""
        raise NotImplementedError

    def references(self) -> set:
        return self.child.references() if self.child is not None else set()

    def alias(self, name: str) -> "AggExpr":
        return AggExpr(self, name)

    def over(self, spec) -> "Expression":
        """sum(x).over(Window.partitionBy(...)) — turn this aggregate into
        a window expression (pyspark's Column.over)."""
        from .window import AGG_WINDOW_KINDS, WindowExpr
        kind = AGG_WINDOW_KINDS.get(type(self).__name__)
        if kind is None:
            raise NotImplementedError(
                f"{type(self).__name__} is not supported over a window")
        return WindowExpr(kind, self.child, spec)

    def _eval_child(self, batch: Batch, sel) -> Tuple[Vec, object]:
        v = self.child.eval(batch)
        m = sel
        if v.validity is not None:
            m = v.validity if m is None else (m & v.validity)
        return v, m

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.child!r})"


class Count(AggregateFunction):
    def result_type(self, schema):
        return T.LONG

    def result_nullable(self, schema):
        return False

    def accumulators(self, schema):
        return [AccSpec("count", np.dtype(np.int64), "sum", width=8)]

    def update(self, batch, sel):
        if self.child is None:
            m = batch.selection_mask() if sel is None else sel
            return [m.astype(jnp.int64)]
        _, m = self._eval_child(batch, sel)
        if m is None:
            m = jnp.ones((batch.capacity,), jnp.bool_)
        return [m.astype(jnp.int64)]

    def finalize(self, accs, schema):
        return accs[0].astype(np.int64), None

    def device_finalize(self, accs, schema):
        return accs[0], None

    def __repr__(self):
        return f"count({'*' if self.child is None else repr(self.child)})"


class Sum(AggregateFunction):
    def result_type(self, schema):
        dt = self.child.dtype(schema)
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(min(38, dt.precision + 10), dt.scale)
        if isinstance(dt, T.IntegralType):
            return T.LONG
        return T.DOUBLE

    def accumulators(self, schema):
        dt = self.child.dtype(schema)
        # int64 sums accumulate mod 2^64 (adds wrap): the final value is
        # exact whenever the true sum fits int64, which is the bound of
        # our scaled-decimal representation anyway — no multi-limb
        # accumulator needed (the MXU kernel limb-decomposes internally)
        if isinstance(dt, (T.DecimalType, T.IntegralType)):
            from .expr import static_unsigned_bits
            w = static_unsigned_bits(self.child) if \
                isinstance(dt, T.IntegralType) else None
            return [AccSpec("sum", np.dtype(np.int64), "sum",
                            width=min(w, 64) if w else 64),
                    AccSpec("cnt", np.dtype(np.int64), "sum", width=8)]
        return [AccSpec("sum", np.dtype(np.float64), "sum"),
                AccSpec("cnt", np.dtype(np.int64), "sum", width=8)]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        spec = self.accumulators(batch.schema())[0]
        x = v.data.astype(spec.np_dtype)
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is None:
            return [x, cnt]
        return [jnp.where(m, x, jnp.zeros_like(x)),
                jnp.where(m, cnt, jnp.zeros_like(cnt))]

    def finalize(self, accs, schema):
        total, cnt = accs
        return total, cnt > 0

    def device_finalize(self, accs, schema):
        total, cnt = accs
        return total, cnt > 0


def decimal_avg_halfup(total, safe_cnt, extra: int):
    """Traced exact integer HALF_UP of (total * extra) / cnt, split as
    q*extra + round(r*extra/cnt) so intermediates stay in int64 (shared
    by Avg.device_finalize and windowed averages)."""
    extra = jnp.int64(extra)
    safe = safe_cnt.astype(jnp.int64)
    absn = jnp.abs(total)
    q0 = absn // safe
    r0 = absn - q0 * safe
    frac = (r0 * extra + safe // 2) // safe  # HALF_UP
    mag = q0 * extra + frac
    return jnp.where(total < 0, -mag, mag)


class Avg(AggregateFunction):
    def result_type(self, schema):
        dt = self.child.dtype(schema)
        if isinstance(dt, T.DecimalType):
            # reference: avg(decimal(p,s)) -> decimal(p+4, s+4)
            return T.DecimalType(min(38, dt.precision + 4), min(38, dt.scale + 4))
        return T.DOUBLE

    def accumulators(self, schema):
        return Sum(self.child).accumulators(schema)

    def update(self, batch, sel):
        return Sum(self.child).update(batch, sel)

    def finalize(self, accs, schema):
        dt = self.child.dtype(schema)
        if isinstance(dt, T.DecimalType):
            total, cnt = accs
            out_dt = self.result_type(schema)
            extra = 10 ** (out_dt.scale - dt.scale)
            vals = []
            for tot, c in zip(total, cnt):
                if c == 0:
                    vals.append(0)
                    continue
                tot = int(tot) * extra
                q, r = divmod(tot, int(c)) if tot >= 0 else \
                    (-((-tot) // int(c)), -((-tot) % int(c)))
                # HALF_UP
                if 2 * abs(r) >= c:
                    q += 1 if tot >= 0 else -1
                vals.append(q)
            return np.array(vals, dtype=np.int64), cnt > 0
        total, cnt = accs
        safe = np.where(cnt > 0, cnt, 1)
        return (total / safe).astype(np.float64), cnt > 0

    def device_finalize(self, accs, schema):
        dt = self.child.dtype(schema)
        if isinstance(dt, T.DecimalType):
            # exact integer HALF_UP, matching the host `finalize` digit
            # for digit (the former float64 round-trip diverged in the
            # last digit — and TPU f64 is emulated, compounding it)
            total, cnt = accs
            out_dt = self.result_type(schema)
            safe = jnp.where(cnt > 0, cnt, 1)
            return decimal_avg_halfup(
                total, safe, 10 ** (out_dt.scale - dt.scale)), cnt > 0
        total, cnt = accs
        safe = jnp.where(cnt > 0, cnt, 1)
        return (total / safe).astype(jnp.float64), cnt > 0


class CountDistinct(AggregateFunction):
    """count(DISTINCT x): a planning marker — the optimizer's
    RewriteDistinctAggregates expands it into a two-level aggregate
    (dedupe on (groups, x), then count), the single-distinct case of the
    reference's `AggUtils.planAggregateWithOneDistinct`. It never reaches
    physical execution itself."""

    def result_type(self, schema):
        return T.LONG

    def result_nullable(self, schema):
        return False

    def accumulators(self, schema):
        raise NotImplementedError(
            "count(DISTINCT) must be rewritten before execution")

    def __repr__(self):
        return f"count(DISTINCT {self.child!r})"


class _CentralMoment(AggregateFunction):
    """Variance/stddev via raw power sums (cnt, sum x, sum x^2) — all
    three are plain associative SUM accumulators, so the partial/final
    split and mesh psum merges work unchanged (the reference's
    `CentralMomentAgg.scala` carries (n, avg, m2) with a merge formula
    instead; power sums trade a little conditioning for fitting the
    declarative reduce model, and the f64 accumulator is ample for the
    engine's test/bench ranges)."""

    _sample = True   # ddof=1
    _sqrt = False

    def result_type(self, schema):
        return T.DOUBLE

    def accumulators(self, schema):
        return [AccSpec("cnt", np.dtype(np.int64), "sum", width=8),
                AccSpec("sx", np.dtype(np.float64), "sum"),
                AccSpec("sxx", np.dtype(np.float64), "sum")]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        x = cast_vec(v, T.DOUBLE).data
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is not None:
            x = jnp.where(m, x, 0.0)
            cnt = jnp.where(m, cnt, 0)
        return [cnt, x, x * x]

    def _finish(self, cnt, sx, sxx, xp):
        ddof = 1 if self._sample else 0
        denom = xp.maximum(cnt - ddof, 1)
        mean = sx / xp.maximum(cnt, 1)
        m2 = xp.maximum(sxx - sx * mean, 0.0)  # clamp the cancellation
        var = m2 / denom
        out = xp.sqrt(var) if self._sqrt else var
        valid = cnt > (1 if self._sample else 0)
        return out, valid

    def finalize(self, accs, schema):
        cnt, sx, sxx = accs
        return self._finish(np.asarray(cnt, np.float64), sx, sxx, np)

    def device_finalize(self, accs, schema):
        cnt, sx, sxx = accs
        return self._finish(cnt.astype(jnp.float64), sx, sxx, jnp)


class VarianceSamp(_CentralMoment):
    _sample, _sqrt = True, False


class VariancePop(_CentralMoment):
    _sample, _sqrt = False, False


class StddevSamp(_CentralMoment):
    _sample, _sqrt = True, True


class StddevPop(_CentralMoment):
    _sample, _sqrt = False, True


class _MinMax(AggregateFunction):
    _reduce = "min"

    def result_type(self, schema):
        return self.child.dtype(schema)

    def accumulators(self, schema):
        dt = self.child.dtype(schema)
        return [AccSpec(self._reduce, dt.np_dtype, self._reduce),
                AccSpec("cnt", np.dtype(np.int64), "sum", width=8)]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        spec = self.accumulators(batch.schema())[0]
        x = v.data.astype(spec.np_dtype)
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is None:
            return [x, cnt]
        return [jnp.where(m, x, jnp.asarray(spec.neutral)),
                jnp.where(m, cnt, jnp.zeros_like(cnt))]

    def finalize(self, accs, schema):
        return accs[0], accs[1] > 0

    def device_finalize(self, accs, schema):
        return accs[0], accs[1] > 0


class Min(_MinMax):
    _reduce = "min"


class Max(_MinMax):
    _reduce = "max"


@dataclass
class AggExpr:
    """A named aggregate output column (reference: AggregateExpression)."""

    func: AggregateFunction
    out_name: str

    def __repr__(self):
        return f"{self.func!r} AS {self.out_name}"

"""Aggregate functions as declarative accumulator specs.

Mirrors the reference's DeclarativeAggregate contract
(`sql/catalyst/.../expressions/aggregate/interfaces.scala`): each function
declares flat *accumulator* columns with an associative/commutative reduce
kind (sum/min/max), an ``update`` producing per-row contributions (already
neutralized for NULL/unselected rows), and a host-side ``finalize``.
Because every reduce is associative+commutative, the same spec serves the
single-chip segment-reduce, the partial/final split across a shuffle, and
`psum`-tree merges across the mesh — replacing Spark's partial/final
physical planning in `AggUtils.scala`.

Decimal/integer SUM accumulates in int64 mod 2^64 (integer adds wrap):
intermediate wraparound is harmless because modular arithmetic recovers
the true sum whenever the final value fits int64 — which is the bound of
the scaled-decimal representation itself. This replaces the reference's
Decimal.scala + `UnsafeFixedWidthAggregationMap.java:39` with plain
vector adds (and the MXU limb kernel in execution/pallas_groupby.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from . import types as T
from .columnar import Batch
from .expr import AnalysisError, Expression, Vec, cast_vec, _and_valid


@dataclass(frozen=True)
class AccSpec:
    """One accumulator column: reduce kind + device dtype + neutral value.

    `width` bounds the per-row contribution: width=8 promises values in
    [0, 256), letting the MXU group-by kernel carry the row as a single
    bf16 limb instead of eight (counts are the common case)."""

    suffix: str
    np_dtype: np.dtype
    reduce: str  # 'sum' | 'min' | 'max'
    width: int = 64

    @property
    def neutral(self):
        if self.reduce == "sum":
            return np.zeros((), self.np_dtype)
        if self.reduce == "min":
            return _max_of(self.np_dtype)
        return _min_of(self.np_dtype)


def _max_of(dt):
    if np.dtype(dt) == np.dtype(np.bool_):
        return np.array(True)
    return np.array(np.finfo(dt).max if np.issubdtype(dt, np.floating)
                    else np.iinfo(dt).max, dt)


def _min_of(dt):
    if np.dtype(dt) == np.dtype(np.bool_):
        return np.array(False)
    return np.array(np.finfo(dt).min if np.issubdtype(dt, np.floating)
                    else np.iinfo(dt).min, dt)


class AggregateFunction:
    """Base class. `child` may be None (COUNT(*))."""

    # True for position-packed aggregates (First/Last/AnyValue) whose
    # update() must receive a globally unique row base so that merges
    # across chunks/shards never tie on in-chunk position (a tie lets
    # the two word accumulators of a 64-bit value pick DIFFERENT rows,
    # fabricating a value present in no input row).
    uses_row_base = False

    def __init__(self, child: Optional[Expression] = None):
        self.child = child
        self.children = (child,) if child is not None else ()

    def result_type(self, schema: T.Schema) -> T.DataType:
        raise NotImplementedError

    def result_nullable(self, schema: T.Schema) -> bool:
        return True

    def accumulators(self, schema: T.Schema) -> List[AccSpec]:
        raise NotImplementedError

    def update(self, batch: Batch, sel) -> List:
        """Per-row contribution arrays, one per accumulator, with the
        accumulator's neutral element wherever the row is unselected or
        the input is NULL."""
        raise NotImplementedError

    def finalize(self, accs: List[np.ndarray], schema: T.Schema):
        """host: accumulator arrays (one value per group) -> (np data, validity|None)."""
        raise NotImplementedError

    def device_finalize(self, accs: List, schema: T.Schema):
        """Traced finalize: accumulator device arrays -> (data, validity|None).
        Used when the aggregate output feeds further device operators; the
        host `finalize` is the exact (arbitrary-precision) egress path."""
        raise NotImplementedError

    def references(self) -> set:
        out = set()
        for c in self.children:
            out |= c.references()
        return out

    def with_args(self, args) -> "AggregateFunction":
        """Copy with argument expressions replaced — the ONE seam plan
        rewriters (scope rewrite, project collapse, map_expressions)
        use, so multi-argument aggregates (corr/covar) are never
        silently skipped by single-child walks."""
        import copy
        nf = copy.copy(self)
        nf.children = tuple(args)
        if len(args) == 1:
            nf.child = args[0]
        return nf

    def alias(self, name: str) -> "AggExpr":
        return AggExpr(self, name)

    def over(self, spec) -> "Expression":
        """sum(x).over(Window.partitionBy(...)) — turn this aggregate into
        a window expression (pyspark's Column.over)."""
        from .window import AGG_WINDOW_KINDS, WindowExpr
        kind = AGG_WINDOW_KINDS.get(type(self).__name__)
        if kind is None:
            raise NotImplementedError(
                f"{type(self).__name__} is not supported over a window")
        return WindowExpr(kind, self.child, spec)

    def _eval_child(self, batch: Batch, sel) -> Tuple[Vec, object]:
        v = self.child.eval(batch)
        m = sel
        if v.validity is not None:
            m = v.validity if m is None else (m & v.validity)
        return v, m

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.child!r})"


class Count(AggregateFunction):
    def result_type(self, schema):
        return T.LONG

    def result_nullable(self, schema):
        return False

    def accumulators(self, schema):
        return [AccSpec("count", np.dtype(np.int64), "sum", width=8)]

    def update(self, batch, sel):
        if self.child is None:
            m = batch.selection_mask() if sel is None else sel
            return [m.astype(jnp.int64)]
        _, m = self._eval_child(batch, sel)
        if m is None:
            m = jnp.ones((batch.capacity,), jnp.bool_)
        return [m.astype(jnp.int64)]

    def finalize(self, accs, schema):
        return accs[0].astype(np.int64), None

    def device_finalize(self, accs, schema):
        return accs[0], None

    def __repr__(self):
        return f"count({'*' if self.child is None else repr(self.child)})"


class Sum(AggregateFunction):
    def result_type(self, schema):
        dt = self.child.dtype(schema)
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(min(38, dt.precision + 10), dt.scale)
        if isinstance(dt, T.IntegralType):
            return T.LONG
        return T.DOUBLE

    def accumulators(self, schema):
        dt = self.child.dtype(schema)
        # int64 sums accumulate mod 2^64 (adds wrap): the final value is
        # exact whenever the true sum fits int64, which is the bound of
        # our scaled-decimal representation anyway — no multi-limb
        # accumulator needed (the MXU kernel limb-decomposes internally)
        if isinstance(dt, (T.DecimalType, T.IntegralType)):
            from .expr import static_unsigned_bits
            w = static_unsigned_bits(self.child) if \
                isinstance(dt, T.IntegralType) else None
            return [AccSpec("sum", np.dtype(np.int64), "sum",
                            width=min(w, 64) if w else 64),
                    AccSpec("cnt", np.dtype(np.int64), "sum", width=8)]
        return [AccSpec("sum", np.dtype(np.float64), "sum"),
                AccSpec("cnt", np.dtype(np.int64), "sum", width=8)]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        spec = self.accumulators(batch.schema())[0]
        x = v.data.astype(spec.np_dtype)
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is None:
            return [x, cnt]
        return [jnp.where(m, x, jnp.zeros_like(x)),
                jnp.where(m, cnt, jnp.zeros_like(cnt))]

    def finalize(self, accs, schema):
        total, cnt = accs
        return total, cnt > 0

    def device_finalize(self, accs, schema):
        total, cnt = accs
        return total, cnt > 0


def decimal_avg_halfup(total, safe_cnt, extra: int):
    """Traced exact integer HALF_UP of (total * extra) / cnt, split as
    q*extra + round(r*extra/cnt) so intermediates stay in int64 (shared
    by Avg.device_finalize and windowed averages)."""
    extra = jnp.int64(extra)
    safe = safe_cnt.astype(jnp.int64)
    absn = jnp.abs(total)
    q0 = absn // safe
    r0 = absn - q0 * safe
    frac = (r0 * extra + safe // 2) // safe  # HALF_UP
    mag = q0 * extra + frac
    return jnp.where(total < 0, -mag, mag)


class Avg(AggregateFunction):
    def result_type(self, schema):
        dt = self.child.dtype(schema)
        if isinstance(dt, T.DecimalType):
            # reference: avg(decimal(p,s)) -> decimal(p+4, s+4)
            return T.DecimalType(min(38, dt.precision + 4), min(38, dt.scale + 4))
        return T.DOUBLE

    def accumulators(self, schema):
        return Sum(self.child).accumulators(schema)

    def update(self, batch, sel):
        return Sum(self.child).update(batch, sel)

    def finalize(self, accs, schema):
        dt = self.child.dtype(schema)
        if isinstance(dt, T.DecimalType):
            total, cnt = accs
            out_dt = self.result_type(schema)
            extra = 10 ** (out_dt.scale - dt.scale)
            vals = []
            for tot, c in zip(total, cnt):
                if c == 0:
                    vals.append(0)
                    continue
                tot = int(tot) * extra
                q, r = divmod(tot, int(c)) if tot >= 0 else \
                    (-((-tot) // int(c)), -((-tot) % int(c)))
                # HALF_UP
                if 2 * abs(r) >= c:
                    q += 1 if tot >= 0 else -1
                vals.append(q)
            return np.array(vals, dtype=np.int64), cnt > 0
        total, cnt = accs
        safe = np.where(cnt > 0, cnt, 1)
        return (total / safe).astype(np.float64), cnt > 0

    def device_finalize(self, accs, schema):
        dt = self.child.dtype(schema)
        if isinstance(dt, T.DecimalType):
            # exact integer HALF_UP, matching the host `finalize` digit
            # for digit (the former float64 round-trip diverged in the
            # last digit — and TPU f64 is emulated, compounding it)
            total, cnt = accs
            out_dt = self.result_type(schema)
            safe = jnp.where(cnt > 0, cnt, 1)
            return decimal_avg_halfup(
                total, safe, 10 ** (out_dt.scale - dt.scale)), cnt > 0
        total, cnt = accs
        safe = jnp.where(cnt > 0, cnt, 1)
        return (total / safe).astype(jnp.float64), cnt > 0


class CountDistinct(AggregateFunction):
    """count(DISTINCT x): a planning marker — the optimizer's
    RewriteDistinctAggregates expands it into a two-level aggregate
    (dedupe on (groups, x), then count), the single-distinct case of the
    reference's `AggUtils.planAggregateWithOneDistinct`. It never reaches
    physical execution itself."""

    def result_type(self, schema):
        return T.LONG

    def result_nullable(self, schema):
        return False

    def accumulators(self, schema):
        raise NotImplementedError(
            "count(DISTINCT) must be rewritten before execution")

    def __repr__(self):
        return f"count(DISTINCT {self.child!r})"


class _CentralMoment(AggregateFunction):
    """Variance/stddev via raw power sums (cnt, sum x, sum x^2) — all
    three are plain associative SUM accumulators, so the partial/final
    split and mesh psum merges work unchanged (the reference's
    `CentralMomentAgg.scala` carries (n, avg, m2) with a merge formula
    instead; power sums trade a little conditioning for fitting the
    declarative reduce model, and the f64 accumulator is ample for the
    engine's test/bench ranges)."""

    _sample = True   # ddof=1
    _sqrt = False

    def result_type(self, schema):
        return T.DOUBLE

    def accumulators(self, schema):
        return [AccSpec("cnt", np.dtype(np.int64), "sum", width=8),
                AccSpec("sx", np.dtype(np.float64), "sum"),
                AccSpec("sxx", np.dtype(np.float64), "sum")]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        x = cast_vec(v, T.DOUBLE).data
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is not None:
            x = jnp.where(m, x, 0.0)
            cnt = jnp.where(m, cnt, 0)
        return [cnt, x, x * x]

    def _finish(self, cnt, sx, sxx, xp):
        ddof = 1 if self._sample else 0
        denom = xp.maximum(cnt - ddof, 1)
        mean = sx / xp.maximum(cnt, 1)
        m2 = xp.maximum(sxx - sx * mean, 0.0)  # clamp the cancellation
        var = m2 / denom
        out = xp.sqrt(var) if self._sqrt else var
        valid = cnt > (1 if self._sample else 0)
        return out, valid

    def finalize(self, accs, schema):
        cnt, sx, sxx = accs
        return self._finish(np.asarray(cnt, np.float64), sx, sxx, np)

    def device_finalize(self, accs, schema):
        cnt, sx, sxx = accs
        return self._finish(cnt.astype(jnp.float64), sx, sxx, jnp)


class VarianceSamp(_CentralMoment):
    _sample, _sqrt = True, False


class VariancePop(_CentralMoment):
    _sample, _sqrt = False, False


class StddevSamp(_CentralMoment):
    _sample, _sqrt = True, True


class StddevPop(_CentralMoment):
    _sample, _sqrt = False, True


class _MinMax(AggregateFunction):
    _reduce = "min"

    def result_type(self, schema):
        return self.child.dtype(schema)

    def accumulators(self, schema):
        dt = self.child.dtype(schema)
        return [AccSpec(self._reduce, dt.np_dtype, self._reduce),
                AccSpec("cnt", np.dtype(np.int64), "sum", width=8)]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        spec = self.accumulators(batch.schema())[0]
        x = v.data.astype(spec.np_dtype)
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is None:
            return [x, cnt]
        return [jnp.where(m, x, jnp.asarray(spec.neutral)),
                jnp.where(m, cnt, jnp.zeros_like(cnt))]

    def finalize(self, accs, schema):
        return accs[0], accs[1] > 0

    def device_finalize(self, accs, schema):
        return accs[0], accs[1] > 0


class Min(_MinMax):
    _reduce = "min"


class Max(_MinMax):
    _reduce = "max"


class First(AggregateFunction):
    """first(x[, ignorenulls]): value at the smallest row position
    (non-deterministic across shuffles, like the reference's First —
    interfaces.scala). Each 32-bit word of the value is packed as
    (pos << 33 | isnull << 32 | word) under a MIN reduce; positions are
    unique, so every word accumulator independently picks the SAME
    winning row — the partial/final split and mesh merges work
    unchanged. 64-bit types carry two word accumulators."""

    _reduce = "min"
    _name = "first"
    uses_row_base = True

    def __init__(self, child, ignorenulls: bool = False):
        super().__init__(child)
        self.ignorenulls = ignorenulls
        self.output_dictionary = None

    def result_type(self, schema):
        return self.child.dtype(schema)

    def _wide(self, schema) -> bool:
        dt = self.child.dtype(schema)
        if isinstance(dt, T.StringType):
            return False  # dictionary codes are int32
        return np.dtype(dt.np_dtype).itemsize > 4

    def accumulators(self, schema):
        specs = [AccSpec(f"{self._name}_w0", np.dtype(np.int64),
                         self._reduce)]
        if self._wide(schema):
            specs.append(AccSpec(f"{self._name}_w1", np.dtype(np.int64),
                                 self._reduce))
        specs.append(AccSpec("cnt", np.dtype(np.int64), "sum", width=8))
        return specs

    def update(self, batch, sel, row_base=None):
        v = self.child.eval(batch)
        self.output_dictionary = v.dictionary
        cap = batch.capacity
        # min reduce picks the smallest position (first); max the
        # largest (last) — the position rides the high packed bits.
        # `row_base` makes positions globally unique across merged
        # chunks/shards (see AggregateFunction.uses_row_base); packed
        # positions carry 30 bits, so callers bound base+cap < 2^30.
        pos = jnp.arange(cap, dtype=jnp.int64)
        if row_base is not None:
            pos = pos + jnp.asarray(row_base, jnp.int64)
        isnull = jnp.zeros((cap,), jnp.int64) if v.validity is None \
            else (~v.validity).astype(jnp.int64)
        data = v.data
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        if data.dtype == jnp.float64:
            # TPU's X64 rewrite cannot bitcast 64-bit floats; carry a
            # double-float (hi, lo) f32 pair instead — reconstruction
            # hi + lo is exact to ~2^-48 relative (documented deviation)
            hi = data.astype(jnp.float32)
            lo = (data - hi.astype(jnp.float64)).astype(jnp.float32)
            words = [hi.view(jnp.int32).astype(jnp.int64)
                     & jnp.int64(0xFFFFFFFF),
                     lo.view(jnp.int32).astype(jnp.int64)
                     & jnp.int64(0xFFFFFFFF)]
        elif np.dtype(data.dtype).itemsize > 4:
            wide = data.astype(jnp.int64)
            words = [wide & jnp.int64(0xFFFFFFFF),
                     (wide >> 32) & jnp.int64(0xFFFFFFFF)]
        else:
            if data.dtype == jnp.float32:
                bits = data.view(jnp.int32)  # bit pattern, same width
            elif data.dtype != jnp.int32:
                bits = data.astype(jnp.int32)  # widen narrow ints
            else:
                bits = data
            words = [bits.astype(jnp.int64) & jnp.int64(0xFFFFFFFF)]
        m = batch.selection_mask() if sel is None else sel
        contributing = m
        if self.ignorenulls and v.validity is not None:
            contributing = contributing & v.validity
        neutral = jnp.asarray(_max_of(np.dtype(np.int64))
                              if self._reduce == "min"
                              else _min_of(np.dtype(np.int64)))
        out = []
        for w in words:
            packed = (pos << 33) | (isnull << 32) | w
            out.append(jnp.where(contributing, packed, neutral))
        out.append(contributing.astype(jnp.int64))
        return out

    def _unpack(self, word_accs, schema, xp):
        """Packed word accumulators -> (value, isnull-of-winner)."""
        dt = self.result_type(schema)
        words = [xp.asarray(p) & xp.int64(0xFFFFFFFF) for p in word_accs]
        isnull = ((xp.asarray(word_accs[0]) >> 32) & 1) \
            .astype(bool if xp is np else jnp.bool_)
        out_np = np.dtype(dt.np_dtype)
        if len(words) == 2:
            if out_np == np.dtype(np.float64):
                hi = words[0].astype(xp.uint32).view(xp.int32) \
                    .view(xp.float32).astype(xp.float64)
                lo = words[1].astype(xp.uint32).view(xp.int32) \
                    .view(xp.float32).astype(xp.float64)
                return hi + lo, isnull
            wide = (words[1] << 32) | words[0]
            return wide, isnull
        low32 = words[0].astype(xp.uint32).view(xp.int32)
        if self.output_dictionary is not None or \
                out_np == np.dtype(np.int32):
            return low32, isnull
        if np.issubdtype(out_np, np.floating):
            return low32.view(xp.float32), isnull
        if out_np == np.dtype(np.bool_):
            return low32.astype(bool if xp is np else jnp.bool_), isnull
        return low32.astype(out_np), isnull

    def finalize(self, accs, schema):
        cnt = np.asarray(accs[-1])
        val, isnull = self._unpack([np.asarray(a) for a in accs[:-1]],
                                   schema, np)
        return val, (cnt > 0) & ~isnull

    def device_finalize(self, accs, schema):
        val, isnull = self._unpack(accs[:-1], schema, jnp)
        return val, (accs[-1] > 0) & ~isnull

    def __repr__(self):
        return f"{self._name}({self.child!r})"


class Last(First):
    _reduce = "max"
    _name = "last"


class AnyValue(First):
    _name = "any_value"

    def __repr__(self):
        return f"any_value({self.child!r})"


class _TwoChildAgg(AggregateFunction):
    """Base for two-input declarative aggregates (corr/covar)."""

    def __init__(self, x: Expression, y: Expression):
        self.child = None
        self.x = x
        self.y = y
        self.children = (x, y)

    def with_args(self, args):
        import copy
        nf = copy.copy(self)
        nf.x, nf.y = args
        nf.children = tuple(args)
        return nf

    def references(self):
        return self.x.references() | self.y.references()

    def result_type(self, schema):
        return T.DOUBLE

    def _xy(self, batch, sel):
        vx = self.x.eval(batch)
        vy = self.y.eval(batch)
        m = sel
        for v in (vx, vy):
            if v.validity is not None:
                m = v.validity if m is None else (m & v.validity)
        x = cast_vec(vx, T.DOUBLE).data
        y = cast_vec(vy, T.DOUBLE).data
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is not None:
            x = jnp.where(m, x, 0.0)
            y = jnp.where(m, y, 0.0)
            cnt = jnp.where(m, cnt, 0)
        return x, y, cnt

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.x!r}, {self.y!r})"


class Corr(_TwoChildAgg):
    """Pearson correlation via power sums (reference:
    Corr in CentralMomentAgg.scala, merge-formula form)."""

    def accumulators(self, schema):
        return [AccSpec("cnt", np.dtype(np.int64), "sum", width=8)] + \
            [AccSpec(s, np.dtype(np.float64), "sum")
             for s in ("sx", "sy", "sxx", "syy", "sxy")]

    def update(self, batch, sel):
        x, y, cnt = self._xy(batch, sel)
        return [cnt, x, y, x * x, y * y, x * y]

    def _finish(self, cnt, sx, sy, sxx, syy, sxy, xp):
        n = xp.maximum(cnt, 1).astype(np.float64) if xp is np else \
            xp.maximum(cnt, 1).astype(jnp.float64)
        cov = sxy - sx * sy / n
        vx = sxx - sx * sx / n
        vy = syy - sy * sy / n
        denom = xp.sqrt(xp.maximum(vx, 0.0) * xp.maximum(vy, 0.0))
        safe = xp.where(denom > 0, denom, 1.0)
        out = cov / safe
        valid = (cnt > 1) & (denom > 0)
        return out, valid

    def finalize(self, accs, schema):
        return self._finish(np.asarray(accs[0]), *map(np.asarray, accs[1:]),
                            np)

    def device_finalize(self, accs, schema):
        return self._finish(accs[0], *accs[1:], jnp)


class _Covar(_TwoChildAgg):
    _ddof = 1

    def accumulators(self, schema):
        return [AccSpec("cnt", np.dtype(np.int64), "sum", width=8),
                AccSpec("sx", np.dtype(np.float64), "sum"),
                AccSpec("sy", np.dtype(np.float64), "sum"),
                AccSpec("sxy", np.dtype(np.float64), "sum")]

    def update(self, batch, sel):
        x, y, cnt = self._xy(batch, sel)
        return [cnt, x, y, x * y]

    def _finish(self, cnt, sx, sy, sxy, xp):
        fl = np.float64 if xp is np else jnp.float64
        n = xp.maximum(cnt, 1).astype(fl)
        denom = xp.maximum(cnt - self._ddof, 1).astype(fl)
        out = (sxy - sx * sy / n) / denom
        valid = cnt > self._ddof
        return out, valid

    def finalize(self, accs, schema):
        return self._finish(*map(np.asarray, accs), np)

    def device_finalize(self, accs, schema):
        return self._finish(*accs, jnp)


class CovarSamp(_Covar):
    _ddof = 1


class CovarPop(_Covar):
    _ddof = 0


class _HigherMoment(AggregateFunction):
    """skewness/kurtosis via raw power sums (reference:
    CentralMomentAgg.scala Skewness/Kurtosis, population form)."""

    _order = 3

    def result_type(self, schema):
        return T.DOUBLE

    def accumulators(self, schema):
        return [AccSpec("cnt", np.dtype(np.int64), "sum", width=8)] + \
            [AccSpec(f"s{k}", np.dtype(np.float64), "sum")
             for k in range(1, self._order + 1)]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        x = cast_vec(v, T.DOUBLE).data
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is not None:
            x = jnp.where(m, x, 0.0)
            cnt = jnp.where(m, cnt, 0)
        out = [cnt]
        p = x
        for _ in range(self._order):
            out.append(p)
            p = p * x
        return out

    def _moments(self, accs, xp):
        fl = np.float64 if xp is np else jnp.float64
        cnt = accs[0]
        n = xp.maximum(cnt, 1).astype(fl)
        mean = accs[1] / n
        m2 = accs[2] / n - mean * mean
        return cnt, n, mean, xp.maximum(m2, 0.0)

    def finalize(self, accs, schema):
        return self._finish([np.asarray(a) for a in accs], np)

    def device_finalize(self, accs, schema):
        return self._finish(accs, jnp)


class Skewness(_HigherMoment):
    _order = 3

    def _finish(self, accs, xp):
        cnt, n, mean, m2 = self._moments(accs, xp)
        m3 = accs[3] / n - 3 * mean * (accs[2] / n) + 2 * mean ** 3
        sd = xp.sqrt(m2)
        safe = xp.where(sd > 0, sd, 1.0)
        out = m3 / (safe ** 3)
        return out, (cnt > 0) & (m2 > 0)


class Kurtosis(_HigherMoment):
    """Excess kurtosis m4/m2^2 - 3 (the reference's Kurtosis)."""
    _order = 4

    def _finish(self, accs, xp):
        cnt, n, mean, m2 = self._moments(accs, xp)
        m4 = (accs[4] / n - 4 * mean * (accs[3] / n)
              + 6 * mean ** 2 * (accs[2] / n) - 3 * mean ** 4)
        safe = xp.where(m2 > 0, m2, 1.0)
        out = m4 / (safe * safe) - 3.0
        return out, (cnt > 0) & (m2 > 0)


class _BoolAggBase(AggregateFunction):
    _reduce = "min"  # bool_and: min over {0,1}

    def result_type(self, schema):
        return T.BOOLEAN

    def accumulators(self, schema):
        return [AccSpec(self._reduce, np.dtype(np.bool_), self._reduce),
                AccSpec("cnt", np.dtype(np.int64), "sum", width=8)]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        x = v.data.astype(jnp.bool_)
        cnt = jnp.ones((batch.capacity,), jnp.int64)
        if m is not None:
            neutral = self._reduce == "min"  # True for and, False for or
            x = jnp.where(m, x, neutral)
            cnt = jnp.where(m, cnt, 0)
        return [x, cnt]

    def finalize(self, accs, schema):
        return accs[0].astype(bool), accs[1] > 0

    def device_finalize(self, accs, schema):
        return accs[0], accs[1] > 0


class BoolAnd(_BoolAggBase):
    _reduce = "min"


class BoolOr(_BoolAggBase):
    _reduce = "max"


class CountIf(AggregateFunction):
    """count_if(pred): rows where the predicate is true."""

    def result_type(self, schema):
        return T.LONG

    def result_nullable(self, schema):
        return False

    def accumulators(self, schema):
        return [AccSpec("count", np.dtype(np.int64), "sum", width=8)]

    def update(self, batch, sel):
        v, m = self._eval_child(batch, sel)
        x = v.data.astype(jnp.bool_)
        if m is not None:
            x = x & m
        return [x.astype(jnp.int64)]

    def finalize(self, accs, schema):
        return accs[0], None

    def device_finalize(self, accs, schema):
        return accs[0], None


class SumDistinct(AggregateFunction):
    """sum(DISTINCT x): planning marker, rewritten by
    RewriteDistinctAggregates into sum over a (groups, x) dedupe."""

    def result_type(self, schema):
        return Sum(self.child).result_type(schema)

    def accumulators(self, schema):
        raise NotImplementedError(
            "sum(DISTINCT) must be rewritten before execution")

    def __repr__(self):
        return f"sum(DISTINCT {self.child!r})"


class AvgDistinct(AggregateFunction):
    """avg(DISTINCT x): planning marker (see SumDistinct)."""

    def result_type(self, schema):
        return Avg(self.child).result_type(schema)

    def accumulators(self, schema):
        raise NotImplementedError(
            "avg(DISTINCT) must be rewritten before execution")

    def __repr__(self):
        return f"avg(DISTINCT {self.child!r})"


@dataclass
class AggExpr:
    """A named aggregate output column (reference: AggregateExpression)."""

    func: AggregateFunction
    out_name: str

    def __repr__(self):
        return f"{self.func!r} AS {self.out_name}"


# ---------------------------------------------------------------------------
# Positional aggregates (reference: Percentile.scala,
# ApproximatePercentile.scala:1, collect.scala). They have no flat
# accumulator decomposition — the engine computes them in ONE complete-
# mode pass via a (group keys, value) device sort (the ObjectHashAggregate
# seat); under a mesh they run per shard behind a hash-clustered exchange.
# ---------------------------------------------------------------------------

class _PositionalAgg(AggregateFunction):
    positional = True

    def accumulators(self, schema):
        raise AnalysisError(
            f"{type(self).__name__} has no accumulator decomposition "
            "(positional aggregates run in one complete pass)")

    def update(self, batch, sel):
        raise AnalysisError(f"{type(self).__name__}.update unreachable")

    def finalize(self, accs, schema):
        raise AnalysisError(f"{type(self).__name__}.finalize unreachable")


class Percentile(_PositionalAgg):
    """Exact percentile with linear interpolation; nulls ignored."""

    def __init__(self, child, q: float):
        super().__init__(child)
        if not (0.0 <= float(q) <= 1.0):
            raise AnalysisError(
                f"percentile fraction must be in [0, 1], got {q}")
        self.q = float(q)

    def result_type(self, schema):
        return T.DOUBLE

    def __repr__(self):
        return f"percentile({self.child!r}, {self.q})"


class Median(Percentile):
    def __init__(self, child):
        super().__init__(child, 0.5)

    def __repr__(self):
        return f"median({self.child!r})"


class CollectList(_PositionalAgg):
    """collect_list: the group's non-null values as an array (order is
    value-sorted — a valid instance of the reference's unspecified
    order)."""

    distinct = False
    _name = "collect_list"

    def result_type(self, schema):
        return T.ArrayType(self.child.dtype(schema))

    def __repr__(self):
        return f"{self._name}({self.child!r})"


class CollectSet(CollectList):
    distinct = True
    _name = "collect_set"

"""Columnar batch substrate: the device-side data representation.

This replaces the reference's row/columnar tier (UnsafeRow
`sql/catalyst/src/main/java/.../expressions/UnsafeRow.java:62`,
`ColumnarBatch.java:30`, `OnHeap/OffHeapColumnVector.java`) with a
TPU-native struct-of-arrays design (SURVEY.md section 2.4):

- a :class:`Column` is one flat ``jax.Array`` of a fixed device dtype plus
  an optional boolean validity array (NULL mask) and, for strings, a
  host-side pyarrow dictionary (values live on host; codes on device);
- a :class:`Batch` is an ordered dict of Columns sharing a *capacity*
  (padded row count) and a *selection* mask marking live rows. Filters
  update the selection instead of compacting, keeping shapes static for
  XLA (the static-shape discipline of SURVEY.md section 7);
- capacities are rounded up to buckets so XLA recompiles O(log n) times
  across input sizes, not O(n).

Batch is registered as a JAX pytree so whole batches flow through
``jax.jit`` / ``shard_map`` directly.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from . import types as T


def bucket_capacity(n: int, growth: float = 2.0, floor: int = 8) -> int:
    """Round n up to the padding bucket (power-of-growth), bounding the
    number of distinct compiled shapes."""
    if n <= floor:
        return floor
    k = math.ceil(math.log(n / floor, growth))
    return int(floor * growth ** k)


class Column:
    """One device column: data + optional validity + optional host dictionary.

    `prov` (provenance) is a trace-time-only hint set by gathering
    operators (joins): ``(base_data, base_validity, idx, present)`` with
    the invariant ``data == take(base_data, idx)`` and ``validity ==
    (take(base_validity, idx) &) present``. A downstream gather composes
    indices (``base[idx[p]]``) instead of gathering the materialized
    data (``(base[idx])[p]``), so in a chain of joins each payload
    column is gathered ONCE from its origin and XLA dead-code-eliminates
    the intermediate per-column gathers — the columnar late-
    materialization the reference gets from row-at-a-time pipelining.
    prov is NOT part of the pytree, so it never crosses a jit boundary
    (dropping it is always sound: `data` stays eagerly defined)."""

    __slots__ = ("data", "validity", "dtype", "dictionary", "prov", "bits",
                 "offsets", "elem_validity")

    def __init__(self, data, dtype: T.DataType, validity=None,
                 dictionary: Optional[pa.Array] = None, prov=None,
                 bits: Optional[int] = None, offsets=None,
                 elem_validity=None):
        self.data = data
        self.dtype = dtype
        self.validity = validity  # None means all-valid
        self.dictionary = dictionary  # host pyarrow array for StringType
        self.prov = prov
        # optional static value bound: values in [0, 2^bits) — lets
        # int64 arithmetic take single-pass f64 fast paths (see Vec.bits)
        self.bits = bits
        # ARRAY columns (T.ArrayType): `data` holds the FLATTENED
        # elements, `offsets` (int32 [rows+1]) marks each row's slice,
        # `elem_validity` is the per-ELEMENT null mask (`validity` stays
        # per-row) — the Arrow List layout (UnsafeArrayData.java:1 seat)
        self.offsets = offsets
        self.elem_validity = elem_validity

    @property
    def capacity(self) -> int:
        if self.offsets is not None:
            return self.offsets.shape[0] - 1
        return self.data.shape[0]

    def with_data(self, data, validity="__keep__") -> "Column":
        v = self.validity if validity == "__keep__" else validity
        return Column(data, self.dtype, v, self.dictionary)

    def __repr__(self) -> str:
        return (f"Column({self.dtype!r}, cap={self.capacity}, "
                f"nullable={self.validity is not None}, "
                f"dict={len(self.dictionary) if self.dictionary is not None else None})")


def _col_flatten(c: Column):
    children = [c.data]
    flags = [c.validity is not None, c.offsets is not None,
             c.elem_validity is not None]
    if flags[0]:
        children.append(c.validity)
    if flags[1]:
        children.append(c.offsets)
    if flags[2]:
        children.append(c.elem_validity)
    return tuple(children), (tuple(flags), c.dtype, c.dictionary)


def _col_unflatten(aux, children):
    flags, dtype, dictionary = aux
    it = iter(children)
    data = next(it)
    validity = next(it) if flags[0] else None
    offsets = next(it) if flags[1] else None
    elem_validity = next(it) if flags[2] else None
    return Column(data, dtype, validity, dictionary, offsets=offsets,
                  elem_validity=elem_validity)


jax.tree_util.register_pytree_node(Column, _col_flatten, _col_unflatten)


class Batch:
    """An ordered set of equal-capacity Columns plus a row-selection mask.

    ``selection`` is a bool[capacity] array; None means all `capacity`
    rows are live. ``num_rows()`` is a traced scalar (selection.sum()).
    """

    __slots__ = ("columns", "selection")

    def __init__(self, columns: Dict[str, Column], selection=None):
        self.columns = dict(columns)
        self.selection = selection

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_numpy(data: Dict[str, np.ndarray], num_rows: Optional[int] = None,
                   dtypes: Optional[Dict[str, T.DataType]] = None,
                   growth: float = 2.0) -> "Batch":
        cols = {}
        n = num_rows
        for name, arr in data.items():
            if n is None:
                n = len(arr)
            cap = bucket_capacity(n, growth)
            dt = (dtypes or {}).get(name) or _np_to_dtype(arr.dtype)
            padded = np.zeros(cap, dtype=dt.np_dtype)
            padded[:n] = arr[:n]
            cols[name] = Column(jnp.asarray(padded), dt)
        sel = jnp.arange(cap) < n
        return Batch(cols, sel)

    @staticmethod
    def from_arrow(table: pa.Table, growth: float = 2.0,
                   capacity: Optional[int] = None) -> "Batch":
        """Ingest a pyarrow table: dictionary-encode strings, pad to bucket.

        Replaces the reference's vectorized Parquet column readers
        (`VectorizedParquetRecordReader.java:54`) as the host->HBM edge.
        `capacity` forces a fixed padded size (chunked loads keep one
        compiled shape across chunks)."""
        n = table.num_rows
        cap = capacity if capacity is not None else bucket_capacity(n, growth)
        assert cap >= n, (cap, n)
        cols: Dict[str, Column] = {}
        for name, col in zip(table.column_names, table.columns):
            cols[name] = _arrow_to_column(name, col, n, cap)
        sel = jnp.arange(cap) < n
        return Batch(cols, sel)

    # -- shape/meta ---------------------------------------------------------

    @property
    def capacity(self) -> int:
        for c in self.columns.values():
            return c.capacity
        return 0

    @property
    def names(self) -> List[str]:
        return list(self.columns.keys())

    def column(self, name: str) -> Column:
        return self.columns[name]

    def num_rows(self):
        """Traced count of live rows."""
        if self.selection is None:
            return jnp.asarray(self.capacity, dtype=jnp.int32)
        return jnp.sum(self.selection).astype(jnp.int32)

    def schema(self) -> T.Schema:
        return T.Schema([T.Field(n, c.dtype, c.validity is not None)
                         for n, c in self.columns.items()])

    def selection_mask(self):
        if self.selection is None:
            return jnp.ones((self.capacity,), dtype=jnp.bool_)
        return self.selection

    # -- transforms ---------------------------------------------------------

    def with_selection(self, sel) -> "Batch":
        return Batch(self.columns, sel)

    def select(self, names: Sequence[str]) -> "Batch":
        return Batch({n: self.columns[n] for n in names}, self.selection)

    def with_column(self, name: str, col: Column) -> "Batch":
        cols = dict(self.columns)
        cols[name] = col
        return Batch(cols, self.selection)

    # -- egress -------------------------------------------------------------

    def to_arrow(self) -> pa.Table:
        """Compact (drop unselected rows), decode dictionaries, return
        host table. ALL device arrays leave in ONE `jax.device_get`
        call: on tunneled runtimes a per-array pull costs a full RPC
        round trip (~150ms each, measured), so batching is the
        difference between milliseconds and seconds of egress."""
        import jax
        pulls = []
        if self.selection is not None:
            pulls.append(self.selection)
        for col in self.columns.values():
            pulls.append(col.data)
            if col.validity is not None:
                pulls.append(col.validity)
            if col.offsets is not None:
                pulls.append(col.offsets)
            if col.elem_validity is not None:
                pulls.append(col.elem_validity)
        host = iter(jax.device_get(pulls))
        sel = next(host) if self.selection is not None else None
        arrays = []
        names = []
        for name, col in self.columns.items():
            data = next(host)
            valid = next(host) if col.validity is not None else None
            offsets = next(host) if col.offsets is not None else None
            evalid = next(host) if col.elem_validity is not None else None
            if offsets is not None:
                arrays.append(_list_to_arrow(col, data, valid, offsets,
                                             evalid, sel))
                names.append(name)
                continue
            if sel is not None:
                data = data[sel]
                if valid is not None:
                    valid = valid[sel]
            arrays.append(_column_to_arrow(col, data, valid))
            names.append(name)
        return pa.table(arrays, names=names)

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def __repr__(self) -> str:
        return f"Batch(cap={self.capacity}, cols={self.columns!r})"


def _batch_flatten(b: Batch):
    names = tuple(b.columns.keys())
    has_sel = b.selection is not None
    children = tuple(b.columns[n] for n in names)
    if has_sel:
        children = children + (b.selection,)
    return children, (names, has_sel)


def _batch_unflatten(aux, children):
    names, has_sel = aux
    if has_sel:
        *cols, sel = children
    else:
        cols, sel = children, None
    return Batch({n: c for n, c in zip(names, cols)}, sel)


jax.tree_util.register_pytree_node(Batch, _batch_flatten, _batch_unflatten)


# ---------------------------------------------------------------------------
# Dictionary algebra (host-side, trace-time static)
#
# String columns carry host pyarrow dictionaries; device data is int32
# codes. Any transform that slices or combines dictionaries must keep the
# invariant "equal strings <=> equal codes *within one dictionary*", and
# any operator combining two columns must first remap both onto one shared
# dictionary. These helpers do that once on host; the resulting remap
# tables become jit constants (a gather on device).
# ---------------------------------------------------------------------------


def dedupe_dictionary(dictionary: pa.Array):
    """Collapse duplicate values in a dictionary.

    Returns (remap, deduped) where `remap` is a device int32 table mapping
    old code -> new code, or None when the dictionary was already unique.
    Needed after value transforms (e.g. substring) that can map distinct
    old values onto one new value — otherwise group-by/join on codes would
    treat equal strings as distinct (the reference gets this for free from
    UTF8String equality)."""
    import pyarrow.compute as pc
    arr = dictionary.combine_chunks() if isinstance(
        dictionary, pa.ChunkedArray) else dictionary
    uniq = pc.unique(arr)
    if len(uniq) == len(arr):
        return None, arr
    remap = pc.index_in(arr, value_set=uniq).cast(pa.int32())
    return jnp.asarray(remap.to_numpy(zero_copy_only=False)), uniq


def unify_dictionaries(da: pa.Array, db: pa.Array):
    """Merge two (internally unique) dictionaries into one shared one.

    Returns (remap_b, merged): `merged` extends `da` with values of `db`
    not already present (so codes into `da` stay valid), and `remap_b` is
    a device int32 table mapping b-codes -> merged codes (None when the
    dictionaries are identical). Mirrors the chunk-level DictUnifier in
    io/sources.py, but for two already-loaded columns."""
    import pyarrow.compute as pc
    da = da.combine_chunks() if isinstance(da, pa.ChunkedArray) else da
    db = db.combine_chunks() if isinstance(db, pa.ChunkedArray) else db
    if da.equals(db):
        return None, da
    present = pc.index_in(db, value_set=da)
    new_mask = pc.is_null(present)
    if pc.any(new_mask).as_py():
        new_vals = pc.filter(db, new_mask)
        merged = pa.concat_arrays([da.cast(pa.string()),
                                   new_vals.cast(pa.string())])
    else:
        merged = da
    remap = pc.index_in(db, value_set=merged).cast(pa.int32())
    return jnp.asarray(remap.to_numpy(zero_copy_only=False)), merged


def apply_code_remap(codes, remap):
    """Gather new codes through a remap table (identity when remap is None)."""
    if remap is None:
        return codes
    if remap.shape[0] == 0:
        # all-null column: the dictionary (and thus the remap) is
        # empty, no code is valid and validity masks every row — any
        # constant code works
        return jnp.zeros_like(codes)
    return jnp.take(remap, jnp.clip(codes, 0, remap.shape[0] - 1))


def unify_string_columns(l_data, l_dict: pa.Array, r_data, r_dict: pa.Array):
    """Re-encode two string code columns onto one shared dictionary.

    Dedupes each side, merges right values into the left dictionary, and
    remaps both code arrays. Returns (l_data, r_data, merged). After this,
    code equality <=> string equality across the two columns."""
    lmap, ld = dedupe_dictionary(l_dict)
    rmap, rd = dedupe_dictionary(r_dict)
    l_data = apply_code_remap(l_data, lmap)
    r_data = apply_code_remap(r_data, rmap)
    bmap, merged = unify_dictionaries(ld, rd)
    r_data = apply_code_remap(r_data, bmap)
    return l_data, r_data, merged


# ---------------------------------------------------------------------------
# Arrow conversion helpers
# ---------------------------------------------------------------------------

_ARROW_TO_DTYPE = {
    pa.bool_(): T.BOOLEAN,
    pa.int8(): T.BYTE,
    pa.int16(): T.SHORT,
    pa.int32(): T.INT,
    pa.int64(): T.LONG,
    pa.float32(): T.FLOAT,
    pa.float64(): T.DOUBLE,
    pa.date32(): T.DATE,
    pa.timestamp("us"): T.TIMESTAMP,
}


def _np_to_dtype(np_dtype) -> T.DataType:
    m = {np.dtype(np.bool_): T.BOOLEAN, np.dtype(np.int8): T.BYTE,
         np.dtype(np.int16): T.SHORT, np.dtype(np.int32): T.INT,
         np.dtype(np.int64): T.LONG, np.dtype(np.float32): T.FLOAT,
         np.dtype(np.float64): T.DOUBLE}
    if np_dtype not in m:
        raise TypeError(f"unsupported numpy dtype {np_dtype}")
    return m[np_dtype]


def _arrow_to_column(name: str, col: pa.ChunkedArray, n: int, cap: int) -> Column:
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    at = arr.type
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return _arrow_list_to_column(name, arr, n, cap)
    dictionary = None
    if pa.types.is_null(at):
        # an empty/all-None pandas object column infers arrow `null`
        # (e.g. a streaming schema df with pd.Series([], dtype=str)):
        # treat it as an all-NULL string column, the dtype the object
        # column would carry with any value present
        arr = arr.cast(pa.string())
        at = arr.type
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        arr = arr.dictionary_encode()
        at = arr.type
    if pa.types.is_dictionary(at):
        dictionary = arr.dictionary
        codes = arr.indices.cast(pa.int32())
        np_data = codes.to_numpy(zero_copy_only=False)
        dt: T.DataType = T.STRING
    elif pa.types.is_decimal(at):
        dt = T.DecimalType(at.precision, at.scale)
        # exact unscaled int64: read the low 64-bit limb of the 128-bit
        # little-endian decimal buffer (two's complement reinterpret is
        # exact for values in int64 range, which our repr requires).
        # decimal128 shares one buffer layout for every precision, so no
        # cast is needed (the cast materialized a full copy — a third of
        # decimal ingest time at TPC-H scale)
        if arr.type.bit_width != 128:
            arr = arr.cast(pa.decimal128(38, at.scale))
        buf = arr.buffers()[1]
        raw = np.frombuffer(buf, dtype=np.int64,
                            count=2 * (arr.offset + len(arr)))
        lo = raw[2 * arr.offset::2]          # strided view, copied once
        if at.precision > 18:
            # only precision > 18 can exceed int64; cheaper columns
            # (TPC-H's (12,2)/(15,2)) skip the check entirely
            hi = raw[2 * arr.offset + 1::2]
            expect_hi = lo >> 63  # sign extension when value fits int64
            mism = hi != expect_hi
            if arr.null_count:
                mism = mism & ~np.asarray(arr.is_null()).astype(bool)
            if mism.any():
                raise OverflowError(
                    f"decimal column {name} exceeds int64 unscaled range")
        np_data = lo
    elif at == pa.date32():
        dt = T.DATE
        np_data = arr.cast(pa.int32()).to_numpy(zero_copy_only=False)
    elif pa.types.is_timestamp(at):
        dt = T.TIMESTAMP
        np_data = arr.cast(pa.timestamp("us")).cast(pa.int64()).to_numpy(
            zero_copy_only=False)
    else:
        dt = _ARROW_TO_DTYPE.get(at)
        if dt is None:
            raise TypeError(f"unsupported arrow type {at} for column {name}")
        np_data = arr.cast(pa.from_numpy_dtype(dt.np_dtype)).to_numpy(
            zero_copy_only=False)

    validity = None
    if arr.null_count > 0:
        valid_np = np.zeros(cap, dtype=np.bool_)
        valid_np[:n] = ~np.asarray(arr.is_null())
        np_data = np.where(valid_np[:n], np_data, np.zeros((), dtype=dt.np_dtype))
        validity = jax.device_put(valid_np)

    padded = np.zeros(cap, dtype=dt.np_dtype)
    padded[:n] = np_data
    # device_put is ~2x jnp.asarray for host->device of large buffers
    return Column(jax.device_put(padded), dt, validity, dictionary)


def _arrow_list_to_column(name: str, arr, n: int, cap: int) -> Column:
    """pa.ListArray -> offsets-encoded list Column: FLATTENED element
    data + absolute int32 offsets [cap+1] (padding rows repeat the last
    offset, i.e. zero-length)."""
    if pa.types.is_large_list(arr.type):
        arr = arr.cast(pa.list_(arr.type.value_type))
    offs = arr.offsets.to_numpy(zero_copy_only=False).astype(np.int32)
    values = arr.values
    vcap = bucket_capacity(max(len(values), 1))
    elem = _arrow_to_column(f"{name}.element", values, len(values), vcap)
    padded_off = np.full(cap + 1, offs[n] if len(offs) > n else 0,
                         dtype=np.int32)
    padded_off[:n + 1] = offs[:n + 1]
    validity = None
    if arr.null_count > 0:
        valid_np = np.zeros(cap, dtype=np.bool_)
        valid_np[:n] = ~np.asarray(arr.is_null())
        validity = jax.device_put(valid_np)
    return Column(elem.data, T.ArrayType(elem.dtype), validity,
                  elem.dictionary, offsets=jax.device_put(padded_off),
                  elem_validity=elem.validity)


def _list_to_arrow(col: Column, data: np.ndarray,
                   valid: Optional[np.ndarray], offsets: np.ndarray,
                   elem_valid: Optional[np.ndarray],
                   sel: Optional[np.ndarray]) -> pa.Array:
    """Offsets-encoded list column -> pa.ListArray over the SELECTED
    rows (compaction happens here — per-row slices can't be gathered by
    the flat-column path)."""
    cap = len(offsets) - 1
    idx = np.nonzero(sel[:cap])[0] if sel is not None else np.arange(cap)
    starts = offsets[idx]
    lengths = (offsets[idx + 1] - starts).astype(np.int64)
    lengths = np.maximum(lengths, 0)
    new_off = np.zeros(len(idx) + 1, dtype=np.int32)
    np.cumsum(lengths, out=new_off[1:])
    total = int(new_off[-1])
    intra = np.arange(total, dtype=np.int64) - np.repeat(
        new_off[:-1].astype(np.int64), lengths)
    val_idx = np.repeat(starts.astype(np.int64), lengths) + intra
    vals = data[val_idx]
    ev = None if elem_valid is None else elem_valid[val_idx]
    elem_col = Column(None, col.dtype.element, None, col.dictionary)
    elem_arrow = _column_to_arrow(elem_col, vals, ev)
    off_mask = None
    if valid is not None:
        off_mask = np.zeros(len(idx) + 1, dtype=bool)
        off_mask[:len(idx)] = ~valid[idx]
    return pa.ListArray.from_arrays(
        pa.array(new_off, type=pa.int32(), mask=off_mask), elem_arrow)


def _column_to_arrow(col: Column, data: np.ndarray,
                     valid: Optional[np.ndarray]) -> pa.Array:
    dt = col.dtype
    mask = None if valid is None else ~valid
    if isinstance(dt, T.StringType):
        if col.dictionary is None:
            return pa.array(data.astype("U"), mask=mask)
        codes = np.clip(data, 0, len(col.dictionary) - 1)
        out = pa.DictionaryArray.from_arrays(
            pa.array(codes.astype(np.int32), mask=mask), col.dictionary)
        return out.cast(pa.string())
    if isinstance(dt, T.DecimalType):
        # inverse of ingest: place unscaled int64 into the low limb of a
        # little-endian 128-bit buffer with sign extension in the high limb
        lo = data.astype(np.int64)
        hi = lo >> 63
        raw = np.empty((len(lo), 2), dtype=np.int64)
        raw[:, 0] = lo
        raw[:, 1] = hi
        validity_buf = None
        if valid is not None:
            validity_buf = pa.array(valid.astype(np.bool_)).buffers()[1]
        return pa.Array.from_buffers(
            pa.decimal128(max(dt.precision, 19), dt.scale), len(lo),
            [validity_buf, pa.py_buffer(raw.tobytes())],
            null_count=int((~valid).sum()) if valid is not None else 0)
    if isinstance(dt, T.DateType):
        return pa.array(data.astype(np.int32), mask=mask).cast(pa.date32())
    if isinstance(dt, T.TimestampType):
        return pa.array(data.astype(np.int64), mask=mask).cast(pa.timestamp("us"))
    return pa.array(data, mask=mask)

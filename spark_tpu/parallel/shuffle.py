"""Collective exchange kernels: the shuffle, TPU-native.

Replaces the reference's entire shuffle stack — write path
(`shuffle/sort/SortShuffleManager.scala:73`, `UnsafeShuffleWriter.java`),
block files (`IndexShuffleBlockResolver.scala`), Netty fetch
(`storage/ShuffleBlockFetcherIterator.scala:85`) and the MapOutputTracker
— with on-device radix partitioning + one `jax.lax.all_to_all` over ICI:

1. hash each row's key columns to a target shard (value-stable for
   dictionary strings: codes hash through a host-computed per-dictionary
   value-hash table, so two tables with different dictionaries still
   co-partition equal strings);
2. sort rows by target shard, scatter into an [n, L] send buffer;
3. `all_to_all` swaps bucket i to shard i; received rows flatten into a
   new local batch with a validity-derived selection.

There are no block files and no size tracking: shapes are static, the
"map output statistics" channel is a psum'd metric. All functions run
INSIDE `shard_map` (ctx.axis_name names the mesh axis).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar import Batch, Column

_MIX_MUL = np.uint64(0xBF58476D1CE4E5B9)
_MIX_MUL2 = np.uint64(0x94D049BB133111EB)
_NULL_HASH = np.int64(-7046029254386353131)


def _mix64(x):
    """splitmix64 finalizer (wrapping uint64 arithmetic)."""
    u = x.astype(jnp.uint64)
    u = (u ^ (u >> 30)) * _MIX_MUL
    u = (u ^ (u >> 27)) * _MIX_MUL2
    u = u ^ (u >> 31)
    return u.astype(jnp.int64)


# id(dict) -> (dictionary, hashes). The strong reference to the
# dictionary keeps its id from being reused while the entry lives, and
# the identity check guards against a stale entry anyway; bounded size.
_DICT_HASH_CACHE: Dict[int, Tuple[object, jnp.ndarray]] = {}
_DICT_HASH_CACHE_MAX = 64


def _dict_value_hashes(dictionary) -> jnp.ndarray:
    """Stable 64-bit hash per dictionary VALUE (host, cached per dict).

    Hashing codes directly would mis-partition: equal strings in two
    tables carry different codes. Hashing the value makes co-partitioning
    hold across dictionaries."""
    key = id(dictionary)
    cached = _DICT_HASH_CACHE.get(key)
    if cached is not None and cached[0] is dictionary:
        # convert per call: caching the jnp array would capture a
        # TRACER when first computed inside a jit trace and leak it
        # into the next trace over the same dictionary
        return jnp.asarray(cached[1])
    hs = np.empty(len(dictionary), dtype=np.int64)
    for i, s in enumerate(dictionary.to_pylist()):
        b = (s if s is not None else "\0").encode("utf-8", "surrogatepass")
        hs[i] = int.from_bytes(
            hashlib.blake2b(b, digest_size=8).digest(), "little", signed=True)
    if len(_DICT_HASH_CACHE) >= _DICT_HASH_CACHE_MAX:
        _DICT_HASH_CACHE.pop(next(iter(_DICT_HASH_CACHE)))
    _DICT_HASH_CACHE[key] = (dictionary, hs)
    return jnp.asarray(hs)


def _resolve(batch: Batch, name: str) -> Column:
    if name in batch.columns:
        return batch.columns[name]
    for n, c in batch.columns.items():
        if n.lower() == name.lower():
            return c
    raise KeyError(f"exchange key {name!r} not in {batch.names}")


def hash_rows(batch: Batch, key_names: Sequence[str]):
    """Combined value-hash of the key columns, int64 per row."""
    h = jnp.zeros((batch.capacity,), jnp.int64)
    for name in key_names:
        col = _resolve(batch, name)
        if isinstance(col.dtype, T.StringType) and col.dictionary is not None:
            table = _dict_value_hashes(col.dictionary)
            x = jnp.take(table, jnp.clip(col.data, 0, table.shape[0] - 1))
        else:
            x = col.data.astype(jnp.int64)
        if col.validity is not None:
            x = jnp.where(col.validity, x, _NULL_HASH)
        h = _mix64(h ^ _mix64(x))
    return h


def _scatter_to_buckets(batch: Batch, tgt, n: int, block: int):
    """Sort rows by target shard and scatter into an [n*block] send layout
    (`block` slots per destination). Returns (flat_idx, perm, max_count):
    row perm[r] goes to flat slot flat_idx[r]; rows past a full bucket
    drop (the caller flags overflow off max_count and retries bigger)."""
    L = batch.capacity
    tgt_s, perm = jax.lax.sort(
        (tgt, jnp.arange(L, dtype=jnp.int32)), num_keys=1)
    counts = jnp.zeros((n + 1,), jnp.int32).at[tgt].add(
        jnp.ones((L,), jnp.int32), mode="drop")
    starts = jnp.cumsum(counts) - counts  # exclusive, [n+1]
    pos = jnp.arange(L, dtype=jnp.int32) - jnp.take(starts,
                                                    jnp.clip(tgt_s, 0, n))
    flat = jnp.where((tgt_s < n) & (pos < block), tgt_s * block + pos,
                     n * block)
    return flat, perm, jnp.max(counts[:n])


def exchange_hash(batch: Batch, key_names: Sequence[str], ctx,
                  block_cap: Optional[int] = None,
                  tag: str = "e0") -> Batch:
    """HashPartitioning exchange: radix-partition + all_to_all.

    `block_cap` is the per-(source, destination) slot count, so each shard
    receives at most n*block_cap rows. The round-2 design used block_cap=L
    (worst case: one shard receives everything) — 8x the input per shard
    at mesh 8, an OOM at any serious scale. The default now seeds
    2*ceil(L/n) (2x a uniform hash spread, the `MapOutputTracker`-style
    size assumption); the actual per-bucket max is surfaced as the
    `exch_max_<tag>` metric and an `exch_overflow_<tag>` flag, and the
    executor's stats->re-plan loop re-jits with a sufficient capacity when
    skew overflows it — the AQE pattern joins already use."""
    n = ctx.n_shards
    L = batch.capacity
    if block_cap is None:
        from ..columnar import bucket_capacity
        block_cap = min(L, bucket_capacity(-(-2 * L // n)))  # ceil(2L/n)
    sel = batch.selection_mask()
    h = hash_rows(batch, key_names)
    tgt = (h.astype(jnp.uint64) % np.uint64(n)).astype(jnp.int32)
    tgt = jnp.where(sel, tgt, n)  # dead rows dropped
    return _exchange_by_target(batch, tgt, ctx, block_cap, tag)


def _exchange_by_target(batch: Batch, tgt, ctx, block: int,
                        tag: str) -> Batch:
    """Route each selected row to shard `tgt[row]` via scatter +
    all_to_all; surfaces the max per-bucket count for the executor's
    capacity-retry loop."""
    from ..testing import faults
    faults.fire("shuffle")  # chaos seam: fires at trace time, per compile
    n = ctx.n_shards
    axis = ctx.axis_name
    sel = batch.selection_mask()
    flat, perm, max_count = _scatter_to_buckets(batch, tgt, n, block)
    ctx.add_metric(f"exch_max_{tag}", max_count)
    # total live rows routed (psum'd): max/(rows/n) is the skew factor
    # the adaptive re-planner reads (OptimizeSkewedJoin.scala:56 seat)
    live_rows = jnp.sum(sel.astype(jnp.int64))
    ctx.add_metric(f"exch_rows_{tag}", live_rows)
    # routed payload volume (rows x static row width incl. validity):
    # the shuffle-bytes observable the metrics sinks aggregate — ICI
    # traffic has no block files to weigh, so it's derived in-trace
    row_width = sum(c.data.dtype.itemsize
                    + (1 if c.validity is not None else 0)
                    for c in batch.columns.values())
    ctx.add_metric(f"exch_bytes_{tag}", live_rows * row_width)
    # per-shard telemetry: one-hot at this shard's mesh position; the
    # executor's psum reduction turns the stack into a replicated [n]
    # per-shard vector — no all_gather, no host sync (the flight
    # recorder's transfer-phase records come from exactly this)
    shard_hot = jnp.zeros((n,), jnp.int64).at[
        jax.lax.axis_index(axis)].set(live_rows)
    ctx.add_metric(f"shard_rows_{tag}", shard_hot)
    ctx.add_metric(f"shard_bytes_{tag}", shard_hot * row_width)
    ctx.add_flag(f"exch_overflow_{tag}", max_count > block)

    def send_recv(x, fill=0):
        x_s = jnp.take(x, perm)
        send = jnp.full((n * block,), fill, x.dtype).at[flat].set(
            x_s, mode="drop")
        return jax.lax.all_to_all(send.reshape(n, block), axis, 0, 0
                                  ).reshape(n * block)

    live = send_recv(sel, fill=False)  # scattered True marks live rows
    # (dead rows never scatter: their flat index is out of bounds)
    cols: Dict[str, Column] = {}
    for name, col in batch.columns.items():
        data = send_recv(col.data)
        validity = None if col.validity is None else send_recv(
            col.validity, fill=False)
        cols[name] = Column(data, col.dtype, validity, col.dictionary)
    return Batch(cols, live)


RANGE_SAMPLES_PER_SHARD = 64


def exchange_range(batch: Batch, orders, ctx,
                   block_cap: Optional[int] = None,
                   tag: str = "e0") -> Batch:
    """RangePartitioning exchange: sampled bounds + all_to_all.

    The distributed global-sort layout (reference: `Partitioner.scala:140`
    RangePartitioner + `partitioning.scala:255`): each shard contributes a
    strided sample of its sort-key tuples; samples are all_gather'ed
    (tiny — n*64 rows), sorted identically on every shard, and n-1
    quantile bounds picked; rows route to the shard whose key range holds
    them (lexicographic compare against the bounds). Shard i then holds
    keys <= shard i+1's, so locally sorted shards concatenate into the
    globally sorted result — no shard ever materializes the full dataset
    (the round-2 design all_gather'ed everything to every shard).
    Sampling skew only unbalances bucket sizes; the exch_overflow retry
    loop keeps it correct."""
    from ..execution.sort import sort_operands
    n = ctx.n_shards
    axis = ctx.axis_name
    L = batch.capacity
    if block_cap is None:
        from ..columnar import bucket_capacity
        block_cap = min(L, bucket_capacity(-(-2 * L // n)))
    sel = batch.selection_mask()
    ops = sort_operands(batch, orders)

    # sample s evenly-spaced VALID rows (round-4 VERDICT weak #5: fixed
    # slot positions yield few valid samples under clustered selections,
    # skewing the bounds); each sample carries weight live/s so shards
    # with more live rows pull the quantiles proportionally
    s = min(RANGE_SAMPLES_PER_SHARD, L)
    live = jnp.sum(sel.astype(jnp.int64))
    rank = jnp.cumsum(sel.astype(jnp.int64))      # 1-based rank per slot
    # int64: arange(s) * live wraps int32 past ~34M live rows
    targets = (jnp.arange(s, dtype=jnp.int64)
               * jnp.maximum(live, 1)) // s + 1
    pos = jnp.clip(jnp.searchsorted(rank, targets, side="left")
                   .astype(jnp.int32), 0, L - 1)
    # duplicate samples when live < s are fine: weights normalize to
    # live total either way (code-review r5: masking them instead
    # collapsed small shards onto their minimum value)
    samp_invalid = ~jnp.take(sel, pos)
    samp_ops = [jnp.take(op, pos) for op in ops]
    samp_w = jnp.where(samp_invalid, jnp.float32(0),
                       live.astype(jnp.float32) / s)

    def gather(x):
        return jax.lax.all_gather(x, axis, axis=0, tiled=True)

    g_invalid = gather(samp_invalid)          # [n*s]
    g_ops = [gather(op) for op in samp_ops]
    g_w = gather(samp_w)
    # identical sort on every shard: invalid samples last, weights ride
    # as payload
    sorted_samples = jax.lax.sort(
        tuple([g_invalid.astype(jnp.int8)] + g_ops + [g_w]),
        num_keys=1 + len(g_ops))
    w_sorted = sorted_samples[-1]
    cumw = jnp.cumsum(w_sorted)
    total_w = cumw[-1]
    # n-1 weighted quantile positions
    qtargets = jnp.arange(1, n, dtype=jnp.float32) * total_w / n
    qpos = jnp.clip(jnp.searchsorted(cumw, qtargets, side="left")
                    .astype(jnp.int32), 0, n * s - 1)
    bounds = [jnp.take(op_s, qpos)
              for op_s in sorted_samples[1:1 + len(g_ops)]]

    # target shard = number of bounds strictly below the row's key tuple
    tgt = jnp.zeros((L,), jnp.int32)
    for b in range(n - 1):
        gt = jnp.zeros((L,), jnp.bool_)
        eq = jnp.ones((L,), jnp.bool_)
        for op, bound in zip(ops, bounds):
            bv = bound[b]
            gt = gt | (eq & (op > bv))
            eq = eq & (op == bv)
        tgt = tgt + gt.astype(jnp.int32)
    tgt = jnp.where(sel, tgt, n)
    return _exchange_by_target(batch, tgt, ctx, block_cap, tag)


def all_gather_batch(batch: Batch, ctx) -> Batch:
    """SinglePartition / Replicated exchange: every shard gets all rows."""
    axis = ctx.axis_name

    def gather(x):
        return jax.lax.all_gather(x, axis, axis=0, tiled=True)

    cols: Dict[str, Column] = {}
    for name, col in batch.columns.items():
        validity = None if col.validity is None else gather(col.validity)
        cols[name] = Column(gather(col.data), col.dtype, validity,
                            col.dictionary)
    return Batch(cols, gather(batch.selection_mask()))


def stripe_batch(batch: Batch, ctx) -> Batch:
    """Take this shard's contiguous stripe of a replicated batch, so an
    out_spec of P('data') reassembles exactly the full array (order
    preserved — sorted output stays sorted)."""
    n = ctx.n_shards
    cap = batch.capacity
    pad = (-cap) % n
    local = (cap + pad) // n
    i = jax.lax.axis_index(ctx.axis_name)

    def take_stripe(x, fill):
        if pad:
            x = jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])
        return jax.lax.dynamic_slice_in_dim(x, i * local, local)

    cols: Dict[str, Column] = {}
    for name, col in batch.columns.items():
        validity = None if col.validity is None else \
            take_stripe(col.validity, False)
        cols[name] = Column(take_stripe(col.data, 0), col.dtype, validity,
                            col.dictionary)
    return Batch(cols, take_stripe(batch.selection_mask(), False))


def pad_batch_to_multiple(batch: Batch, n: int) -> Batch:
    """Host-side: pad capacity so dim 0 divides the mesh axis."""
    cap = batch.capacity
    pad = (-cap) % n
    if pad == 0:
        return batch
    cols: Dict[str, Column] = {}
    for name, col in batch.columns.items():
        data = jnp.concatenate([col.data,
                                jnp.zeros((pad,), col.data.dtype)])
        validity = None if col.validity is None else jnp.concatenate(
            [col.validity, jnp.zeros((pad,), jnp.bool_)])
        cols[name] = Column(data, col.dtype, validity, col.dictionary)
    sel = jnp.concatenate([batch.selection_mask(),
                           jnp.zeros((pad,), jnp.bool_)])
    return Batch(cols, sel)


def shard_batch_spec(axis: str):
    """PartitionSpec prefix sharding every batch leaf on dim 0."""
    from jax.sharding import PartitionSpec as P
    return P(axis)

"""Mesh construction: the device topology the engine schedules onto.

One 1-D "data" axis for now (row sharding + exchanges); the Mesh API
generalizes to multi-axis layouts (e.g. ("data", "model")) without
changing operator code, because every collective names its axis.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

# jax moved shard_map from jax.experimental to the top level around
# 0.5.x and renamed check_rep -> check_vma; import whichever this jax
# ships (0.4.37 has only the experimental location) and normalize the
# kwarg so call sites can always pass check_vma.
try:
    from jax.experimental.shard_map import shard_map as _shard_map
except ImportError:  # newer jax: top-level export only
    from jax import shard_map as _shard_map

import inspect as _inspect

_SHARD_MAP_PARAMS = frozenset(
    _inspect.signature(_shard_map).parameters)


def shard_map(f, *args, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, *args, **kwargs)

AXIS = "data"

EXCLUDE_KEY = "spark_tpu.sql.mesh.excludeDevices"


def mesh_size(conf) -> int:
    n = int(conf.get("spark_tpu.sql.mesh.size"))
    return max(1, n)


def excluded_device_ids(conf) -> set:
    """Decommissioned device ids (spark_tpu.sql.mesh.excludeDevices):
    drained by the elastic-mesh layer (parallel/elastic.py) or pinned
    by an operator — never meshed over again this session. Malformed
    entries WARN (an operator's typo'd pin-out silently keeping the
    bad device in the gang would be worse than noise)."""
    from .elastic import _parse_int_set
    return _parse_int_set(conf.get(EXCLUDE_KEY))


def get_mesh(conf) -> Optional[Mesh]:
    """Build the 1-D data mesh from conf, or None for single-chip.

    With no exclusions a short device pool is a setup ERROR (the
    remediation-hint diagnostic below). With exclusions — a graceful
    decommission drained part of the gang — the mesh shrinks to the
    surviving pool instead: elasticity means a smaller gang, not a
    failed query. A pool of <= 1 survivors degrades to single-chip,
    which runs on the process's JAX DEFAULT device without consulting
    the exclusion list (see the excludeDevices conf doc) — excluding
    the default device needs JAX visible-device flags, not conf."""
    n = mesh_size(conf)
    if n <= 1:
        return None
    init_distributed(conf)  # no-op unless cluster.coordinator is set
    devices = jax.devices()
    import numpy as np
    if len(devices) < n:
        # a pool short even BEFORE exclusions is a setup error, never
        # elasticity — exclusions must not swallow the diagnostic
        raise RuntimeError(
            f"mesh.size={n} but only {len(devices)} devices visible "
            f"({[d.platform for d in devices[:4]]}...); for CI use "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}")
    excluded = excluded_device_ids(conf)
    if excluded:
        pool = [d for d in devices
                if int(getattr(d, "id", -1)) not in excluded]
        n = min(n, len(pool))
        if n <= 1:
            return None
        return Mesh(np.array(pool[:n]), (AXIS,))
    return Mesh(np.array(devices[:n]), (AXIS,))


def shard_hosts(mesh: Mesh) -> list:
    """Per-shard host identity for telemetry records: the JAX process
    index owning each data-axis position's device (0 for every shard on
    a single-host/virtual-CPU mesh). Multi-host straggler reports need
    the shard -> host mapping to name the slow MACHINE, not just the
    slow mesh position."""
    return [int(getattr(d, "process_index", 0) or 0)
            for d in mesh.devices.flat]


def init_distributed(conf) -> int:
    """Multi-host bring-up: initialize the JAX distributed runtime so
    `jax.devices()` spans every host's chips and the engine's collectives
    ride ICI within a slice and DCN across slices.

    The control-plane analog of the reference's executor registration
    (`CoarseGrainedExecutorBackend.main:405` dialing the driver): every
    host runs the SAME engine process, pointed at one coordinator:

        spark_tpu.sql.cluster.coordinator = host0:8476
        spark_tpu.sql.cluster.numProcesses = <hosts>
        spark_tpu.sql.cluster.processId   = <this host's rank>

    After init, set spark_tpu.sql.mesh.size to the GLOBAL device count;
    gang SPMD replaces the reference's scheduler/shuffle-service fleet —
    there is no other inter-host protocol to deploy. Returns the global
    device count. No-op (returns local count) when no coordinator is
    configured; idempotent per process."""
    coord = str(conf.get("spark_tpu.sql.cluster.coordinator") or "")
    if not coord:
        return len(jax.devices())
    num = int(conf.get("spark_tpu.sql.cluster.numProcesses"))
    pid = int(conf.get("spark_tpu.sql.cluster.processId"))
    state = getattr(jax.distributed, "global_state", None)
    already = state is not None and \
        getattr(state, "coordinator_address", None)
    if not already:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=num, process_id=pid)
    return len(jax.devices())

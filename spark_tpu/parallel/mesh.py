"""Mesh construction: the device topology the engine schedules onto.

One 1-D "data" axis for now (row sharding + exchanges); the Mesh API
generalizes to multi-axis layouts (e.g. ("data", "model")) without
changing operator code, because every collective names its axis.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

AXIS = "data"


def mesh_size(conf) -> int:
    n = int(conf.get("spark_tpu.sql.mesh.size"))
    return max(1, n)


def get_mesh(conf) -> Optional[Mesh]:
    """Build the 1-D data mesh from conf, or None for single-chip."""
    n = mesh_size(conf)
    if n <= 1:
        return None
    devices = jax.devices()
    if len(devices) < n:
        raise RuntimeError(
            f"mesh.size={n} but only {len(devices)} devices visible "
            f"({[d.platform for d in devices[:4]]}...); for CI use "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}")
    import numpy as np
    return Mesh(np.array(devices[:n]), (AXIS,))

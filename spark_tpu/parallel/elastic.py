"""Elastic mesh recovery: gang restart, graceful decommission, and
straggler chunk rebalancing.

PR 5 left mesh failure a one-way door: `is_mesh_failure` -> permanent
single-device fallback, throwing away 7/8 of a v5e-8 gang's throughput
for the rest of the query. PR 8 shipped the DETECTION half (per-shard
telemetry + StragglerMonitor); this module ships the MITIGATION half —
the `BlockManagerDecommissioner`/task-speculation seats (SURVEY §5,
§2.5) re-thought for gang SPMD, where there are no independent task
attempts to relaunch:

- **Gang restart** (`ElasticMeshState`): on a mesh/collective failure
  the executor no longer degrades straight to single-device — it
  re-executes the query still mesh-planned, up to
  `spark_tpu.execution.meshRestart.maxRestarts` times with the
  existing exponential-backoff RetryPolicy. The mesh streaming driver
  finds its own surviving checkpoint (execution/recovery.py) and
  resumes at the checkpointed chunk cursor ON THE MESH, so a
  kill-one-host mid-stream replays at most `checkpoint.everyChunks`
  chunks. Single-device fallback becomes the FINAL rung, not the
  first. The `mesh_restart` chaos seam fires at each restart boundary:
  a fault injected there fails that attempt (budget consumed) and the
  ladder falls through — ultimately to the single-device rung.
- **Graceful decommission** (`MeshDecommissionRequest` +
  `pending_decommission`): `spark_tpu.execution.decommission.shards`
  (or `session.decommission_shards([...])`) requests a drain; the mesh
  chunk driver honors it at the next chunk boundary — forces a
  checkpoint at the current cursor, fires the `decommission` seam, and
  raises the request. The executor excludes the draining shards'
  devices at SESSION level (`spark_tpu.sql.mesh.excludeDevices`, so
  the drain outlives this query), clears the request, and re-executes
  on the reduced gang, which resumes from the forced checkpoint — the
  `BlockManagerDecommissioner:39` analog.
- **Straggler rebalancing** (`RebalanceState` + `ElasticRebalancer`):
  a built-in `on_straggler` bus consumer closes the detect->act loop.
  When the StragglerMonitor flags a shard mid-stream, subsequent
  chunks re-assign live rows AWAY from the flagged shard (its share
  drops by `spark_tpu.sql.straggler.rebalance.maxSkew`, spread over
  the healthy shards) — the moral analog of speculation: the gang
  still steps together, but the slow device steps over fewer rows.
  Assignment is pure data movement inside the (slightly re-padded)
  chunk; per-shard SLOT capacity stays uniform so XLA re-specializes
  at most once per weight change. Results are identical for
  integer/decimal aggregates (partial aggregation is row-assignment
  independent); float aggregates may differ in the last ulp, exactly
  as any change of mesh size or chunk boundaries already does
  (summation order moves).

All three flow through `_record_fault` -> fault_summary -> event
log/history/`GET /queries/<id>/timeline` as the actions
`mesh_restart`, `decommission`, `shard_rebalance`; the registry counts
`mesh_restart_attempts` and `rebalance_rows` (bench sidecars
`tpch_*_mesh_restarts` / `tpch_*_rebalanced_rows`).
"""

from __future__ import annotations

import contextlib
import warnings
from contextvars import ContextVar
from typing import Dict, Optional, Sequence, Set, Tuple

from ..observability.listener import QueryListener

RESTART_ENABLED_KEY = "spark_tpu.execution.meshRestart.enabled"
RESTART_MAX_KEY = "spark_tpu.execution.meshRestart.maxRestarts"
DECOMMISSION_KEY = "spark_tpu.execution.decommission.shards"
EXCLUDE_KEY = "spark_tpu.sql.mesh.excludeDevices"
REBALANCE_ENABLED_KEY = "spark_tpu.sql.straggler.rebalance.enabled"
REBALANCE_MAX_SKEW_KEY = "spark_tpu.sql.straggler.rebalance.maxSkew"
REBALANCE_DECAY_KEY = "spark_tpu.sql.straggler.rebalance.decayChunks"
BACKOFF_KEY = "spark_tpu.execution.backoffMs"


def _parse_int_set(spec, warn: bool = True) -> Set[int]:
    """Comma-separated ints -> set. `warn=False` for per-chunk hot-path
    callers (pending_decommission): toggling process-global warning
    filters there would race the concurrent SQL service's threads, so
    those callers parse silently and one coherent warning fires per
    query instead (discard_stale_decommission)."""
    out: Set[int] = set()
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            out.add(int(part))
        except ValueError:
            if warn:
                warnings.warn(f"ignoring non-integer entry {part!r} in "
                              f"shard/device list {spec!r}")
    return out


# ---------------------------------------------------------------------------
# Gang restart
# ---------------------------------------------------------------------------

class ElasticMeshState:
    """Per-query-execution gang-restart budget: N bounded restart
    attempts with exponential backoff (the existing RetryPolicy), each
    gated by the `mesh_restart` chaos seam. Created fresh by every
    `execute_batch`, so the budget is per execution like every other
    recovery budget."""

    def __init__(self, conf):
        from ..execution.failures import RetryPolicy
        self.enabled = bool(conf.get(RESTART_ENABLED_KEY))
        self.max_restarts = int(conf.get(RESTART_MAX_KEY))
        self.policy = RetryPolicy(self.max_restarts,
                                  float(conf.get(BACKOFF_KEY)))
        #: restart attempts that passed their seam (i.e. were applied)
        self.restarts = 0

    def try_restart(self, record) -> Optional[float]:
        """Consume restart attempts until one passes its chaos seam or
        the budget runs out. Returns the backoff slept (ms) for the
        attempt that will be applied, or None when the ladder must fall
        through to the single-device rung. A fault injected at the
        `mesh_restart` seam fails THAT attempt — recorded with
        ok=False, budget consumed — proving the ladder still lands on
        single-device fallback when restarts keep dying."""
        from ..testing import faults
        if not self.enabled:
            return None
        while True:
            slept = self.policy.attempt_retry()
            if slept is None:
                return None
            try:
                # chaos seam: the restart boundary (host-side, once per
                # attempt) — models the re-admitted host dying again
                faults.fire("mesh_restart")
            except Exception as e:  # noqa: BLE001 — attempt failed
                record("mesh_restart", e, attempt=self.policy.attempts,
                       ok=False)
                continue
            self.restarts += 1
            return slept


def healthy_device_count(conf) -> Optional[int]:
    """Devices currently visible and not decommissioned — the pool a
    gang restart may re-mesh over. None when the backend cannot even
    enumerate (the restart then keeps the configured size and lets the
    next attempt classify whatever happens)."""
    try:
        import jax
        from .mesh import excluded_device_ids
        excl = excluded_device_ids(conf)
        return len([d for d in jax.devices() if d.id not in excl])
    except Exception:  # noqa: BLE001 — probing must never raise
        return None


# ---------------------------------------------------------------------------
# Graceful decommission
# ---------------------------------------------------------------------------

class MeshDecommissionRequest(Exception):
    """Control-flow signal, not a failure: a drain request reached a
    chunk boundary of a running mesh stream. Carries the draining mesh
    positions and their device ids; the executor applies the exclusion
    at session level and re-executes on the reduced gang."""

    def __init__(self, shards: Sequence[int], device_ids: Sequence[int]):
        super().__init__(
            f"decommission requested for shard(s) {sorted(shards)} "
            f"(device ids {sorted(device_ids)})")
        self.shards = tuple(shards)
        self.device_ids = tuple(device_ids)


def pending_decommission(conf, mesh) -> Tuple[Tuple[int, ...],
                                              Tuple[int, ...]]:
    """The drain request's (mesh positions, device ids) valid for the
    CURRENT mesh — empty tuples when nothing is pending. Positions
    outside [0, n) are ignored (a request naming an already-drained
    position must not re-fire forever)."""
    spec = str(conf.get(DECOMMISSION_KEY) or "").strip()
    if not spec:
        return (), ()
    n = int(mesh.devices.size)
    # silent parse: this runs at every chunk boundary, and parse noise
    # is handled ONCE per query by discard_stale_decommission
    requested = _parse_int_set(spec, warn=False)
    positions = sorted(p for p in requested if 0 <= p < n)
    if not positions:
        return (), ()
    devs = list(mesh.devices.flat)
    ids = tuple(int(getattr(devs[p], "id", p)) for p in positions)
    return tuple(positions), ids


def discard_stale_decommission(session_conf, mesh) -> None:
    """Drop a drain request with NO position valid for the gang about
    to run (e.g. `decommission_shards([9])` on an 8-gang): left armed,
    the stale request would silently fire months later the first time
    a LARGER mesh makes the position valid. Called by the executor at
    mesh-query start; a partially-valid request is kept (its valid
    positions still drain)."""
    spec = str(session_conf.get(DECOMMISSION_KEY) or "").strip()
    if not spec:
        return
    n = int(mesh.devices.size)
    requested = _parse_int_set(spec, warn=False)  # re-warned below
    if not requested:
        # nothing parseable at all: the request could never fire, and
        # left armed it would re-warn at every chunk boundary forever
        warnings.warn(
            f"discarding unparseable decommission request {spec!r}")
        session_conf.set(DECOMMISSION_KEY, "")
    elif not any(0 <= p < n for p in requested):
        warnings.warn(
            f"discarding stale decommission request {spec!r}: no "
            f"requested position is valid for the {n}-shard gang")
        session_conf.set(DECOMMISSION_KEY, "")


def apply_decommission(session_conf, device_ids: Sequence[int]) -> None:
    """Persist a drain: merge the device ids into the SESSION-level
    exclusion set (the decommission outlives this query — get_mesh
    builds every later gang over the surviving pool), clear the
    one-shot request key, and follow mesh.size down to the surviving
    pool so PLANNING (join-strategy and exchange sizing divide by n)
    agrees with the gang that will actually run — for this query's
    re-execution and every later one."""
    merged = _parse_int_set(session_conf.get(EXCLUDE_KEY)) \
        | set(int(i) for i in device_ids)
    session_conf.set(EXCLUDE_KEY, ",".join(str(i) for i in sorted(merged)))
    session_conf.set(DECOMMISSION_KEY, "")
    try:
        import jax
        pool = len([d for d in jax.devices()
                    if int(getattr(d, "id", -1)) not in merged])
    except Exception:  # noqa: BLE001 — probing must never fail a drain
        return
    n = int(session_conf.get("spark_tpu.sql.mesh.size") or 0)
    if n > 1 and pool < n:
        session_conf.set("spark_tpu.sql.mesh.size", max(pool, 0))


def decommission_shards(session, shards: Sequence[int]) -> None:
    """The drain API: request a graceful decommission of the given mesh
    positions. A running mesh stream drains at its next chunk boundary
    (checkpoint forced, `decommission` recorded); otherwise the next
    mesh query applies it at its first boundary. MERGES with any
    still-pending request — back-to-back drains of different shards
    must not silently drop the earlier one."""
    pending = _parse_int_set(session.conf.get(DECOMMISSION_KEY))
    merged = pending | {int(s) for s in shards}
    session.conf.set(DECOMMISSION_KEY,
                     ",".join(str(s) for s in sorted(merged)))


# ---------------------------------------------------------------------------
# Straggler chunk rebalancing
# ---------------------------------------------------------------------------

#: the mesh chunk driver installs its live rebalance state here for the
#: duration of its chunk loop; the ElasticRebalancer bus listener
#: (on_straggler fires synchronously on the driver thread, inside the
#: telemetry flush) flags shards into it — the same context-threading
#: pattern as ShardStreamTelemetry, so driver signatures stay stable
_REBALANCE: ContextVar[Optional["RebalanceState"]] = \
    ContextVar("spark_tpu_rebalance", default=None)


def current_rebalance() -> Optional["RebalanceState"]:
    return _REBALANCE.get()


@contextlib.contextmanager
def use_rebalance(state: Optional["RebalanceState"]):
    token = _REBALANCE.set(state)
    try:
        yield state
    finally:
        _REBALANCE.reset(token)


class RebalanceState:
    """Per-stream chunk-row assignment weights over the mesh axis.

    Until a shard is flagged the state is inert and padding takes the
    zero-cost `pad_batch_to_multiple` path. After `flag(shard)`, each
    chunk's live rows are re-assigned: the flagged shard's share drops
    to (1 - maxSkew) x fair, the deficit spreads evenly over healthy
    shards. With `straggler.rebalance.decayChunks` > 0 the penalty is
    not a life sentence: each rebalanced chunk fades every flagged
    shard's penalty linearly by 1/decayChunks, so a recovered shard
    earns its fair share back over that many healthy chunks and the
    state goes inert again (shares return to uniform; 0 keeps the
    legacy stay-flagged-forever behavior). Per-shard slot capacity is
    uniform and sized from the FULL-penalty trajectory (not the
    decayed weights), so shapes stay stable across the whole decay
    and the jitted update step re-specializes at most once per flag. Partial aggregation does not depend on which
    shard folds which row — integer/decimal results are bit-exact;
    float sums can move in the last ulp (summation order), as with
    any mesh-size or chunk-boundary change."""

    def __init__(self, n: int, conf, recovery=None):
        self.n = int(n)
        self.enabled = bool(conf.get(REBALANCE_ENABLED_KEY))
        self.max_skew = float(conf.get(REBALANCE_MAX_SKEW_KEY))
        self.recovery = recovery  # RecoveryContext: record() + metrics
        self.decay_chunks = int(conf.get(REBALANCE_DECAY_KEY))
        self.slow: Set[int] = set()
        #: shard -> remaining penalty in (0, 1]; 1.0 at flag time,
        #: fading by 1/decayChunks per rebalanced chunk (tick())
        self.penalty: Dict[int, float] = {}
        self.moved_rows = 0

    @property
    def active(self) -> bool:
        return bool(self.slow)

    def flag(self, shard: int) -> None:
        """Mark one shard slow (idempotent). Called by the
        ElasticRebalancer when the StragglerMonitor posts
        on_straggler; records ONE `shard_rebalance` action per shard."""
        shard = int(shard)
        if not self.enabled or self.max_skew <= 0:
            return
        if shard in self.slow:
            self.penalty[shard] = 1.0  # re-flag mid-decay: full again
            return
        if not 0 <= shard < self.n:
            return
        if len(self.slow) >= self.n - 1:
            return  # at least one healthy shard must absorb the skew
        self.slow.add(shard)
        self.penalty[shard] = 1.0
        if self.recovery is not None:
            self.recovery.record("shard_rebalance", None, shard=shard,
                                 max_skew=self.max_skew)

    # -- assignment math ----------------------------------------------------

    def _weights(self, decayed: bool = True):
        """Per-shard assignment weights. `decayed=True` scales each
        flagged shard's skew by its remaining penalty (the live
        assignment); `decayed=False` is the full-penalty trajectory
        slot_capacity sizes shapes from, stable across a decay."""
        import numpy as np
        w = np.ones(self.n)
        z = len(self.slow)
        if z and z < self.n:
            deficit = 0.0
            for i in self.slow:
                p = self.penalty.get(i, 1.0) if decayed else 1.0
                w[i] = 1.0 - self.max_skew * p
                deficit += self.max_skew * p
            boost = deficit / (self.n - z)
            for i in range(self.n):
                if i not in self.slow:
                    w[i] = 1.0 + boost
        return w

    def targets(self, live: int):
        """Per-shard live-row assignment for one chunk (sums to
        `live` exactly — largest-remainder rounding)."""
        import numpy as np
        raw = live * self._weights() / self.n
        t = np.floor(raw).astype(np.int64)
        for i in np.argsort(-(raw - t), kind="stable")[:live - t.sum()]:
            t[i] += 1
        return t

    def slot_capacity(self, chunk_capacity: int) -> int:
        """Uniform per-shard slot count: covers the worst-case target
        of a fully-live chunk (+1 rounding margin), constant while the
        flag set is stable so shapes stay stable."""
        import numpy as np
        wmax = float(np.max(self._weights(decayed=False)))
        return int(-(-int(chunk_capacity) * wmax // self.n)) + 1

    def rebalance(self, batch, n: int):
        """Re-assign one chunk's live rows to shard segments by the
        current weights. Pays one host pull of the selection mask per
        chunk — only on the mitigation path (state active), where the
        straggler's stall already dwarfs it."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ..columnar import Batch, Column
        mask = np.asarray(jax.device_get(batch.selection_mask()))
        live_idx = np.flatnonzero(mask)
        live = int(live_idx.size)
        t = self.targets(live)
        s_cap = self.slot_capacity(batch.capacity)
        take = np.zeros(s_cap * n, np.int64)
        sel = np.zeros(s_cap * n, bool)
        off = 0
        for i in range(n):
            k = int(t[i])
            seg = i * s_cap
            take[seg:seg + k] = live_idx[off:off + k]
            sel[seg:seg + k] = True
            off += k
        # accounting: rows shifted OFF the flagged shards vs the even
        # split (the `rebalance_rows` counter / bench sidecar evidence)
        fair = live // n
        moved = sum(max(0, fair - int(t[i])) for i in self.slow)
        self.moved_rows += moved
        if self.recovery is not None and self.recovery.metrics is not None \
                and moved:
            self.recovery.metrics.counter("rebalance_rows").inc(moved)
        self.tick()
        take_d = jnp.asarray(take)
        cols = {}
        for name, c in batch.columns.items():
            data = jnp.take(c.data, take_d, axis=0)
            validity = None if c.validity is None \
                else jnp.take(c.validity, take_d, axis=0)
            cols[name] = Column(data, c.dtype, validity, c.dictionary)
        return Batch(cols, jnp.asarray(sel))

    def tick(self) -> None:
        """One rebalanced chunk elapsed: fade every flagged shard's
        penalty by 1/decayChunks; a shard whose penalty reaches zero
        unflags — when the last one does, `active` goes False and
        padding returns to the zero-cost path (shares uniform
        again)."""
        if self.decay_chunks <= 0:
            return
        step = 1.0 / self.decay_chunks
        for shard in sorted(self.slow):
            p = self.penalty.get(shard, 1.0) - step
            if p > 1e-12:
                self.penalty[shard] = p
            else:
                self.slow.discard(shard)
                self.penalty.pop(shard, None)


def pad_chunk_for_shards(batch, n: int,
                         state: Optional[RebalanceState] = None):
    """The mesh chunk driver's padding step: the plain
    `pad_batch_to_multiple` until a straggler was flagged, the skewed
    re-assignment afterwards."""
    from .shuffle import pad_batch_to_multiple
    if state is None or not state.active:
        return pad_batch_to_multiple(batch, n)
    return state.rebalance(batch, n)


class ElasticRebalancer(QueryListener):
    """Built-in bus subscriber closing the straggler detect->act loop:
    on_straggler (posted synchronously by the StragglerMonitor from the
    telemetry flush, on the driver thread mid-stream) flags the shard
    into the stream's live RebalanceState, so the NEXT chunk's rows
    already skew away from it. Stateless — the per-stream state lives
    in the context var, scoped to exactly the executing stream."""

    _builtin = True

    def on_straggler(self, event) -> None:
        state = current_rebalance()
        if state is not None:
            state.flag(int(event.shard))

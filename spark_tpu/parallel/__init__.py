"""Multi-chip SPMD execution over a `jax.sharding.Mesh`.

The TPU-native replacement for the reference's entire distributed runtime
tier (SURVEY.md sections 2.5/2.6): instead of a DAGScheduler cutting
stages into tasks (`scheduler/DAGScheduler.scala:119`), shuffle files
(`shuffle/sort/SortShuffleManager.scala:73`), Netty block transfer, and a
MapOutputTracker, the whole physical plan runs as ONE gang-scheduled SPMD
program via `shard_map` over a 1-D "data" mesh axis:

- leaves shard rows over the axis (a scan batch is split; Range
  synthesizes only its stripe);
- `ExchangeExec(HashPartitioning)` lowers to device radix-partition +
  `jax.lax.all_to_all` over ICI (parallel/shuffle.py) — the shuffle;
- `ExchangeExec(SinglePartition | Replicated)` lowers to
  `jax.lax.all_gather` — broadcast / global collapse;
- aggregates are planned partial -> exchange -> final (`AggUtils.scala`
  analog, plan/planner.py), so only small accumulator tables ride ICI;
- flags/metrics are `psum`/`pmax`-reduced back to the host — the AQE
  stats channel.
"""

from .mesh import get_mesh, mesh_size
from .shuffle import (all_gather_batch, exchange_hash, pad_batch_to_multiple,
                      shard_batch_spec, stripe_batch)

__all__ = ["get_mesh", "mesh_size", "exchange_hash", "all_gather_batch",
           "stripe_batch", "pad_batch_to_multiple", "shard_batch_spec"]

"""Session catalog: temp views + a persistent parquet warehouse.

Reference: `sql/catalyst/.../catalog/SessionCatalog.scala:1` (temp-view
shadowing, lookup order) + `InMemoryCatalog` + the command layer in
`sql/core/.../execution/command/tables.scala:1`. The TPU-era inversion:
no Hive metastore process — table metadata is a JSON sidecar per table
directory under ``spark_tpu.sql.warehouse.dir`` and the data is plain
parquet parts, so a fresh session over the same warehouse dir sees every
table (the DDL round-trip the reference gets from the metastore).

Lookup order matches the reference: temp views shadow persistent tables.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Dict, Iterator, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from . import types as T
from .expr import AnalysisError

_META = "_spark_tpu_table.json"


def _type_name(dt: T.DataType) -> str:
    return repr(dt)


class Catalog:
    """Mapping-compatible with the former plain dict (``name in``,
    ``[name]``, ``.get``), plus the persistent-table command surface."""

    def __init__(self, session):
        self._session = session
        self._temp: Dict[str, object] = {}

    # -- mapping protocol (temp views shadow persistent tables) -------------

    def warehouse_dir(self) -> str:
        return str(self._session.conf.get("spark_tpu.sql.warehouse.dir"))

    def _table_dir(self, name: str) -> str:
        return os.path.join(self.warehouse_dir(), name.lower())

    def _is_persistent(self, name: str) -> bool:
        return os.path.isfile(os.path.join(self._table_dir(name), _META))

    def __contains__(self, name: str) -> bool:
        return name in self._temp or self._is_persistent(name)

    def __getitem__(self, name: str):
        if name in self._temp:
            return self._temp[name]
        if self._is_persistent(name):
            return self._persistent_source(name)
        raise KeyError(name)

    def get(self, name: str, default=None):
        try:
            return self[name]
        except KeyError:
            return default

    def __setitem__(self, name: str, source) -> None:
        self._temp[name] = source

    def __delitem__(self, name: str) -> None:
        del self._temp[name]

    def __iter__(self) -> Iterator[str]:
        seen = set(self._temp)
        yield from self._temp
        wh = self.warehouse_dir()
        if os.path.isdir(wh):
            for d in sorted(os.listdir(wh)):
                if d not in seen and self._is_persistent(d):
                    yield d

    def __len__(self) -> int:
        return sum(1 for _ in self)

    def keys(self):
        return list(self)

    def _persistent_source(self, name: str):
        # a FRESH dataset each lookup: INSERT INTO appends part files,
        # and the stat-stamped cache_token keeps device caches honest
        from .io.sources import ParquetSource
        src = ParquetSource(self._table_dir(name), name)
        return src

    # -- metadata ------------------------------------------------------------

    def _read_meta(self, name: str) -> dict:
        with open(os.path.join(self._table_dir(name), _META)) as f:
            return json.load(f)

    def _write_meta(self, name: str, meta: dict) -> None:
        os.makedirs(self._table_dir(name), exist_ok=True)
        with open(os.path.join(self._table_dir(name), _META), "w") as f:
            json.dump(meta, f, indent=1)

    # -- commands (command/tables.scala analog) ------------------------------

    def create_table(self, name: str, schema: Optional[pa.Schema] = None,
                     data: Optional[pa.Table] = None,
                     if_not_exists: bool = False,
                     or_replace: bool = False) -> None:
        if name in self._temp:
            raise AnalysisError(
                f"temp view {name!r} already exists")
        if self._is_persistent(name):
            if if_not_exists:
                return
            if not or_replace:
                raise AnalysisError(f"table {name!r} already exists")
            self.drop_table(name)
        if data is not None:
            schema = data.schema
        if schema is None:
            raise AnalysisError("CREATE TABLE needs a schema or a query")
        self._write_meta(name, {
            "name": name,
            "created": time.time(),
            "format": "parquet",
            "schema": {f.name: str(f.type) for f in schema},
        })
        # always materialize one (possibly empty) part so the dataset
        # scanner knows the schema without reading the JSON
        part = data if data is not None else schema.empty_table()
        self._append_part(name, part)

    def _append_part(self, name: str, table: pa.Table) -> None:
        d = self._table_dir(name)
        os.makedirs(d, exist_ok=True)
        existing = [f for f in os.listdir(d) if f.endswith(".parquet")]
        pq.write_table(table,
                       os.path.join(d, f"part-{len(existing):05d}.parquet"))

    def insert_into(self, name: str, table: pa.Table) -> None:
        if not self._is_persistent(name):
            if name in self._temp:
                raise AnalysisError(
                    f"INSERT INTO a temp view {name!r} is not supported")
            raise AnalysisError(f"table {name!r} not found")
        target = self._persistent_source(name)._dataset.schema
        if len(table.schema) != len(target):
            raise AnalysisError(
                f"INSERT INTO {name}: {len(table.schema)} columns for "
                f"{len(target)} target columns")
        # position-based with implicit casts, like the reference's
        # by-position resolution for INSERT
        cols = [table.column(i).cast(target.field(i).type)
                for i in range(len(target))]
        self._append_part(name, pa.table(cols, names=target.names))

    def drop_table(self, name: str, if_exists: bool = False,
                   temp_only: bool = False) -> bool:
        if name in self._temp:
            del self._temp[name]
            return True
        if not temp_only and self._is_persistent(name):
            from .io.device_cache import CACHE
            src = self._persistent_source(name)
            token = src.cache_token()
            if token is not None:
                CACHE.invalidate_token(token)
            shutil.rmtree(self._table_dir(name))
            return True
        if not if_exists:
            raise AnalysisError(f"table {name!r} not found")
        return False

    def list_tables(self) -> List[dict]:
        out = []
        for name in self:
            out.append({"name": name,
                        "isTemporary": name in self._temp})
        return out

    def describe(self, name: str) -> List[dict]:
        if name not in self:
            raise AnalysisError(f"table {name!r} not found")
        src = self[name]
        return [{"col_name": f.name, "data_type": _type_name(f.dtype),
                 "nullable": f.nullable}
                for f in src.schema().fields]

"""Device-resident table cache: loaded scans stay in HBM across queries.

The round-3 headline perf failure was re-ingesting every scan on every
execution (full Parquet read + dictionary-encode + device_put per
query). The reference avoids this with `CacheManager.scala:1`'s
plan-fingerprint cache and the BlockManager's storage tier; here the
analog is a process-level LRU over loaded device Batches keyed on
(source identity stamp, pruned columns, pushed filters), with a byte
budget (`spark_tpu.sql.io.deviceCacheBytes`) — HBM is the storage
memory pool of `UnifiedMemoryManager.scala:49`, with LRU eviction
playing the role of its storage-eviction policy.

Source identity stamps make staleness structural rather than
time-based: an Arrow-backed source gets a fresh monotonic token per
source object (re-registering a table name creates a new source, so
stale hits are impossible), and a Parquet source stamps the file list
with (size, mtime) pairs, so rewritten files miss the cache.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

CACHE_BYTES_KEY = "spark_tpu.sql.io.deviceCacheBytes"


def batch_nbytes(batch) -> int:
    total = 0
    for col in batch.columns.values():
        total += getattr(col.data, "nbytes", 0)
        if col.validity is not None:
            total += getattr(col.validity, "nbytes", 0)
    sel = batch.selection
    if sel is not None:
        total += getattr(sel, "nbytes", 0)
    return total


class DeviceTableCache:
    """LRU cache of loaded device Batches with a byte budget.

    Lock-guarded: the SQL service runs concurrent queries whose scans
    hit/fill/evict this cache from worker threads, and the resource
    arbiter (service/arbiter.py) evicts it under lease pressure."""

    def __init__(self):
        self._lock = threading.RLock()
        self._entries: "OrderedDict[Tuple, Tuple[object, int]]" = \
            OrderedDict()
        #: pin counts per key: entries a RUNNING query was admitted
        #: against (the arbiter pins them) — lease-pressure eviction
        #: must skip these, because evicting a batch another query
        #: still references frees no HBM (the reference stays live)
        #: while the accounting would credit its bytes as free
        self._pins: Dict[Tuple, int] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        #: entries dropped by budget pressure or OOM-ladder clears (the
        #: storage-eviction observable; never reset with clear())
        self.evictions = 0

    def get(self, key) -> Optional[object]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0]

    def contains(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def put(self, key, batch, budget: int) -> None:
        nbytes = batch_nbytes(batch)
        if nbytes > budget:
            return  # larger than the whole budget: don't thrash
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (batch, nbytes)
            self._bytes += nbytes
            while self._bytes > budget:
                # LRU, but skip the just-inserted key and pinned
                # entries — running queries still reference those, so
                # evicting them frees no HBM (evict_bytes discipline)
                victim = next((k for k in self._entries
                               if k != key and not self._pins.get(k)),
                              None)
                if victim is None:
                    break
                _, evicted = self._entries.pop(victim)
                self._bytes -= evicted
                self.evictions += 1

    def evict_bytes(self, nbytes: int) -> int:
        """Evict LRU entries until at least `nbytes` are freed (or
        only pinned entries remain); returns bytes actually freed. The
        storage-eviction lever the cross-query arbiter pulls when an
        execution lease can't fit next to cached tables. Pinned
        entries (in use by a running query) are skipped: their bytes
        would not actually be freed."""
        freed = 0
        with self._lock:
            for key in list(self._entries):
                if freed >= nbytes:
                    break
                if self._pins.get(key):
                    continue
                _, entry_bytes = self._entries.pop(key)
                self._bytes -= entry_bytes
                self.evictions += 1
                freed += entry_bytes
        return freed

    def pin(self, key) -> bool:
        """Mark `key` in-use by a running query (counted); False when
        the entry is not present (caller falls back to leasing)."""
        with self._lock:
            if key not in self._entries:
                return False
            self._pins[key] = self._pins.get(key, 0) + 1
            return True

    def unpin(self, key) -> None:
        with self._lock:
            n = self._pins.get(key)
            if n is not None:
                if n <= 1:
                    del self._pins[key]
                else:
                    self._pins[key] = n - 1

    def invalidate_token(self, token) -> None:
        """Drop every entry whose source stamp is `token`."""
        with self._lock:
            for k in [k for k in self._entries if k[0] == token]:
                _, nbytes = self._entries.pop(k)
                self._bytes -= nbytes

    def clear(self) -> None:
        with self._lock:
            self.evictions += len(self._entries)
            self._entries.clear()
            self._pins.clear()  # unpin on ghost keys is a no-op
            self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes

    def stats(self) -> Dict[str, int]:
        """Observability snapshot (the metrics listener publishes these
        as device_cache_* gauges at every query end)."""
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions, "bytes": self._bytes,
                    "entries": len(self._entries)}


#: process-level cache (the session is effectively a singleton; HBM is a
#: process resource either way, like the reference's block manager)
CACHE = DeviceTableCache()


def scan_cache_key(scan) -> Optional[Tuple]:
    """Cache key for a ScanExec, or None when the source is uncacheable."""
    token = scan.source.cache_token()
    if token is None:
        return None
    cols = None if scan.required_columns is None \
        else tuple(scan.required_columns)
    filters = tuple(repr(f) for f in scan.pushed_filters)
    return (token, cols, filters)


def estimated_scan_bytes(scan) -> Optional[int]:
    """Rough post-prune device footprint of a scan (for the stream-vs-
    resident decision): rows x per-column width, with 2x headroom for
    capacity bucketing. None when the source can't estimate rows."""
    from .. import types as T
    est = scan.source.estimated_rows()
    if est is None:
        return None
    width = 0
    for f in scan.schema().fields:
        if isinstance(f.dtype, T.StringType):
            width += 4  # dictionary codes (dictionary bytes stay host-side)
        elif isinstance(f.dtype, T.DecimalType):
            width += 16
        elif isinstance(f.dtype, (T.IntegerType, T.DateType, T.FloatType)):
            width += 4
        elif isinstance(f.dtype, T.BooleanType):
            width += 1
        else:
            width += 8
        if f.nullable:
            width += 1
    return 2 * est * width


def is_cached(scan) -> bool:
    key = scan_cache_key(scan)
    return key is not None and CACHE.contains(key)


def load_scan(scan, conf) -> object:
    """Load a ScanExec's Batch through the device cache."""
    budget = int(conf.get(CACHE_BYTES_KEY))
    key = scan_cache_key(scan) if budget > 0 else None
    if key is not None:
        batch = CACHE.get(key)
        if batch is not None:
            return batch
    batch = scan.load()
    if key is not None:
        CACHE.put(key, batch, budget)
        # the bytes now count as STORAGE (headroom subtracts
        # CACHE.nbytes): a residency lease the running query took for
        # this scan would double-count — convert it to a pin
        from ..service.arbiter import note_scan_cached
        note_scan_cached(key)
    return batch

"""Socket network stream source: length-framed Arrow IPC over TCP.

The reference's production sources are network-offset-managed
(`KafkaSourceProvider.scala:50`): the broker owns a durable offset per
partition and the consumer commits the range each micro-batch covered.
This engine's analog keeps the durability on the CONSUMER side — every
frame read off the wire is fsync-persisted under the query checkpoint
BEFORE it counts, and the persisted frame count IS the source offset —
so the same offset/seen-log machinery the file source rides
(`streaming.py` `_MetadataLog`) gives the network tier exactly-once
replay for free.

Wire protocol (reusing `udf_worker/protocol.py`'s `>cI` framing, one
type byte + u32 big-endian payload length):

    consumer -> producer, once per connection:
        O frame, 8-byte big-endian payload = durable frame count
        (the offset handshake: "resume after this many frames")
    producer -> consumer, repeatedly:
        R frame, payload = one Arrow IPC stream (a record batch)
        X frame, empty payload = end of stream (optional)

The handshake makes reconnects exactly-once BY CONSTRUCTION: a
connection killed mid-frame loses only bytes that never became a
durable frame, and the next connection's handshake tells the producer
to resume at the durable count — zero loss (nothing durable is
skipped), zero duplication (nothing durable is resent).

Failure ladder (`latest_offset`, once per poll):

    idle    a read that times out waiting for the FIRST byte of a new
            frame = a quiet producer; return the offsets drained so
            far and keep the connection warm.
    stall   the same timeout MID-frame (header or payload partially
            read) = a dead or wedged peer; drop the connection.
    drop    EOF / connection reset / a framing violation
            (ProtocolError) also drop the connection.

Dropped connections climb a reconnect ladder — exponential backoff +
jitter via `failures.RetryPolicy`, budgeted by
`spark_tpu.streaming.source.network.maxReconnects` — counting
`streaming_reconnects` per re-established connection. An exhausted
ladder raises a TRANSIENT-shaped connection error for the trigger
supervisor to classify. A frame that arrives intact but fails to
decode as Arrow is QUARANTINED exactly like the file source's corrupt
file: the reason lands in its seen-log entry, the
`streaming_frames_quarantined` counter ticks, and every replay skips
it — one poison frame cannot wedge the stream.

Chaos seams: `stream_net_connect` fires before every connect attempt
(first connect and each ladder rung), `stream_net_recv` before every
frame read (testing/faults.py).

`FrameProducer` at the bottom is the in-process peer (tests, bench,
preflight): it speaks the handshake, serves frames from the agreed
offset, and survives `kill_connection()` so reconnect scenarios are
one method call.
"""

from __future__ import annotations

import os
import select
import socket
import struct
import threading
import time
import warnings
from typing import Dict, List, Optional

import pandas as pd
import pyarrow as pa

from ..udf_worker.protocol import (MAX_FRAME_BYTES, _HEADER,
                                   ProtocolError, ipc_to_table,
                                   table_to_ipc)

MAX_RECONNECTS_KEY = "spark_tpu.streaming.source.network.maxReconnects"
CONNECT_TIMEOUT_KEY = \
    "spark_tpu.streaming.source.network.connectTimeoutMs"
IDLE_TIMEOUT_KEY = "spark_tpu.streaming.source.network.idleTimeoutMs"
BACKOFF_KEY = "spark_tpu.streaming.source.network.backoffMs"

FRAME_OFFSET = b"O"   # consumer->producer: resume-offset handshake
FRAME_RECORD = b"R"   # producer->consumer: one Arrow IPC record batch
FRAME_END = b"X"      # producer->consumer: end of stream

_OFFSET_STRUCT = struct.Struct(">Q")


class _Idle(Exception):
    """Timed out waiting for the first byte of a new frame: a quiet
    producer, not a failure."""


class _Stall(Exception):
    """Timed out mid-frame: the peer is dead or wedged."""


class NetworkStreamSource:
    """TCP frame source with consumer-side durable offsets (see module
    docstring). API-compatible with the other sources: `source_kind`,
    `attach_checkpoint`, `latest_offset`, `slice`, `to_df`."""

    source_kind = "network"

    def __init__(self, session, host: str, port: int,
                 schema_df: pd.DataFrame):
        self.session = session
        self.host = host
        self.port = int(port)
        self._table = pa.Table.from_pandas(schema_df.iloc[0:0],
                                           preserve_index=False)
        #: seen-frame log entries ({name, rows, quarantined}), the
        #: durable mirror under <checkpoint>/sources/0/; the offset is
        #: len(self._seen), exactly the file source's contract
        self._seen: List[dict] = []
        self._log = None
        self._frames_dir: Optional[str] = None
        #: decoded-frame cache (receipt-time decode); replays on a
        #: fresh query re-read the persisted frame files instead
        self._cache: Dict[int, pa.Table] = {}
        self._sock: Optional[socket.socket] = None
        self._had_connection = False
        self._ended = False

    # -- checkpoint binding -------------------------------------------------

    def attach_checkpoint(self, path: str) -> None:
        from ..streaming import _MetadataLog
        self._log = _MetadataLog(path, metrics=self.session.metrics)
        self._seen = self._log.read_all()
        self._frames_dir = os.path.join(path, "frames")
        os.makedirs(self._frames_dir, exist_ok=True)
        self._cache = {}
        self._ended = any(e.get("end") for e in self._seen)

    # -- socket plumbing ----------------------------------------------------

    def _conf_ms(self, key: str) -> float:
        return float(self.session.conf.get(key))

    def _drop_connection(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    close = _drop_connection

    def _connect(self) -> None:
        """One connect attempt + offset handshake. The caller owns the
        reconnect ladder; a failure here is one consumed rung."""
        from ..testing import faults
        faults.fire("stream_net_connect")
        sock = socket.create_connection(
            (self.host, self.port),
            timeout=self._conf_ms(CONNECT_TIMEOUT_KEY) / 1e3)
        sock.settimeout(self._conf_ms(IDLE_TIMEOUT_KEY) / 1e3)
        payload = _OFFSET_STRUCT.pack(len(self._seen))
        sock.sendall(_HEADER.pack(FRAME_OFFSET, len(payload)) + payload)
        self._sock = sock
        if self._had_connection:
            self.session.metrics.counter("streaming_reconnects").inc()
        self._had_connection = True

    def _recv_exact(self, n: int, mid_frame: bool) -> bytes:
        """Read exactly n bytes; a timeout with NOTHING read yet and
        `mid_frame` unset is the quiet-producer signal (_Idle), any
        other timeout is a stall (_Stall)."""
        buf = b""
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except socket.timeout:
                if not buf and not mid_frame:
                    raise _Idle() from None
                raise _Stall(
                    f"peer stalled mid-frame after {len(buf)}/{n} "
                    f"bytes") from None
            if not chunk:
                raise EOFError(
                    f"Socket closed by peer after {len(buf)}/{n} "
                    f"frame bytes")
            buf += chunk
        return buf

    def _read_frame(self) -> tuple:
        """(type, payload) for the next frame, or raises _Idle/_Stall/
        EOFError/ProtocolError per the failure ladder."""
        header = self._recv_exact(_HEADER.size, mid_frame=False)
        ftype, length = _HEADER.unpack(header)
        if ftype not in (FRAME_RECORD, FRAME_END):
            raise ProtocolError(
                f"unexpected frame type {ftype!r} from producer "
                f"(cannot resync a byte stream; reconnecting)")
        if length > MAX_FRAME_BYTES:
            raise ProtocolError(
                f"frame length {length} exceeds MAX_FRAME_BYTES")
        payload = self._recv_exact(length, mid_frame=True) \
            if length else b""
        return ftype, payload

    # -- durable receipt ----------------------------------------------------

    def _persist(self, idx: int) -> None:
        if self._log is not None:
            self._log.add(idx, self._seen[idx])

    def _accept_frame(self, payload: bytes) -> None:
        """Persist one received frame durably, THEN count it: the frame
        file lands (fsync + atomic rename) before its seen-log entry,
        and the entry before the offset moves, so a crash anywhere
        leaves a prefix — the handshake count never covers bytes that
        could be lost."""
        idx = len(self._seen)
        name = f"frame-{idx:06d}.arrow"
        if self._frames_dir is not None:
            from ..execution.state_store import fsync_replace
            full = os.path.join(self._frames_dir, name)
            tmp = full + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            fsync_replace(tmp, full)
        entry = {"name": name, "rows": 0, "quarantined": None}
        try:
            t = self._conform(ipc_to_table(payload))
            entry["rows"] = int(t.num_rows)
            self._cache[idx] = t
        except Exception as e:  # noqa: BLE001 — decode = quarantine
            entry["quarantined"] = f"{type(e).__name__}: {e}"[:200]
            self.session.metrics.counter(
                "streaming_frames_quarantined").inc()
            warnings.warn(
                f"quarantined poison network frame {idx} from "
                f"{self.host}:{self.port}: {entry['quarantined']}")
        self._seen.append(entry)
        self._persist(idx)

    def _conform(self, t: pa.Table) -> pa.Table:
        if t.schema == self._table.schema:
            return t
        return t.select(self._table.column_names).cast(self._table.schema)

    # -- the source contract ------------------------------------------------

    def latest_offset(self) -> int:
        """Drain every frame the producer has ready (bounded by the
        idle timeout) and return the durable frame count. Connection
        failures climb the reconnect ladder; the ladder's budget is
        per-poll, so a long-lived stream never exhausts it on
        accumulated history."""
        from ..execution.failures import RetryPolicy
        from ..testing import faults
        if self._ended:
            return len(self._seen)
        policy = RetryPolicy(
            int(self.session.conf.get(MAX_RECONNECTS_KEY)),
            self._conf_ms(BACKOFF_KEY))
        while True:
            if self._sock is None:
                try:
                    self._connect()
                except OSError as e:
                    self._drop_connection()
                    if policy.attempt_retry() is None:
                        raise ConnectionError(
                            f"network source {self.host}:{self.port}: "
                            f"connection attempt budget exhausted "
                            f"after {policy.attempts} reconnects "
                            f"({type(e).__name__}: {e})") from e
                continue
            try:
                faults.fire("stream_net_recv")
                ftype, payload = self._read_frame()
            except _Idle:
                return len(self._seen)
            except (_Stall, EOFError, ConnectionError, ProtocolError,
                    OSError) as e:
                self._drop_connection()
                if policy.attempt_retry() is None:
                    raise ConnectionError(
                        f"network source {self.host}:{self.port}: "
                        f"connection attempt budget exhausted after "
                        f"{policy.attempts} reconnects "
                        f"({type(e).__name__}: {e})") from e
                continue
            if ftype == FRAME_END:
                idx = len(self._seen)
                self._seen.append({"name": None, "rows": 0,
                                   "quarantined": None, "end": True})
                self._persist(idx)
                self._ended = True
                self._drop_connection()
                return len(self._seen)
            self._accept_frame(payload)

    def slice(self, start: int, end: int) -> pa.Table:
        """Rows of the durable frames in [start, end), skipping
        quarantined frames and the end marker — replays read the
        PERSISTED bytes, so a fresh query over the checkpoint sees
        byte-identical batches."""
        if end > len(self._seen):
            raise RuntimeError(
                f"network seen-frame log has {len(self._seen)} entries "
                f"but the planned offset range is [{start}, {end}): "
                f"frames covered by a planned batch vanished; cannot "
                f"recover exactly-once")
        tables = []
        for i in range(start, end):
            entry = self._seen[i]
            if entry.get("quarantined") or entry.get("end") \
                    or not entry.get("rows"):
                continue
            t = self._cache.get(i)
            if t is None:
                if self._frames_dir is None:
                    raise RuntimeError(
                        f"network frame {i} is not cached and no "
                        f"checkpoint is attached to re-read it from")
                with open(os.path.join(self._frames_dir,
                                       entry["name"]), "rb") as f:
                    t = self._conform(ipc_to_table(f.read()))
                self._cache[i] = t
            tables.append(t)
        if not tables:
            return self._table
        return pa.concat_tables(tables)

    def quarantined(self) -> List[dict]:
        return [dict(e, index=i) for i, e in enumerate(self._seen)
                if e.get("quarantined")]

    def to_df(self):
        from ..dataframe import DataFrame
        from ..streaming import _StreamSource
        return DataFrame(self.session, _StreamSource(self))


class FrameProducer:
    """In-process protocol peer for tests/bench/preflight: listens on
    an ephemeral port, answers each connection's offset handshake by
    serving frames FROM THAT OFFSET, and exposes `kill_connection()` /
    `kill_connection_midframe()` so reconnect and stall scenarios are
    deterministic one-liners. Thread-confined state: the serve loop
    runs on one daemon thread; the driving test thread only appends
    payloads (GIL-atomic) and sets events."""

    def __init__(self):
        self._payloads: List[bytes] = []
        self._stop = threading.Event()
        self._end_when_drained = threading.Event()
        self._kill = threading.Event()
        self._kill_midframe = threading.Event()
        self._lsock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self.connections = 0

    def start(self) -> int:
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(2)
        self._lsock.settimeout(0.05)
        self.port = self._lsock.getsockname()[1]
        self._thread = threading.Thread(
            target=self._serve, daemon=True,
            name="spark-tpu-frame-producer")
        self._thread.start()
        return self.port

    def send(self, df) -> None:
        """Queue one frame (a pandas DataFrame or Arrow table)."""
        t = df if isinstance(df, pa.Table) \
            else pa.Table.from_pandas(df, preserve_index=False)
        self._payloads.append(table_to_ipc(t))

    def send_poison(self, payload: bytes = b"not arrow bytes") -> None:
        """Queue a frame whose payload will not decode (the
        quarantine path)."""
        self._payloads.append(bytes(payload))

    def end(self) -> None:
        """Send X once every queued frame has been served."""
        self._end_when_drained.set()

    def kill_connection(self) -> None:
        """Drop the live connection at the next frame boundary (the
        clean mid-stream kill; the consumer sees EOF)."""
        self._kill.set()

    def kill_connection_midframe(self) -> None:
        """Drop the live connection after sending only PART of the
        next frame (the stall/torn-frame kill)."""
        self._kill_midframe.set()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass

    # -- serve loop (producer daemon thread only) ---------------------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.connections += 1
            try:
                self._serve_one(conn)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _read_handshake(self, conn) -> int:
        buf = b""
        want = _HEADER.size + _OFFSET_STRUCT.size
        while len(buf) < want:
            chunk = conn.recv(want - len(buf))
            if not chunk:
                raise OSError("consumer closed before handshake")
            buf += chunk
        ftype, length = _HEADER.unpack(buf[:_HEADER.size])
        if ftype != FRAME_OFFSET or length != _OFFSET_STRUCT.size:
            raise OSError(f"bad handshake frame {ftype!r}/{length}")
        return _OFFSET_STRUCT.unpack(buf[_HEADER.size:])[0]

    def _serve_one(self, conn) -> None:
        conn.settimeout(5.0)
        idx = self._read_handshake(conn)
        while not self._stop.is_set():
            if self._kill.is_set():
                self._kill.clear()
                return
            if idx < len(self._payloads):
                p = self._payloads[idx]
                header = _HEADER.pack(FRAME_RECORD, len(p))
                if self._kill_midframe.is_set():
                    self._kill_midframe.clear()
                    conn.sendall(header + p[:max(1, len(p) // 2)])
                    return
                conn.sendall(header + p)
                idx += 1
                continue
            if self._end_when_drained.is_set():
                conn.sendall(_HEADER.pack(FRAME_END, 0))
                return
            # idle: the consumer never sends after the handshake, so a
            # readable socket means FIN/RST — a vanished consumer (the
            # tests' hard-crash simulation) must free this loop for the
            # next connection's accept, not wedge it polling forever
            readable, _, _ = select.select([conn], [], [], 0)
            if readable:
                try:
                    if not conn.recv(1):
                        return
                except OSError:
                    return
            time.sleep(0.002)

"""Table sources: the host->HBM ingest edge.

Plays the role of the reference's DataSource V2 read stack
(`connector/read/ScanBuilder` -> `Scan` -> `Batch` with
`SupportsPushDownFilters` / `SupportsPushDownRequiredColumns`) and of the
vectorized Parquet reader (`VectorizedParquetRecordReader.java:54`): the
C++ Arrow/Parquet reader does columnar decode + predicate/column pushdown
on host, then columns are dictionary-encoded/padded and device_put —
ingest is the only place bytes cross host->device (SURVEY.md section 2.4).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.dataset as pa_dataset

from .. import types as T
from ..columnar import Batch
from ..expr import (And, BinaryComparison, ColumnRef, EQ, Expression, GE, GT,
                    In, IsNull, LE, LT, Literal, NE, Not, Or)


def _decimal_literal_scalar(col_field: pa.Field, value):
    """Coerce a numeric literal to the column's decimal type for a
    pushed comparison — pyarrow cannot compare decimal to float64.
    Returns None when the value is not exactly representable at the
    column's scale (the conjunct then stays residual-only, where the
    device compares in float and is exact)."""
    import decimal as D
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return None
    d = D.Decimal(str(value))
    q = d.quantize(D.Decimal(1).scaleb(-col_field.type.scale))
    if q != d:
        return None  # rounding would change the predicate
    return pa.scalar(q, type=col_field.type)


def expr_to_arrow(e: Expression, schema: Optional[pa.Schema] = None):
    """Convert a pushable predicate to a pyarrow.dataset expression.
    Returns None when not convertible (the conjunct stays residual)."""
    if isinstance(e, ColumnRef):
        return pc.field(e._name)
    if isinstance(e, Literal):
        v = e.value
        if isinstance(e._dtype, T.DateType):
            import datetime
            # date literals carry either epoch days (SQL to_date path)
            # or a datetime.date (F.lit(date) path)
            if not isinstance(v, datetime.date):
                v = datetime.date(1970, 1, 1) + \
                    datetime.timedelta(days=int(v))
        return pa.scalar(v) if not isinstance(v, Expression) else None
    if isinstance(e, BinaryComparison):
        le, re = e.children
        l = expr_to_arrow(le, schema)
        r = expr_to_arrow(re, schema)
        if l is None or r is None:
            return None
        # decimal column vs numeric literal: coerce the literal
        if schema is not None:
            for col_e, is_left in ((le, True), (re, False)):
                lit_e = re if is_left else le
                if isinstance(col_e, ColumnRef) and isinstance(lit_e, Literal):
                    idx = schema.get_field_index(col_e._name)
                    if idx >= 0 and pa.types.is_decimal(schema.field(idx).type):
                        s = _decimal_literal_scalar(schema.field(idx),
                                                    lit_e.value)
                        if s is None:
                            return None
                        if is_left:
                            r = s
                        else:
                            l = s
        ops = {EQ: lambda a, b: a == b, NE: lambda a, b: a != b,
               LT: lambda a, b: a < b, LE: lambda a, b: a <= b,
               GT: lambda a, b: a > b, GE: lambda a, b: a >= b}
        return ops[type(e)](l, r)
    if isinstance(e, And):
        l, r = (expr_to_arrow(c, schema) for c in e.children)
        return None if l is None or r is None else l & r
    if isinstance(e, Or):
        l, r = (expr_to_arrow(c, schema) for c in e.children)
        return None if l is None or r is None else l | r
    if isinstance(e, Not):
        c = expr_to_arrow(e.children[0], schema)
        return None if c is None else ~c
    if isinstance(e, In):
        c = expr_to_arrow(e.children[0], schema)
        return None if c is None else c.isin(list(e.values))
    if isinstance(e, IsNull):
        c = expr_to_arrow(e.children[0], schema)
        return None if c is None else c.is_null()
    return None


class TableSource:
    name: str = "<source>"

    def schema(self) -> T.Schema:
        raise NotImplementedError

    def can_push(self, e: Expression) -> bool:
        return False

    def load(self, required_columns: Optional[Sequence[str]],
             pushed_filters: Sequence[Expression]) -> Batch:
        raise NotImplementedError

    def estimated_rows(self) -> Optional[int]:
        return None

    def column_stats(self) -> Optional[dict]:
        """Per-column statistics, `{name: {"min", "max", "null_count",
        "row_groups"}}`, or None when unavailable. Parquet sources read
        these from footers (no row data touched); consumers are the
        reorder cost model's range selectivities
        (plan/join_reorder.py) and the analyzer's SUM_I64_OVERFLOW
        magnitude bounds (analysis/plan_analyzer.py). Advisory only:
        min/max are BOUNDS over the whole dataset, never per-row
        truth, so consumers may only use them to widen/narrow
        estimates — never for correctness."""
        return None

    def cache_token(self):
        """Identity stamp for the device-table cache; None = uncacheable.
        Must change whenever the underlying data can differ."""
        return None


def _arrow_schema_to_engine(schema: pa.Schema) -> T.Schema:
    from ..columnar import _ARROW_TO_DTYPE
    fields = []
    for f in schema:
        at = f.type
        if pa.types.is_string(at) or pa.types.is_large_string(at) or \
                pa.types.is_dictionary(at) or pa.types.is_null(at):
            # arrow `null` = an empty/all-None object column (e.g. a
            # streaming schema df): STRING is the dtype it would carry
            # with any value present (columnar casts it the same way)
            dt: T.DataType = T.STRING
        elif pa.types.is_decimal(at):
            dt = T.DecimalType(at.precision, at.scale)
        elif pa.types.is_timestamp(at):
            dt = T.TIMESTAMP
        elif at == pa.date32():
            dt = T.DATE
        elif pa.types.is_list(at) or pa.types.is_large_list(at):
            elem = _arrow_schema_to_engine(
                pa.schema([pa.field("e", at.value_type)])).fields[0]
            dt = T.ArrayType(elem.dtype)
        else:
            dt = _ARROW_TO_DTYPE.get(at)
            if dt is None:
                raise TypeError(f"unsupported arrow type {at} ({f.name})")
        fields.append(T.Field(f.name, dt, f.nullable))
    return T.Schema(fields)


class DictUnifier:
    """Grows one global dictionary per string column across chunks so
    device codes are comparable between chunks (append-only: codes handed
    out earlier stay valid). The analog of the reference's per-column
    dictionary pages being resolved to one dictionary at read time."""

    def __init__(self):
        self.dicts = {}

    def unify(self, table: pa.Table) -> pa.Table:
        cols = []
        for name, col in zip(table.column_names, table.columns):
            arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
            at = arr.type
            if pa.types.is_string(at) or pa.types.is_large_string(at):
                arr = arr.cast(pa.string()).dictionary_encode()
                at = arr.type
            if pa.types.is_dictionary(at):
                chunk_dict = arr.dictionary.cast(pa.string())
                glob = self.dicts.get(name)
                if glob is None:
                    glob = chunk_dict
                else:
                    present = pc.index_in(chunk_dict, value_set=glob)
                    new_mask = pc.is_null(present)
                    if pc.any(new_mask).as_py():
                        new_vals = pc.filter(chunk_dict, new_mask)
                        glob = pa.concat_arrays([glob, new_vals])
                self.dicts[name] = glob
                mapping = pc.index_in(chunk_dict, value_set=glob) \
                    .cast(pa.int32())
                codes = mapping.take(arr.indices)
                arr = pa.DictionaryArray.from_arrays(codes, glob)
            cols.append(arr)
        return pa.table(cols, names=table.column_names)


class ChunkIterator:
    """Single-pass iterator of uniform-capacity Batches over a record
    -batch stream; `.dictionaries` holds the final global dictionaries."""

    def __init__(self, batches_iter, chunk_rows: int):
        self._batches = batches_iter
        self._chunk_rows = chunk_rows
        self._capacity = None
        self._pending = []
        self._pending_rows = 0
        self._done = False
        self._failed: Optional[BaseException] = None
        self._unifier = DictUnifier()

    @property
    def dictionaries(self):
        return self._unifier.dicts

    def __iter__(self):
        return self

    def _fill(self) -> None:
        if self._failed is not None:
            # the underlying reader raised mid-stream: a generator dies
            # when an exception propagates through it, so continuing
            # would silently truncate the stream to the buffered prefix
            # (reading as end-of-stream). Poison the iterator instead —
            # a per-chunk ingest retry re-raises the original error and
            # the whole-query ladder restarts the stream fresh.
            raise self._failed
        while not self._done and self._pending_rows < self._chunk_rows:
            try:
                rb = next(self._batches)
            except StopIteration:
                self._done = True
                break
            except Exception as e:
                self._failed = e
                raise
            self._pending.append(rb)
            self._pending_rows += rb.num_rows

    def _take_chunk(self) -> Optional[pa.Table]:
        """One chunk's Arrow slice off the stream (the shared cursor
        advance of __next__ and skip_chunks, so both cut identical
        chunk boundaries), or None at end of stream."""
        self._fill()
        if self._pending_rows == 0:
            return None
        table = pa.Table.from_batches(self._pending)
        take = min(self._pending_rows, self._chunk_rows)
        chunk = table.slice(0, take)
        rest = table.slice(take)
        self._pending = rest.to_batches() if rest.num_rows else []
        self._pending_rows = rest.num_rows
        return chunk

    def skip_chunks(self, n: int) -> int:
        """Advance the cursor past the next `n` chunks without
        dictionary-unifying or moving bytes to the device — the
        checkpoint-restore path resumes a stream at a chunk cursor.
        Returns how many chunks were actually skipped (fewer when the
        stream ends first)."""
        skipped = 0
        while skipped < int(n):
            if self._take_chunk() is None:
                break
            skipped += 1
        return skipped

    def _host_next(self) -> Optional[pa.Table]:
        """One decoded + dictionary-unified HOST chunk (pa.Table), or
        None at end of stream. All the per-chunk host work lives here;
        device placement stays in __next__ — the split the prefetcher
        (PrefetchChunkIterator) overlaps with device compute."""
        chunk = self._take_chunk()
        if chunk is None:
            return None
        if self._capacity is None:
            from ..columnar import bucket_capacity
            self._capacity = bucket_capacity(self._chunk_rows)
        return self._unifier.unify(chunk)

    def _to_device(self, chunk: pa.Table) -> Batch:
        return Batch.from_arrow(chunk, capacity=self._capacity)

    def __next__(self) -> Batch:
        chunk = self._host_next()
        if chunk is None:
            raise StopIteration
        return self._to_device(chunk)


import itertools

_SOURCE_TOKENS = itertools.count()


class ArrowTableSource(TableSource):
    """In-memory table (the reference's LocalRelation / InMemoryRelation)."""

    def __init__(self, name: str, table: pa.Table):
        self.name = name
        self.table = table
        # fresh per-source stamp: re-registering a name builds a new
        # source object, so a stale device-cache hit is impossible
        self._cache_token = ("arrow", next(_SOURCE_TOKENS))

    def cache_token(self):
        return self._cache_token

    def schema(self) -> T.Schema:
        return _arrow_schema_to_engine(self.table.schema)

    def can_push(self, e: Expression) -> bool:
        return expr_to_arrow(e, self.table.schema) is not None

    def estimated_rows(self):
        return self.table.num_rows

    #: row bound above which in-memory stats are skipped: unlike a
    #: Parquet footer read, computing them means min/max SCANS over
    #: the whole table, and the optimize path must stay cheap
    _STATS_MAX_ROWS = 1 << 22

    def column_stats(self) -> Optional[dict]:
        """In-memory analog of the Parquet footer read: one vectorized
        min/max pass per numeric/temporal column, cached per source
        (re-registering a table builds a fresh source). Tables past
        _STATS_MAX_ROWS report no stats rather than paying full-column
        scans during optimization."""
        cached = getattr(self, "_column_stats", None)
        if cached is not None:
            return cached
        if self.table.num_rows > self._STATS_MAX_ROWS:
            self._column_stats = {}
            return self._column_stats
        stats: dict = {}
        for name, col in zip(self.table.column_names, self.table.columns):
            at = col.type
            if not (pa.types.is_integer(at) or pa.types.is_floating(at)
                    or pa.types.is_decimal(at) or at == pa.date32()):
                continue
            try:
                mm = pc.min_max(col)
                lo, hi = mm["min"].as_py(), mm["max"].as_py()
            except Exception:  # noqa: BLE001 — stats are advisory
                continue
            if lo is None or hi is None:
                continue
            stats[name] = {"min": lo, "max": hi,
                           "null_count": col.null_count, "row_groups": 1}
        self._column_stats = stats
        return stats

    def load(self, required_columns, pushed_filters) -> Batch:
        from ..testing import faults
        faults.fire("scan_load")  # chaos seam: host->HBM ingest edge
        t = self.table
        for f in pushed_filters:
            ae = expr_to_arrow(f, self.table.schema)
            if ae is not None:
                t = t.filter(ae)
        if required_columns is not None:
            t = t.select(list(required_columns))
        return Batch.from_arrow(t)

    def load_chunks(self, required_columns, pushed_filters,
                    chunk_rows: int) -> ChunkIterator:
        t = self.table
        for f in pushed_filters:
            ae = expr_to_arrow(f, self.table.schema)
            if ae is not None:
                t = t.filter(ae)
        if required_columns is not None:
            t = t.select(list(required_columns))
        return ChunkIterator(iter(t.to_batches()), chunk_rows)


class CsvSource(ArrowTableSource):
    """CSV via the C++ Arrow reader (reference: csv/CSVFileFormat +
    UnivocityParser; here native decode + dictionary-encoding happen
    before any bytes reach the device). Eagerly read: CSV has no
    row-group skipping, so pushdown happens post-parse in Arrow."""

    def __init__(self, path: str, name: Optional[str] = None, **options):
        import pyarrow.csv as pa_csv
        parse = pa_csv.ParseOptions(
            delimiter=options.get("sep", options.get("delimiter", ",")))
        read = pa_csv.ReadOptions(
            autogenerate_column_names=not options.get("header", True))
        table = pa_csv.read_csv(path, parse_options=parse,
                                read_options=read)
        super().__init__(name or os.path.basename(path).split(".")[0],
                         table)


class JsonSource(ArrowTableSource):
    """Line-delimited JSON via the C++ Arrow reader (reference:
    json/JsonFileFormat + JacksonParser)."""

    def __init__(self, path: str, name: Optional[str] = None):
        import pyarrow.json as pa_json
        table = pa_json.read_json(path)
        super().__init__(name or os.path.basename(path).split(".")[0],
                         table)


class ParquetSource(TableSource):
    """Parquet directory/file via the C++ Arrow dataset reader: column
    pruning + row-group predicate skipping happen in native code before
    any bytes reach the device."""

    def __init__(self, path: str, name: Optional[str] = None):
        self.path = path
        self.name = name or os.path.basename(path).split(".")[0]
        self._dataset = pa_dataset.dataset(path, format="parquet")
        self._column_stats: Optional[dict] = None

    def column_stats(self) -> Optional[dict]:
        """Per-column min/max + null/row-group counts merged across
        every fragment's footer row-group statistics (the C++ reader
        exposes them without touching row data). Cached per source —
        the source object is rebuilt on re-registration, so staleness
        follows the same lifecycle as cache_token. A column missing
        min/max in ANY row group is omitted entirely (a partial bound
        is not a bound)."""
        if self._column_stats is not None:
            return self._column_stats
        stats: dict = {}
        dropped = set()
        n_groups = 0
        try:
            for frag in self._dataset.get_fragments():
                md = frag.metadata
                for rg in range(md.num_row_groups):
                    n_groups += 1
                    rgm = md.row_group(rg)
                    for ci in range(rgm.num_columns):
                        col = rgm.column(ci)
                        name = col.path_in_schema
                        st = col.statistics
                        if name in dropped:
                            continue
                        if st is None or not st.has_min_max:
                            dropped.add(name)
                            stats.pop(name, None)
                            continue
                        cur = stats.get(name)
                        nulls = st.null_count if st.has_null_count \
                            else None
                        if cur is None:
                            stats[name] = {"min": st.min, "max": st.max,
                                           "null_count": nulls,
                                           "row_groups": 1}
                        else:
                            cur["min"] = min(cur["min"], st.min)
                            cur["max"] = max(cur["max"], st.max)
                            if nulls is None:
                                cur["null_count"] = None
                            elif cur["null_count"] is not None:
                                cur["null_count"] += nulls
                            cur["row_groups"] += 1
        except Exception:  # noqa: BLE001 — stats are advisory
            self._column_stats = {}
            return self._column_stats
        # a column absent from some row group has no dataset-wide bound
        for name in list(stats):
            if stats[name]["row_groups"] != n_groups:
                del stats[name]
        self._column_stats = stats
        return self._column_stats

    def cache_token(self):
        """(path, per-file (size, mtime_ns)) stamp: rewriting any file in
        the dataset invalidates cached device tables for it."""
        stamps = []
        try:
            for f in self._dataset.files:
                st = os.stat(f)
                stamps.append((f, st.st_size, st.st_mtime_ns))
        except OSError:
            return None
        return ("parquet", self.path, tuple(stamps))

    def schema(self) -> T.Schema:
        return _arrow_schema_to_engine(self._dataset.schema)

    def can_push(self, e: Expression) -> bool:
        return expr_to_arrow(e, self._dataset.schema) is not None

    def estimated_rows(self):
        try:
            return sum(f.metadata.num_rows for f in self._dataset.get_fragments())
        except Exception:
            return None

    def load(self, required_columns, pushed_filters) -> Batch:
        from ..testing import faults
        faults.fire("scan_load")  # chaos seam: host->HBM ingest edge
        ae = None
        for f in pushed_filters:
            e = expr_to_arrow(f, self._dataset.schema)
            if e is not None:
                ae = e if ae is None else (ae & e)
        t = self._dataset.to_table(
            columns=list(required_columns) if required_columns is not None else None,
            filter=ae)
        return Batch.from_arrow(t)

    def load_chunks(self, required_columns, pushed_filters,
                    chunk_rows: int) -> ChunkIterator:
        ae = None
        for f in pushed_filters:
            e = expr_to_arrow(f, self._dataset.schema)
            if e is not None:
                ae = e if ae is None else (ae & e)
        scanner = self._dataset.scanner(
            columns=list(required_columns) if required_columns is not None else None,
            filter=ae, batch_size=min(chunk_rows, 1 << 20))
        return ChunkIterator(scanner.to_batches(), chunk_rows)


# ---------------------------------------------------------------------------
# Double-buffered ingest (SURVEY 2.5 "Async/overlap": the shuffle-fetch/
# compute pipelining seat, host->HBM edition)
# ---------------------------------------------------------------------------

INGEST_PREFETCH_KEY = "spark_tpu.sql.ingest.prefetch"


class PrefetchChunkIterator:
    """Double-buffered wrapper over a ChunkIterator: a background thread
    decodes + dictionary-unifies Parquet chunk N+1 into HOST buffers
    (``ChunkIterator._host_next`` — pyarrow releases the GIL, so the
    decode genuinely overlaps the consumer's device compute) while the
    consumer computes chunk N. Bounded to ONE in-flight chunk (a
    size-1 queue), and device placement stays on the CONSUMER thread,
    so HBM residency, arbiter leases and the per-chunk retry/checkpoint
    semantics of the streaming drivers are unchanged.

    Fault behavior: the worker runs each host decode under the SAME
    per-chunk retry path the compute steps use (``ChunkRetrier`` with
    the ``ingest_prefetch`` chaos seam) — a transient fault fired at
    the seam replays exactly one chunk's decode (`rec_chunks_replayed`
    counts it); a real reader failure poisons the inner iterator as
    before and surfaces on the consumer thread for the whole-query
    ladder.

    Observability: ``ingest_stall_ms`` counts time the consumer waited
    for a chunk (the pipeline failing to hide host decode) and
    ``ingest_overlap_ms`` counts decode time hidden behind compute —
    both in the process metrics registry and the `tpch_*` bench
    sidecars."""

    def __init__(self, inner: ChunkIterator, conf, recovery=None,
                 metrics=None):
        from ..execution.recovery import ChunkRetrier
        self._inner = inner
        self._retrier = ChunkRetrier(conf, recovery,
                                     site="ingest_prefetch")
        self._metrics = metrics
        self._started = False
        self._closed = False
        self._chunk = 0  # next chunk ordinal the worker will decode
        import queue as _queue
        import threading
        import weakref
        self._queue: "_queue.Queue" = _queue.Queue(maxsize=1)
        # the worker is handed this event (never `self`): when the
        # consumer abandons the iterator without close() — a fault
        # unwinding a chunk driver mid-stream — the iterator becomes
        # unreachable (the thread holds no ref to it), this finalizer
        # fires, and the worker exits instead of spinning forever on
        # its full queue holding a decoded chunk
        self._stop = threading.Event()
        self._finalizer = weakref.finalize(self, self._stop.set)
        #: the worker thread, kept so close() can JOIN it (bounded):
        #: a daemon thread must not outlive its query — the lockwatch
        #: stress test asserts none does
        self._thread: "threading.Thread | None" = None

    # -- ChunkIterator surface ---------------------------------------------

    @property
    def dictionaries(self):
        return self._inner.dictionaries

    def skip_chunks(self, n: int) -> int:
        """Checkpoint-restore cursor advance; only valid before the
        worker starts (the drivers skip right after load_chunks)."""
        if self._started:
            raise RuntimeError("skip_chunks after prefetch started")
        skipped = self._inner.skip_chunks(n)
        self._chunk += skipped
        return skipped

    def __iter__(self):
        return self

    # -- pipeline -----------------------------------------------------------

    @staticmethod
    def _worker(host_next, retrier, q, stop, chunk) -> None:
        # deliberately a staticmethod over plain arguments: holding a
        # ref to the iterator would keep it reachable forever and its
        # abandonment finalizer (see __init__) could never fire
        import queue as _queue
        import time as _time
        while not stop.is_set():
            t0 = _time.perf_counter()
            try:
                item = ("ok", retrier.run(host_next, chunk=chunk),
                        _time.perf_counter() - t0)
            except BaseException as e:  # noqa: BLE001 — relayed verbatim
                item = ("err", e, 0.0)
            # bounded put that notices close()/abandonment: the worker
            # must not strand blocked on a full size-1 queue forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    break
                except _queue.Full:
                    continue
            if item[0] == "err" or item[1] is None:
                return
            chunk += 1

    def __next__(self) -> Batch:
        import threading
        import time as _time
        if self._closed:
            raise StopIteration
        if not self._started:
            self._started = True
            self._thread = threading.Thread(
                target=self._worker, daemon=True,
                name="spark-tpu-ingest-prefetch",
                args=(self._inner._host_next, self._retrier,
                      self._queue, self._stop, self._chunk))
            self._thread.start()
        t0 = _time.perf_counter()
        kind, payload, decode_s = self._queue.get()
        stall_s = _time.perf_counter() - t0
        if kind == "err":
            self._closed = True
            raise payload
        if payload is None:
            self._closed = True
            raise StopIteration
        if self._metrics is not None:
            self._metrics.counter("ingest_stall_ms").inc(
                round(stall_s * 1e3, 3))
            self._metrics.counter("ingest_overlap_ms").inc(
                round(max(0.0, decode_s - stall_s) * 1e3, 3))
        return self._inner._to_device(payload)

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the worker AND join it with a bounded timeout
        (early-exit consumers: external LIMIT). Setting the stop event
        alone left the thread parked up to one put-poll interval — and
        a bug there would strand it invisibly; joining makes "no
        daemon thread outlives its query" an enforced contract (the
        lockwatch stress test asserts it). The queue is drained first
        so a worker blocked mid-put unblocks immediately instead of
        riding out its 0.1s poll."""
        self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            import queue as _queue
            try:
                while True:
                    self._queue.get_nowait()
            except _queue.Empty:
                pass
            t.join(timeout_s)
            if t.is_alive():
                import warnings
                warnings.warn(
                    f"ingest-prefetch worker failed to exit within "
                    f"{timeout_s}s of close()")
        self._thread = None


# ---------------------------------------------------------------------------
# File stream source helpers (the FileStreamSource half that belongs to
# the IO layer: directory listing + per-file decode; the offset/seen-log
# machinery lives with the micro-batch loop in streaming.py)
# ---------------------------------------------------------------------------


def list_stream_files(path: str) -> list:
    """Data files under `path` ordered by (mtime_ns, name) — the
    FileStreamSource discovery order (the reference sorts its seen-map
    candidates by modification time too, `FileStreamSource.scala`).
    Hidden files, `_`-prefixed metadata (the sink's `_metadata/`
    manifest dir, `_SUCCESS` markers) and `.tmp`/`.crc` in-flight
    names are not data."""
    entries = []
    try:
        names = os.listdir(path)
    except OSError:
        return entries
    for name in names:
        if name.startswith((".", "_")) or \
                name.endswith((".tmp", ".crc")):
            continue
        full = os.path.join(path, name)
        try:
            st = os.stat(full)
        except OSError:
            continue  # vanished between listdir and stat
        if not os.path.isfile(full):
            continue
        entries.append({"name": name, "mtime_ns": int(st.st_mtime_ns),
                        "size": int(st.st_size)})
    entries.sort(key=lambda e: (e["mtime_ns"], e["name"]))
    return entries


def decode_stream_file(path: str, fmt: str) -> pa.Table:
    """One stream file -> Arrow table via the native readers. Raises on
    any decode failure (torn/partial writes, wrong format) — the
    caller quarantines or fails per
    spark_tpu.streaming.source.file.strict."""
    if fmt == "parquet":
        import pyarrow.parquet as pq
        return pq.read_table(path)
    if fmt == "csv":
        import pyarrow.csv as pa_csv
        return pa_csv.read_csv(path)
    if fmt == "json":
        import pyarrow.json as pa_json
        return pa_json.read_json(path)
    raise ValueError(f"unsupported stream file format {fmt!r} "
                     f"(parquet, csv, json)")


def maybe_prefetch(chunks, conf, recovery=None):
    """Wrap a chunk stream in the double-buffered prefetcher when
    ``spark_tpu.sql.ingest.prefetch`` is on. The one entry point every
    chunk driver (streaming_agg direct/spill/mesh, external collect)
    routes its `load_chunks` result through — results are identical
    on/off, only ingest/compute overlap changes."""
    if not isinstance(chunks, ChunkIterator):
        return chunks
    if not bool(conf.get(INGEST_PREFETCH_KEY)):
        return chunks
    metrics = getattr(recovery, "metrics", None)
    return PrefetchChunkIterator(chunks, conf, recovery=recovery,
                                 metrics=metrics)

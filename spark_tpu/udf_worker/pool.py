"""Reusable CPython subprocess worker pool for the out-of-process UDF
lane.

The `PythonWorkerFactory` seat: workers are spawned once (`sys.executable
worker.py`, PING/PONG handshake timed into `udf_worker_spawn_ms`), kept
idle between batches AND between queries (reuse amortizes the ~100ms
interpreter start the way the reference's daemon-forked workers do),
bounded by `spark_tpu.sql.udf.pool.maxWorkers`, and reaped after
`udf.pool.idleTimeoutMs` without a checkout.

Concurrency contract (analysis/concurrency/registry.py): `_cv` is the
single pool lock ("udf.pool", rank 59) guarding `_idle`/`_live`/`_all`.
Rank 59 sits ABOVE faults.plan (56) and lifecycle-adjacent locks, so
NOTHING that can fire a chaos seam or a cancellation checkpoint runs
while `_cv` is held: `lifecycle.checkpoint` and `faults.fire` happen
outside the lock, spawns happen outside the lock (a 100ms interpreter
start must not serialize unrelated checkouts), kills happen outside the
lock. A checked-out `WorkerHandle` is thread-confined to its query
thread (ConfinedDecl) — only the hand-off back into `_idle` is locked.

Failure surface: a worker that dies mid-batch (SIGKILL, segfault in
user code, OOM-killer) raises `UdfWorkerLost` whose message carries the
UNAVAILABLE token, so the failure taxonomy classifies it TRANSIENT and
ChunkRetrier replays exactly the in-flight batch on a fresh worker. A
worker that exceeds `udf.batchTimeoutMs` raises StageTimeoutError
(TIMEOUT — same replay path). A worker that died BETWEEN queries is
reaped lazily at checkout (`poll()` before reuse), so the next query's
first batch gets a live worker instead of a stale-pipe
BrokenPipeError.
"""

from __future__ import annotations

import os
import select
import subprocess
import sys
import threading
import time
from typing import List, Optional, Tuple

from ..execution import lifecycle
from ..execution.failures import StageTimeoutError
from ..testing import faults
from . import protocol

#: PING->PONG handshake budget for a fresh interpreter (generous: the
#: child imports numpy/pandas/pyarrow before it can answer)
SPAWN_TIMEOUT_S = 30.0

_WORKER_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "worker.py")


class UdfWorkerLost(RuntimeError):
    """The worker process died mid-batch (pipe EOF / broken pipe). The
    UNAVAILABLE token classifies this TRANSIENT (execution/failures.py)
    so ChunkRetrier replays the in-flight batch on a fresh worker."""

    def __init__(self, pid: int, detail: str):
        super().__init__(
            f"UNAVAILABLE: python udf worker pid {pid} died mid-batch "
            f"({detail})")
        self.pid = pid


class WorkerHandle:
    """One live worker subprocess, checked out to a single query thread
    at a time (thread-confined; hand-off under the pool cv). All reads
    go through `os.read` on the raw stdout fd with `select` timeouts —
    never the BufferedReader — so a poll/deadline can interrupt a read
    without leaving bytes stranded in a Python-side buffer."""

    def __init__(self, proc: subprocess.Popen, spawn_ms: float):
        self.proc = proc
        self.pid = proc.pid
        self.spawn_ms = spawn_ms
        self.last_used = time.monotonic()
        self._rbuf = bytearray()

    # -- timed framed I/O ---------------------------------------------------

    def _read_exact(self, n: int, deadline: Optional[float], poll) -> bytes:
        fd = self.proc.stdout.fileno()
        while len(self._rbuf) < n:
            if poll is not None:
                poll()
            slice_s = 0.05
            if deadline is not None:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise _BatchTimeout()
                slice_s = min(slice_s, max(rem, 1e-3))
            ready, _, _ = select.select([fd], [], [], slice_s)
            if not ready:
                continue
            chunk = os.read(fd, 1 << 20)
            if not chunk:
                raise UdfWorkerLost(
                    self.pid, f"pipe closed, exit {self.proc.poll()}")
            self._rbuf += chunk
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    def _read_frame(self, deadline: Optional[float],
                    poll) -> Tuple[bytes, bytes]:
        header = self._read_exact(protocol._HEADER.size, deadline, poll)
        ftype, length = protocol._HEADER.unpack(header)
        if length > protocol.MAX_FRAME_BYTES:
            raise protocol.ProtocolError(
                f"frame length {length} exceeds bound")
        payload = (self._read_exact(length, deadline, poll)
                   if length else b"")
        return ftype, payload

    def _write_frame(self, ftype: bytes, payload: bytes) -> None:
        try:
            protocol.write_frame(self.proc.stdin, ftype, payload)
        except (BrokenPipeError, OSError):
            raise UdfWorkerLost(
                self.pid, f"broken stdin pipe, exit {self.proc.poll()}")

    def handshake(self, timeout_s: float = SPAWN_TIMEOUT_S) -> None:
        self._write_frame(protocol.FRAME_PING, b"")
        deadline = time.monotonic() + timeout_s
        try:
            ftype, _ = self._read_frame(deadline, None)
        except _BatchTimeout:
            raise UdfWorkerLost(
                self.pid, f"no PONG within {timeout_s:g}s of spawn")
        if ftype != protocol.FRAME_PONG:
            raise protocol.ProtocolError(
                f"worker pid {self.pid} answered handshake with "
                f"{ftype!r}, expected PONG")

    def eval(self, payload: bytes, timeout_s: Optional[float] = None,
             poll=None) -> Tuple[bytes, bytes]:
        """One EVAL round-trip. `poll` (if given) runs every ~50ms while
        waiting — the lane passes the cancellation check, so a
        cancel/deadline raises out of here mid-batch instead of waiting
        the worker out. Raises UdfWorkerLost (worker died) or
        StageTimeoutError (batch exceeded udf.batchTimeoutMs)."""
        self._write_frame(protocol.FRAME_EVAL, payload)
        deadline = (time.monotonic() + timeout_s
                    if timeout_s and timeout_s > 0 else None)
        try:
            return self._read_frame(deadline, poll)
        except _BatchTimeout:
            raise StageTimeoutError(
                f"python udf worker pid {self.pid} exceeded "
                f"udf.batchTimeoutMs={timeout_s * 1e3:g} on one batch")

    # -- lifecycle ----------------------------------------------------------

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """Hard-kill and reap (never leaves a zombie: wait() always
        follows the kill)."""
        try:
            if self.proc.poll() is None:
                self.proc.kill()
        except OSError:
            pass
        try:
            self.proc.wait(timeout=10)
        except Exception:
            pass
        for s in (self.proc.stdin, self.proc.stdout):
            try:
                if s is not None:
                    s.close()
            except OSError:
                pass


class _BatchTimeout(Exception):
    """Internal deadline marker; translated to StageTimeoutError (eval)
    or UdfWorkerLost (handshake) at the call boundary."""


class UdfWorkerPool:
    """Bounded pool of reusable UDF workers, shared across the queries
    of one session (worker reuse across queries is the point: spawn
    cost is paid once, not per query)."""

    def __init__(self, max_workers: int, idle_timeout_ms: float = 0.0,
                 metrics=None):
        self.max_workers = max(1, int(max_workers))
        self.idle_timeout_ms = float(idle_timeout_ms)
        self._metrics = metrics
        #: THE pool lock ("udf.pool", rank 59): guards _idle/_live/_all
        self._cv = threading.Condition()
        self._idle: List[WorkerHandle] = []
        #: workers alive or reserved (idle + checked out + mid-spawn)
        self._live = 0
        #: every Popen ever spawned — the leak-check test surface:
        #: after cancel/shutdown, all entries must have poll() != None
        self._all: List[subprocess.Popen] = []

    # -- checkout / checkin -------------------------------------------------

    def checkout(self, timeout_s: Optional[float] = None) -> WorkerHandle:
        """Take an idle worker, or spawn one under the maxWorkers bound,
        or wait for a checkin. The wait is a cooperative boundary:
        `lifecycle.checkpoint` runs outside the lock each iteration, so
        cancel/deadline land within ~one poll slice."""
        t0 = time.monotonic()
        while True:
            lifecycle.checkpoint("udf_pool_wait")
            handle = None
            reserved = False
            to_kill: List[WorkerHandle] = []
            with self._cv:
                self._reap_locked(to_kill)
                if self._idle:
                    handle = self._idle.pop()
                elif self._live < self.max_workers:
                    self._live += 1
                    reserved = True
                else:
                    self._cv.wait(lifecycle.wait_slice(0.25, 0.05) or 0.05)
            for h in to_kill:
                h.kill()
            if handle is not None:
                return handle
            if reserved:
                try:
                    return self._spawn()
                except BaseException:
                    with self._cv:
                        self._live -= 1
                        self._cv.notify_all()
                    raise
            if (timeout_s is not None
                    and time.monotonic() - t0 > timeout_s):
                raise RuntimeError(
                    f"udf worker pool checkout timed out after "
                    f"{timeout_s:g}s (maxWorkers={self.max_workers} all "
                    f"busy)")

    def checkin(self, handle: WorkerHandle) -> None:
        """Return a LIVE worker for reuse (a dead/killed one goes
        through `discard`)."""
        handle.last_used = time.monotonic()
        with self._cv:
            self._idle.append(handle)
            self._cv.notify()

    def discard(self, handle: WorkerHandle) -> None:
        """Drop a checked-out worker (died mid-batch, timed out, or
        cancelled): kill outside the lock, then release its slot."""
        handle.kill()
        with self._cv:
            self._live -= 1
            self._cv.notify()

    def _reap_locked(self, to_kill: List[WorkerHandle]) -> None:
        """Under `_cv`: drop idle workers that died between queries
        (the stale-pipe bugfix — poll() before reuse, so a checkout
        never hands out a corpse) and queue idle-expired ones for an
        outside-the-lock kill."""
        now = time.monotonic()
        keep = []
        for h in self._idle:
            if not h.alive():
                self._live -= 1
                h.proc.poll()  # already dead; poll() reaps the zombie
            elif (self.idle_timeout_ms > 0
                  and (now - h.last_used) * 1e3 > self.idle_timeout_ms):
                self._live -= 1
                to_kill.append(h)
            else:
                keep.append(h)
        self._idle = keep

    # -- spawn --------------------------------------------------------------

    def _spawn(self) -> WorkerHandle:
        """Spawn + handshake one worker, OUTSIDE the pool lock. The
        `udf_worker_spawn` chaos seam fires before the exec so spawn
        failures ride the normal batch-replay path."""
        faults.fire("udf_worker_spawn")
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            [sys.executable, _WORKER_PATH],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE)
        handle = WorkerHandle(proc, 0.0)
        try:
            handle.handshake()
        except BaseException:
            handle.kill()
            raise
        handle.spawn_ms = (time.perf_counter() - t0) * 1e3
        if self._metrics is not None:
            self._metrics.counter("udf_worker_spawn_ms").inc(
                int(handle.spawn_ms))
        with self._cv:
            self._all.append(proc)
        return handle

    # -- shutdown / test surface --------------------------------------------

    def shutdown(self) -> None:
        """Kill every idle worker and reap it. Checked-out workers are
        their query thread's to kill (the cancel path kills the
        in-flight handle first, then calls this) — after both, every
        proc in `child_procs()` is dead."""
        with self._cv:
            victims = self._idle
            self._idle = []
            self._live -= len(victims)
            self._cv.notify_all()
        for h in victims:
            h.kill()

    def child_procs(self) -> List[subprocess.Popen]:
        """Every Popen this pool ever spawned (the no-orphan test
        surface: after cancel + shutdown, all must have exited)."""
        with self._cv:
            return list(self._all)

    def idle_count(self) -> int:
        with self._cv:
            return len(self._idle)

    def live_count(self) -> int:
        with self._cv:
            return self._live

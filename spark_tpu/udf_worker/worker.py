"""UDF worker child: the out-of-process `pyspark/worker.py:504` loop.

Spawned as ``sys.executable <this file>`` by the pool (never ``-m``:
the child must NOT import spark_tpu — the package __init__ pulls jax
and the TPU runtime is single-client, so a child touching the device
would wedge the parent). protocol.py is loaded by file path for the
same reason; the only imports are stdlib + numpy/pandas/pyarrow +
cloudpickle.

Loop: read one frame from stdin; PING answers PONG (the spawn
handshake the pool times); EVAL deserializes the Arrow batch, applies
the user function (scalar row loop, vectorized pandas, or grouped-map
— NULL semantics exactly matching the in-process lane in
spark_tpu/udf.py), and streams the typed result columns back as a
RESULT frame. A raising user function answers an ERROR frame carrying
the USER traceback captured here — the parent re-raises it as the
structured UDF_ERROR, so the client sees the line in their lambda,
not the pool's framing stack. EOF on stdin exits cleanly (idle reap).
"""

from __future__ import annotations

import importlib.util
import os
import sys
import traceback

import numpy as np
import pandas as pd
import pyarrow as pa


def _load_protocol():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "protocol.py")
    spec = importlib.util.spec_from_file_location("udf_worker_protocol",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


#: scalar return-type name -> numpy dtype (the worker-side mirror of
#: udf.py result_to_arrow's mapping; type NAMES cross the pipe, never
#: spark_tpu type objects)
_NP_TYPES = {"long": np.int64, "int": np.int32, "double": np.float64,
             "float": np.float32, "boolean": np.bool_}

_PA_TYPES = {np.dtype(np.int64): pa.int64(),
             np.dtype(np.int32): pa.int32(),
             np.dtype(np.float64): pa.float64(),
             np.dtype(np.float32): pa.float32(),
             np.dtype(np.bool_): pa.bool_()}


def _column_to_args(col: pa.ChunkedArray):
    """Arrow column -> (host array, validity|None), reconstructing the
    exact representation the in-process lane's _vec_to_host produces:
    object arrays for string/date/timestamp/decimal, typed numpy for
    the rest, validity split out — so both lanes run the user function
    over identical values and stay byte-parity."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    valid = None
    if arr.null_count:
        valid = ~np.asarray(arr.is_null())
    t = arr.type
    if (pa.types.is_string(t) or pa.types.is_large_string(t)
            or pa.types.is_date(t) or pa.types.is_timestamp(t)
            or pa.types.is_decimal(t) or pa.types.is_dictionary(t)
            or pa.types.is_null(t)):
        data = np.array(arr.to_pylist(), dtype=object)
    elif pa.types.is_boolean(t):
        data = np.asarray(arr.fill_null(False) if arr.null_count else arr)
    elif pa.types.is_floating(t):
        data = (arr.fill_null(0.0) if arr.null_count else arr).to_numpy(
            zero_copy_only=False)
    else:
        data = (arr.fill_null(0) if arr.null_count else arr).to_numpy(
            zero_copy_only=False)
    return data, valid


def _evaluate(fn, vectorized: bool, name: str, arg_arrays, arg_valids,
              n_rows: int):
    """The spark_tpu.udf.evaluate_udf loop, verbatim semantics: scalar
    UDFs get Python None for NULLs and may return None; pandas UDFs
    get Series with the invalid slots masked."""
    if vectorized:
        series = []
        for a, v in zip(arg_arrays, arg_valids):
            s = pd.Series(a)
            if v is not None:
                s = s.where(pd.Series(v))
            series.append(s)
        out = fn(*series)
        if not isinstance(out, pd.Series):
            out = pd.Series(out)
        if len(out) != n_rows:
            raise RuntimeError(
                f"pandas UDF {name!r} returned {len(out)} rows "
                f"for {n_rows} input rows")
        valid = ~out.isna().to_numpy()
        return out, valid
    results = []
    valid = np.ones(n_rows, dtype=bool)
    for i in range(n_rows):
        args = []
        for a, v in zip(arg_arrays, arg_valids):
            if v is not None and not v[i]:
                args.append(None)
            else:
                x = a[i]
                args.append(x.item() if isinstance(x, np.generic) else x)
        r = fn(*args)
        if r is None:
            valid[i] = False
            results.append(None)
        else:
            results.append(r)
    return results, valid


def _result_array(rt_name: str, values, valid) -> pa.Array:
    """spark_tpu.udf.result_to_arrow, keyed by type name."""
    if isinstance(values, pd.Series):
        values = values.to_numpy(dtype=object, na_value=None)
    cleaned = [None if not v else x for x, v in zip(values, valid)]
    if rt_name == "string":
        return pa.array([None if c is None else str(c) for c in cleaned],
                        type=pa.string())
    if rt_name == "date":
        return pa.array(cleaned, type=pa.date32())
    return pa.array(cleaned, type=_PA_TYPES[np.dtype(_NP_TYPES[rt_name])])


def _eval_batch(spec: dict, table: pa.Table) -> pa.Table:
    import cloudpickle
    n = table.num_rows
    cols, names = [], []
    for i, u in enumerate(spec["udfs"]):
        fn = cloudpickle.loads(u["fn"])
        arg_arrays, arg_valids = [], []
        for j in range(u["n_args"]):
            data, valid = _column_to_args(table.column(f"u{i}_a{j}"))
            arg_arrays.append(data)
            arg_valids.append(valid)
        values, valid = _evaluate(fn, u["vectorized"], u["name"],
                                  arg_arrays, arg_valids, n)
        cols.append(_result_array(u["rt"], values, valid))
        names.append(f"__udf_{spec['base'] + i}")
    return pa.table(cols, names=names)


def _eval_grouped(spec: dict, table: pa.Table) -> pa.Table:
    import cloudpickle
    fn = cloudpickle.loads(spec["fn"])
    out = fn(table.to_pandas().reset_index(drop=True))
    if not isinstance(out, pd.DataFrame):
        raise RuntimeError(
            f"grouped-map function returned {type(out).__name__}, "
            f"expected a pandas DataFrame")
    out = out[list(spec["fields"])]
    return pa.Table.from_pandas(out, preserve_index=False)


def main() -> int:
    proto = _load_protocol()
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    # anything user code prints must not corrupt the frame stream:
    # repoint fd 1 at stderr, keep the REAL stdout pipe privately
    stdout_fd = os.dup(sys.stdout.fileno())
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    out = os.fdopen(stdout_fd, "wb")
    while True:
        try:
            ftype, payload = proto.read_frame(stdin)
        except EOFError:
            return 0  # parent closed stdin: clean idle-reap exit
        if ftype == proto.FRAME_PING:
            proto.write_frame(out, proto.FRAME_PONG, b"")
            continue
        if ftype != proto.FRAME_EVAL:
            proto.write_frame(out, proto.FRAME_ERROR, proto.encode_error(
                RuntimeError(f"unexpected frame {ftype!r}"), ""))
            continue
        try:
            spec, table = proto.decode_eval(payload)
            if spec.get("kind") == "grouped_map":
                result = _eval_grouped(spec, table)
            else:
                result = _eval_batch(spec, table)
            proto.write_frame(out, proto.FRAME_RESULT,
                              proto.table_to_ipc(result))
        except BaseException as e:  # noqa: BLE001 — shipped to parent
            proto.write_frame(out, proto.FRAME_ERROR,
                              proto.encode_error(e, traceback.format_exc()))


if __name__ == "__main__":
    sys.exit(main())

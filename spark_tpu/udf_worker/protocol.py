"""Length-framed Arrow IPC protocol between the engine and UDF workers.

The reference streams Arrow record batches to its Python workers over
sockets with a tiny control vocabulary around them
(`PythonRunner.scala:84` writes, `python/pyspark/worker.py:504` reads);
here the transport is the worker subprocess's stdin/stdout pipes and
the vocabulary is four typed frames:

    frame := type(1 byte) + length(4 bytes, big-endian) + payload

    PING  -> PONG   spawn handshake (parent times it: udf_worker_spawn_ms)
    EVAL  -> RESULT one batch: pickled spec + Arrow IPC stream in,
                    Arrow IPC stream of result columns back
    EVAL  -> ERROR  the user function raised: pickled {etype, message,
                    traceback} — the USER traceback, captured inside
                    the worker, not the pool's framing stack

IMPORT DISCIPLINE: this module is executed inside the worker child,
which must never import spark_tpu (the package __init__ pulls jax, and
the TPU runtime is single-client — a child grabbing the device would
wedge the parent). Only stdlib + pyarrow + cloudpickle here; worker.py
loads this file by path, not through the package.
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Tuple

import pyarrow as pa

FRAME_PING = b"P"
FRAME_PONG = b"O"
FRAME_EVAL = b"E"
FRAME_RESULT = b"R"
FRAME_ERROR = b"X"

#: sanity bound on one frame's payload (a corrupted length prefix must
#: not drive a multi-GB allocation): generous for real batches, which
#: are sliced by udf.arrow.maxRecordsPerBatch well below this
MAX_FRAME_BYTES = 1 << 31

_HEADER = struct.Struct(">cI")


class ProtocolError(RuntimeError):
    """Framing violation on the worker pipe (short read mid-frame,
    unknown frame type, oversized length prefix)."""


def write_frame(stream, ftype: bytes, payload: bytes) -> None:
    stream.write(_HEADER.pack(ftype, len(payload)))
    if payload:
        stream.write(payload)
    stream.flush()


def read_exact(stream, n: int) -> bytes:
    """Read exactly n bytes from a blocking stream; EOFError on a pipe
    closed mid-frame (the worker-died signal on the parent side)."""
    chunks = []
    got = 0
    while got < n:
        chunk = stream.read(n - got)
        if not chunk:
            raise EOFError(f"pipe closed after {got}/{n} frame bytes")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(stream) -> Tuple[bytes, bytes]:
    header = read_exact(stream, _HEADER.size)
    ftype, length = _HEADER.unpack(header)
    if ftype not in (FRAME_PING, FRAME_PONG, FRAME_EVAL, FRAME_RESULT,
                     FRAME_ERROR):
        raise ProtocolError(f"unknown frame type {ftype!r}")
    if length > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame length {length} exceeds bound")
    payload = read_exact(stream, length) if length else b""
    return ftype, payload


# ---------------------------------------------------------------------------
# Payload (de)serialization
# ---------------------------------------------------------------------------

def table_to_ipc(table: pa.Table) -> bytes:
    buf = io.BytesIO()
    with pa.ipc.new_stream(buf, table.schema) as w:
        w.write_table(table)
    return buf.getvalue()


def ipc_to_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return r.read_all()


def encode_eval(spec: dict, table: pa.Table) -> bytes:
    """One EVAL payload: plain-pickled envelope; the user function
    inside `spec` is already a cloudpickle BLOB (bytes), so the
    envelope itself never needs cloudpickle to decode."""
    return pickle.dumps({"spec": spec, "arrow": table_to_ipc(table)},
                        protocol=pickle.HIGHEST_PROTOCOL)


def decode_eval(payload: bytes) -> Tuple[dict, pa.Table]:
    env = pickle.loads(payload)
    return env["spec"], ipc_to_table(env["arrow"])


def encode_error(exc: BaseException, tb_text: str) -> bytes:
    return pickle.dumps({"etype": type(exc).__name__,
                         "message": str(exc)[:2000],
                         "traceback": tb_text[:8000]},
                        protocol=pickle.HIGHEST_PROTOCOL)


def decode_error(payload: bytes) -> dict:
    return pickle.loads(payload)

"""Out-of-process Python UDF worker pool (the `ArrowEvalPythonExec` /
`PythonRunner.scala:84` seat): Arrow-batched evaluation of user Python
in reusable CPython subprocesses, with batch-granular retry, cancel,
and observability. See pool.py (parent side), worker.py (child loop),
protocol.py (framing). Selected by `spark_tpu.sql.udf.mode = worker`;
the default `inprocess` keeps the original single-process lane.

This package __init__ stays import-light: the SQL service imports
`UdfError` from here for its error mapping, and must not drag the pool
machinery (or pyarrow) in before it needs it.
"""

from __future__ import annotations


class UdfError(RuntimeError):
    """User code raised inside a UDF worker. Carries the USER traceback
    captured in the child (not the pool's framing stack), surfaces as
    the structured `UDF_ERROR` service code (HTTP 400-class: the query
    is at fault, not the engine), and classifies FATAL — a user bug
    never burns retry budget."""

    code = "UDF_ERROR"

    def __init__(self, udf_name: str, etype: str, message: str,
                 worker_traceback: str):
        super().__init__(
            f"python UDF {udf_name!r} raised {etype}: {message}")
        self.udf_name = udf_name
        self.etype = etype
        self.worker_traceback = worker_traceback
